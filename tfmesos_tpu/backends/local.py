"""Local subprocess backend: run a whole "cluster" on this host.

The reference has no equivalent — its only path to a running cluster is a
real Mesos master (SURVEY §4: the de-facto test was a live cluster).  This
backend exists precisely to fix that: it synthesizes offers describing the
local host and launches tasks as child processes, so the full control plane
(rendezvous, config broadcast, Mode A/B node runtime, failure policy) is
exercisable in CI with no Mesos and no TPU.
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence

from tfmesos_tpu.backends import ResourceBackend
from tfmesos_tpu.spec import Offer, TaskStatus
from tfmesos_tpu.utils.logging import get_logger


class LocalBackend(ResourceBackend):
    colocated = True

    def __init__(self, cpus: Optional[float] = None, mem: float = 1 << 20,
                 chips: int = 0, offer_interval: float = 0.05,
                 inherit_env: bool = True,
                 default_platform: Optional[str] = "cpu",
                 chaos=None):
        # Co-located processes cannot share one TPU, so local children run on
        # CPU unless the caller (or the environment) says otherwise.
        self.default_platform = default_platform
        # "cpus" here are scheduling slots, not a pinning claim: this backend
        # exists to run many-task dev clusters on small hosts, so advertise a
        # generous floor rather than the literal core count.
        self.cpus = float(cpus if cpus is not None else max(os.cpu_count() or 1, 16))
        self.mem = float(mem)
        self.chips = chips
        self.offer_interval = offer_interval
        self.inherit_env = inherit_env
        # Optional chaos.FaultPlan: launched pids register with it (so
        # kill_task faults can SIGKILL by job:index name) and drop_agent
        # faults execute through chaos_drop_agent below.
        self.chaos = chaos
        self.log = get_logger("tfmesos_tpu.local")

        self._scheduler = None
        self._suppressed = threading.Event()
        self._shutdown = threading.Event()
        self._offer_thread: Optional[threading.Thread] = None
        self._procs: Dict[str, subprocess.Popen] = {}
        self._in_use = [0.0, 0.0, 0]  # cpus, mem, chips
        self._lock = threading.Lock()

    # -- ResourceBackend ---------------------------------------------------

    def start(self, scheduler) -> None:
        self._scheduler = scheduler
        if self.chaos is not None:
            self.chaos.bind_backend(self)
        scheduler.on_registered({"backend": "local", "cpus": self.cpus,
                                 "mem": self.mem, "chips": self.chips})
        self._offer_thread = threading.Thread(target=self._offer_loop,
                                              name="local-offers", daemon=True)
        self._offer_thread.start()

    def _offer_loop(self) -> None:
        while not self._shutdown.is_set():
            if not self._suppressed.is_set():
                with self._lock:
                    free = Offer(
                        id=str(uuid.uuid4()), agent_id="local",
                        hostname="127.0.0.1",
                        cpus=self.cpus - self._in_use[0],
                        mem=self.mem - self._in_use[1],
                        chips=self.chips - self._in_use[2],
                    )
                if free.cpus > 0 and free.mem > 0:
                    try:
                        self._scheduler.on_offers([free])
                    except Exception as e:  # pragma: no cover - defensive
                        self.log.exception("offer delivery failed: %s", e)
            self._shutdown.wait(self.offer_interval)

    def launch(self, offer: Offer, task_infos: Sequence[dict]) -> None:
        for info in task_infos:
            task_id = info["task_id"]["value"]
            env = dict(os.environ) if self.inherit_env else {}
            if self.default_platform:
                # Override the *inherited* platform pin (a site-installed TPU
                # plugin's env would make co-located processes fight over one
                # chip) — but before the task-env merge, so an explicit
                # JAX_PLATFORMS passed via the scheduler's env= still wins.
                env["JAX_PLATFORMS"] = self.default_platform
            for var in info["command"]["environment"]["variables"]:
                env[var["name"]] = var["value"]
            cmd = info["command"]["value"]
            argv = cmd if info["command"].get("shell") else shlex.split(cmd)
            res = info["resources"]
            used = [_res(res, "cpus"), _res(res, "mem"), int(_res(res, "tpus"))]
            with self._lock:
                for i in range(3):
                    self._in_use[i] += used[i]
            try:
                proc = subprocess.Popen(argv,
                                        shell=bool(info["command"].get("shell")),
                                        env=env, start_new_session=True)
            except OSError as e:
                # A spawn failure (bad interpreter, ENOENT, EMFILE...) must
                # feed the failure policy, not vanish into a log line with
                # the task stuck offered=True until start_timeout.
                with self._lock:
                    for i in range(3):
                        self._in_use[i] -= used[i]
                self.log.warning("local launch of %s failed: %s",
                                 task_id[:8], e)
                self._scheduler.on_status(TaskStatus(
                    task_id, "TASK_DROPPED", message=f"launch failed: {e}",
                    agent_id="local"))
                continue
            self._procs[task_id] = proc
            self.log.info("launched local task %s pid=%d", task_id[:8], proc.pid)
            if self.chaos is not None:
                self.chaos.observe_launch(info.get("name", task_id),
                                          task_id, proc.pid)
            self._scheduler.on_status(TaskStatus(task_id, "TASK_RUNNING",
                                                 agent_id="local"))
            threading.Thread(target=self._watch, args=(task_id, proc, used),
                             name=f"watch-{task_id[:8]}", daemon=True).start()

    def _watch(self, task_id: str, proc: subprocess.Popen, used) -> None:
        rc = proc.wait()
        with self._lock:
            for i in range(3):
                self._in_use[i] -= used[i]
        if self._shutdown.is_set():
            return
        state = "TASK_FINISHED" if rc == 0 else "TASK_FAILED"
        self._scheduler.on_status(
            TaskStatus(task_id, state, message=f"exit code {rc}", agent_id="local"))

    def decline(self, offer: Offer, refuse_seconds: float = 5.0) -> None:
        pass  # synthetic offers; nothing to return

    def suppress(self) -> None:
        self._suppressed.set()

    def revive(self) -> None:
        self._suppressed.clear()

    def kill(self, task_id: str) -> None:
        proc = self._procs.get(task_id)
        if proc is not None and proc.poll() is None:
            _terminate(proc)

    def chaos_drop_agent(self) -> None:
        """Fault-injection entry (chaos.FaultPlan 'drop_agent'): the whole
        agent vanishes — every task process SIGKILLed at once, then the
        agent-lost callback, exactly the order a real host loss presents."""
        for proc in list(self._procs.values()):
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        self._scheduler.on_agent_lost("local")

    def stop(self) -> None:
        self._shutdown.set()
        for proc in self._procs.values():
            if proc.poll() is None:
                _terminate(proc)
        deadline = time.monotonic() + 5.0
        for proc in self._procs.values():
            remaining = deadline - time.monotonic()
            try:
                proc.wait(timeout=max(0.0, remaining))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        if self._offer_thread is not None:
            self._offer_thread.join(timeout=2.0)


def _terminate(proc: subprocess.Popen) -> None:
    # Tasks are session leaders (start_new_session=True) so Mode B shell
    # children die with them.
    try:
        os.killpg(proc.pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        try:
            proc.terminate()
        except ProcessLookupError:
            pass


def _res(resources: List[dict], name: str) -> float:
    for r in resources:
        if r["name"] == name:
            return float(r["scalar"]["value"])
    return 0.0
