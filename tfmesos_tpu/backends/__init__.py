"""Resource backends.

The reference binds its scheduler directly to pymesos' callback surface
(scheduler.py:180, 223-277).  We invert that: ``TPUMesosScheduler`` owns the
cluster logic and talks to a narrow ``ResourceBackend`` interface, with two
implementations — ``LocalBackend`` (subprocess fan-out, for development and
tests, no Mesos needed) and ``MesosBackend`` (Mesos v1 HTTP scheduler API,
speaking JSON/RecordIO directly with no pymesos dependency).
"""

from __future__ import annotations

import abc
from typing import List, Sequence

from tfmesos_tpu.spec import Offer

FOREVER = 0xFFFFFFFF  # reference: scheduler.py:17


class ResourceBackend(abc.ABC):
    """Delivers offers/status to the scheduler and executes its decisions.

    A backend pushes events by calling the scheduler's callback surface
    (``on_registered`` / ``on_offers`` / ``on_status`` / ``on_agent_lost`` /
    ``on_error``) from its own thread; the scheduler serializes state behind
    its own lock.
    """

    #: True when launched tasks share the scheduler's filesystem (so secrets
    #: can travel as mode-0600 files instead of state-visible env vars).
    colocated = False

    @abc.abstractmethod
    def start(self, scheduler) -> None:
        """Connect and begin delivering events."""

    @abc.abstractmethod
    def stop(self) -> None:
        """Tear down; kill anything still running that we launched."""

    @abc.abstractmethod
    def launch(self, offer: Offer, task_infos: Sequence[dict]) -> None:
        """Launch tasks against an offer (reference: driver.launchTasks,
        scheduler.py:277)."""

    @abc.abstractmethod
    def decline(self, offer: Offer, refuse_seconds: float = 5.0) -> None:
        """Return an offer unused (reference: scheduler.py:230-232)."""

    @abc.abstractmethod
    def suppress(self) -> None:
        """Stop receiving offers once fully placed (reference: scheduler.py:229)."""

    @abc.abstractmethod
    def revive(self) -> None:
        """Resume receiving offers after a task revive (reference:
        scheduler.py:430)."""

    @abc.abstractmethod
    def kill(self, task_id: str) -> None:
        """Kill one task by id."""

    def acknowledge(self, status) -> None:  # only meaningful for Mesos
        pass


def first_fit(tasks, offer: Offer) -> List:
    """First-fit packing of unoffered tasks into one offer — the reference's
    allocation strategy (scheduler.py:252-275).  Mutates ``offer``'s free
    resources and returns the tasks placed."""
    placed = []
    for task in tasks:
        if task.offered:
            continue
        if task.fits(offer):
            task.take_from(offer)
            task.offered = True
            task.offer_id = offer.id
            task.agent_id = offer.agent_id
            task.hostname = offer.hostname
            placed.append(task)
    return placed
