"""Mesos backend: the v1 HTTP scheduler API, spoken directly.

The reference rides pymesos (setup.py:51) for its Mesos session; we
implement the protocol ourselves on the stdlib — a long-lived SUBSCRIBE
stream of RecordIO-framed JSON events plus one-shot POST calls — so the
framework has zero dependencies beyond JAX.  Protocol shape:

* ``POST /api/v1/scheduler`` with a SUBSCRIBE call opens a chunked response
  carrying ``<length>\\n<json>`` records (SUBSCRIBED, OFFERS, UPDATE,
  FAILURE, ERROR, HEARTBEAT ...) and a ``Mesos-Stream-Id`` header.
* Every subsequent call (ACCEPT/DECLINE/ACKNOWLEDGE/REVIVE/SUPPRESS/KILL/
  TEARDOWN) is a separate POST carrying the framework id and stream id.

TPU-era resource mapping: tasks request the custom scalar resource ``tpus``
(chips on TPU-VM agents); ``gpus`` offers are also read into the same chips
dimension for parity with the reference's GPU accounting, including the
Mesos SET-type form (scheduler.py:244-250).

The reference's semantics are preserved: explicit status acknowledgements,
revive/suppress passthrough, decline with configurable refuse_seconds
(FOREVER once placed), teardown on stop (scheduler.py:459-472).
"""

from __future__ import annotations

import getpass
import http.client
import json
import socket
import threading
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Sequence

from tfmesos_tpu.backends import ResourceBackend
from tfmesos_tpu.spec import Offer, TaskStatus
from tfmesos_tpu.utils.logging import get_logger

API_PATH = "/api/v1/scheduler"


class RecordIOParser:
    """Incremental ``<length>\\n<bytes>`` record parser."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf.extend(data)
        out = []
        while True:
            nl = self._buf.find(b"\n")
            if nl < 0:
                break
            try:
                length = int(bytes(self._buf[:nl]))
            except ValueError:
                raise IOError(f"bad RecordIO length {bytes(self._buf[:nl])!r}")
            end = nl + 1 + length
            if len(self._buf) < end:
                break
            out.append(bytes(self._buf[nl + 1:end]))
            del self._buf[:end]
        return out


def parse_master(master: str) -> tuple:
    """Accept ``host:port``, ``http://host:port``, or ``zk://.../mesos``
    (resolved to the leading master through the minimal ZooKeeper client in
    backends/zk.py — the reference gets the same capability transitively via
    pymesos, SURVEY §1)."""
    if master.startswith("zk://"):
        from tfmesos_tpu.backends.zk import resolve_master
        master = resolve_master(master)
    if "//" in master:
        parsed = urllib.parse.urlparse(master)
        return parsed.hostname, parsed.port or 5050
    host, _, port = master.partition(":")
    return host, int(port or 5050)


def parse_offer(raw: dict) -> Offer:
    """Read cpus/mem plus accelerator chips.

    Chips come from the ``tpus`` custom scalar resource, or — so a plain GPU
    cluster still schedules — from a SCALAR ``gpus`` resource; either way the
    offer records WHICH name supplied them (``chips_resource``) and the
    TaskInfo requests chips under that same name, so launch cannot ask for a
    resource the agent never advertised.  SET-type ``gpus`` (the reference's
    nvidia-docker-v1 uuid lists, scheduler.py:244-250) have no valid scalar
    request shape and no TPU analogue: they are ignored, not matched.
    """
    cpus = mem = 0.0
    tpus = gpus = 0
    for res in raw.get("resources", []):
        name, rtype = res.get("name"), res.get("type")
        if name == "cpus" and rtype == "SCALAR":
            cpus = float(res["scalar"]["value"])
        elif name == "mem" and rtype == "SCALAR":
            mem = float(res["scalar"]["value"])
        elif name == "tpus" and rtype == "SCALAR":
            tpus += int(float(res["scalar"]["value"]))
        elif name == "gpus" and rtype == "SCALAR":
            gpus += int(float(res["scalar"]["value"]))
    attributes = {}
    for attr in raw.get("attributes", []):
        if attr.get("type") == "TEXT":
            attributes[attr["name"]] = attr["text"]["value"]
        elif attr.get("type") == "SCALAR":
            attributes[attr["name"]] = str(attr["scalar"]["value"])
    chips, chips_resource = (tpus, "tpus") if tpus or not gpus else (gpus,
                                                                     "gpus")
    return Offer(id=raw["id"]["value"], agent_id=raw["agent_id"]["value"],
                 hostname=raw.get("hostname", ""), cpus=cpus, mem=mem,
                 chips=chips, chips_resource=chips_resource,
                 attributes=attributes, raw=raw)


class MesosBackend(ResourceBackend):
    def __init__(self, master: str, framework_name: str = "tpumesos",
                 role: str = "*", user: Optional[str] = None,
                 failover_timeout: float = 3600.0,
                 reconnect_wait: float = 2.0):
        self.host, self.port = parse_master(master)
        self.framework_name = framework_name
        self.role = role
        self.user = user if user is not None else getpass.getuser()
        self.failover_timeout = failover_timeout
        self.reconnect_wait = reconnect_wait
        self.log = get_logger("tfmesos_tpu.mesos")

        self._scheduler = None
        self.framework_id: Optional[str] = None
        self.stream_id: Optional[str] = None
        self._shutdown = threading.Event()
        self._subscribed = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conn: Optional[http.client.HTTPConnection] = None

    # -- subscribe stream --------------------------------------------------

    def start(self, scheduler) -> None:
        self._scheduler = scheduler
        self._thread = threading.Thread(target=self._subscribe_loop,
                                        name="mesos-subscribe", daemon=True)
        self._thread.start()
        if not self._subscribed.wait(timeout=60.0):
            self.stop()  # stop the reconnect loop; don't leak it behind the raise
            raise RuntimeError(
                f"could not subscribe to Mesos master at "
                f"{self.host}:{self.port} within 60s")

    def _subscribe_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                self._run_stream()
            except Exception as e:
                if self._shutdown.is_set():
                    return
                self.log.warning("subscribe stream broke: %s; reconnecting "
                                 "in %.1fs", e, self.reconnect_wait)
                time.sleep(self.reconnect_wait)

    def _run_stream(self) -> None:
        try:
            self._stream_once()
        finally:
            # We are the reader thread, so closing here cannot deadlock on
            # the response buffer lock (unlike closing from stop()).
            if self._conn is not None:
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None

    def _subscribe_body(self) -> Dict[str, Any]:
        """The v1 SUBSCRIBE call payload (golden-tested against the API
        shape in tests/test_mesos_golden.py)."""
        body: Dict[str, Any] = {
            "type": "SUBSCRIBE",
            "subscribe": {
                "framework_info": {
                    "user": self.user,
                    "name": self.framework_name,
                    "roles": [self.role],
                    "failover_timeout": self.failover_timeout,
                    "capabilities": [{"type": "MULTI_ROLE"}],
                },
            },
        }
        if self.framework_id:  # failover re-subscription keeps our tasks
            body["framework_id"] = {"value": self.framework_id}
            body["subscribe"]["framework_info"]["id"] = {
                "value": self.framework_id}
        return body

    def _accept_body(self, offer: Offer,
                     task_infos: Sequence[dict]) -> Dict[str, Any]:
        """The v1 ACCEPT call payload (golden-tested)."""
        return {
            "type": "ACCEPT",
            "accept": {
                "offer_ids": [{"value": offer.id}],
                "operations": [{
                    "type": "LAUNCH",
                    "launch": {"task_infos": list(task_infos)},
                }],
                "filters": {"refuse_seconds": 5.0},
            },
        }

    def _stream_once(self) -> None:
        body = self._subscribe_body()
        conn = http.client.HTTPConnection(self.host, self.port, timeout=60)
        self._conn = conn
        conn.request("POST", API_PATH, body=json.dumps(body),
                     headers={"Content-Type": "application/json",
                              "Accept": "application/json"})
        resp = conn.getresponse()
        if resp.status in (302, 307):  # not the leading master
            location = resp.getheader("Location", "")
            host, port = self._parse_redirect(location)
            if host:
                # Follow the leader: update our target and let the
                # reconnect loop re-subscribe there (reference parity: a
                # zk:// framework always lands on the leader).
                self.log.info("master redirected to %s:%d; following",
                              host, port)
                self.host, self.port = host, port
            raise IOError(f"master redirected to {location}")
        if resp.status != 200:
            raise IOError(f"SUBSCRIBE failed: HTTP {resp.status} "
                          f"{resp.read(200)!r}")
        self.stream_id = resp.getheader("Mesos-Stream-Id")
        parser = RecordIOParser()
        while not self._shutdown.is_set():
            chunk = resp.read1(65536)
            if not chunk:
                raise IOError("subscribe stream EOF")
            for record in parser.feed(chunk):
                self._dispatch(json.loads(record))

    @staticmethod
    def _parse_redirect(location: str):
        """``//host:port[/path]`` or a full URL -> (host, port)."""
        if not location:
            return None, None
        parsed = urllib.parse.urlparse(
            location if "//" in location else f"//{location}")
        return parsed.hostname, parsed.port or 5050

    def _master_version(self, sub: Dict[str, Any]) -> Optional[str]:
        """Master version from SUBSCRIBED metadata, else the /version
        endpoint (reference probes the version at registration to pick a
        containerizer, scheduler.py:378-382)."""
        version = sub.get("master_info", {}).get("version")
        if version:
            return version
        try:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=10)
            try:
                conn.request("GET", "/version")
                resp = conn.getresponse()
                if resp.status == 200:
                    return json.loads(resp.read(4096)).get("version")
            finally:
                conn.close()
        except Exception as e:  # pure metadata; never fail bring-up on it
            self.log.debug("/version probe failed: %s", e)
        return None

    def _dispatch(self, event: Dict[str, Any]) -> None:
        etype = event.get("type")
        if etype == "SUBSCRIBED":
            sub = event["subscribed"]
            self.framework_id = sub["framework_id"]["value"]
            self.log.info("subscribed: framework %s", self.framework_id)
            self._subscribed.set()
            self._scheduler.on_registered(
                {"backend": "mesos", "framework_id": self.framework_id,
                 "master": f"{self.host}:{self.port}",
                 "master_version": self._master_version(sub)})
        elif etype == "OFFERS":
            offers = [parse_offer(o)
                      for o in event["offers"].get("offers", [])]
            if offers:
                self._scheduler.on_offers(offers)
        elif etype == "UPDATE":
            status = event["update"]["status"]
            self._scheduler.on_status(TaskStatus(
                task_id=status["task_id"]["value"],
                state=status["state"],
                message=status.get("message", ""),
                agent_id=status.get("agent_id", {}).get("value", ""),
                uuid=status.get("uuid", ""),
            ))
        elif etype == "FAILURE":
            failure = event.get("failure", {})
            agent = failure.get("agent_id", {}).get("value")
            if agent and not failure.get("executor_id"):
                self._scheduler.on_agent_lost(agent)
        elif etype == "ERROR":
            self._scheduler.on_error(event.get("error", {}).get("message",
                                                                "unknown"))
        elif etype == "RESCIND":
            # An outstanding offer was withdrawn.  If tasks were placed on
            # it and their launch never confirmed, the scheduler synthesizes
            # terminal statuses so the two-phase policy revives them instead
            # of idling until start_timeout (the reference ignored rescinds,
            # scheduler.py: no offerRescinded handler — a stale-offer launch
            # on a busy cluster would hang its bring-up).
            offer_id = event.get("rescind", {}).get("offer_id", {}).get(
                "value")
            if offer_id:
                self._scheduler.on_rescind(offer_id)
        elif etype == "HEARTBEAT":
            # Liveness backstop: a failed/rejected REVIVE while the stream
            # stays healthy would otherwise leave the offer tap closed.
            self._scheduler.on_heartbeat()
        else:
            self.log.debug("ignoring event %s", etype)

    # -- calls -------------------------------------------------------------

    def _with_envelope(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """The call envelope every non-SUBSCRIBE POST carries (golden-
        tested: the goldens freeze exactly what goes on the wire)."""
        body = dict(body)
        if self.framework_id:
            body["framework_id"] = {"value": self.framework_id}
        return body

    def _call(self, body: Dict[str, Any]) -> int:
        """POST one scheduler call; returns the HTTP status (2xx = the
        master took it)."""
        body = self._with_envelope(body)
        headers = {"Content-Type": "application/json"}
        if self.stream_id:
            headers["Mesos-Stream-Id"] = self.stream_id
        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        try:
            conn.request("POST", API_PATH, body=json.dumps(body),
                         headers=headers)
            resp = conn.getresponse()
            data = resp.read(4096)
            if resp.status not in (200, 202):
                self.log.warning("call %s failed: HTTP %d %r",
                                 body.get("type"), resp.status, data[:200])
            return resp.status
        finally:
            conn.close()

    def launch(self, offer: Offer, task_infos: Sequence[dict]) -> None:
        # A rejected or unreachable ACCEPT must not leave the placed tasks
        # in offered=True limbo (they would idle until start_timeout):
        # synthesize a terminal status per task so on_status routes them
        # through the normal two-phase revive/abort policy.
        task_ids = [info["task_id"]["value"] for info in task_infos]
        try:
            status = self._call(self._accept_body(offer, task_infos))
        except Exception as e:
            self._drop_launch(task_ids, f"ACCEPT failed: {e}")
            return
        if status not in (200, 202):
            self._drop_launch(task_ids, f"ACCEPT rejected: HTTP {status}")

    def _drop_launch(self, task_ids: List[str], why: str) -> None:
        self.log.warning("launch of %d task(s) failed (%s); reporting "
                         "TASK_DROPPED", len(task_ids), why)
        for tid in task_ids:
            # The failure may be AMBIGUOUS (e.g. the ACCEPT was delivered
            # but its response timed out): the task might actually be
            # launching.  Kill the soon-to-be-stale id first — a no-op if
            # it never ran, and it stops a zombie from holding resources
            # if it did.  Guarded separately: a failed kill must not skip
            # the drop, and neither may strand the remaining tasks.
            try:
                self.kill(tid)
            except Exception as e:
                self.log.warning("kill of %s failed: %s", tid[:8], e)
            try:
                self._scheduler.on_status(TaskStatus(tid, "TASK_DROPPED",
                                                     message=why))
            except Exception as e:
                # on_status's follow-up REVIVE can hit the same unreachable
                # master; EVERY task must still get its drop (or the rest
                # stay in the offered=True limbo this path exists to clear).
                self.log.warning("drop of %s partially failed: %s",
                                 tid[:8], e)

    def decline(self, offer: Offer, refuse_seconds: float = 5.0) -> None:
        self._call({
            "type": "DECLINE",
            "decline": {"offer_ids": [{"value": offer.id}],
                        "filters": {"refuse_seconds": float(refuse_seconds)}},
        })

    def suppress(self) -> None:
        self._call({"type": "SUPPRESS"})

    def revive(self) -> None:
        # Raise on rejection: REVIVE is the liveness backstop's lever, and
        # the scheduler's heartbeat gating only retries failures it can
        # SEE (a silently-dropped 500 would close the offer tap for good).
        status = self._call({"type": "REVIVE"})
        if status not in (200, 202):
            raise RuntimeError(f"REVIVE rejected: HTTP {status}")

    def kill(self, task_id: str) -> None:
        self._call({"type": "KILL", "kill": {"task_id": {"value": task_id}}})

    def acknowledge(self, status: TaskStatus) -> None:
        # Explicit acks are required on the v1 API whenever a status carries
        # a uuid (the analogue of pymesos' implicit acks the reference used).
        if not status.uuid or not status.agent_id:
            return
        self._call({
            "type": "ACKNOWLEDGE",
            "acknowledge": {
                "agent_id": {"value": status.agent_id},
                "task_id": {"value": status.task_id},
                "uuid": status.uuid,
            },
        })

    def stop(self) -> None:
        self._shutdown.set()
        if self.framework_id:
            try:
                self._call({"type": "TEARDOWN"})
            except Exception as e:  # master may already be gone
                self.log.warning("teardown failed: %s", e)
        if self._conn is not None:
            # Wake the reader thread blocked in recv: a raw shutdown() on the
            # socket interrupts it immediately, whereas HTTPConnection.close()
            # would deadlock on the response buffer lock the reader holds
            # (until the socket timeout fires, 60s later).
            sock = getattr(self._conn, "sock", None)
            if sock is not None:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._conn is not None:
            try:
                self._conn.close()
            except Exception:
                pass
