"""Minimal ZooKeeper client: resolve a ``zk://`` Mesos master URL.

The reference accepts ``zk://host:port,.../mesos`` masters via pymesos'
ZooKeeper dependency (reference requirements.txt:11, scheduler.py:188).  We
need exactly one read path — find the leading master's advertised address —
so instead of a ZK client library this speaks the few jute-encoded frames
that path requires (connect, getChildren, getData) over a raw socket.

Mesos masters register ephemeral sequential znodes ``json.info_XXXXXXXXXX``
under the configured path; the lowest sequence number is the leader, and its
data is a JSON ``MasterInfo`` carrying ``address.ip``/``address.port``.
"""

from __future__ import annotations

import json
import socket
import struct
import urllib.parse
from typing import List, Tuple

from tfmesos_tpu.utils.logging import get_logger

log = get_logger("tfmesos_tpu.zk")

_GET_CHILDREN = 8
_GET_DATA = 4


def _buf(data: bytes) -> bytes:
    return struct.pack(">i", len(data)) + data


def _frame(payload: bytes) -> bytes:
    return struct.pack(">i", len(payload)) + payload


def _read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise IOError("ZooKeeper connection closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _read_frame(sock: socket.socket) -> bytes:
    (length,) = struct.unpack(">i", _read_exact(sock, 4))
    if length < 0 or length > 1 << 22:
        raise IOError(f"bad ZooKeeper frame length {length}")
    return _read_exact(sock, length)


def _connect(sock: socket.socket, timeout_ms: int = 10000) -> None:
    # ConnectRequest: protocolVersion, lastZxidSeen, timeOut, sessionId,
    # passwd buffer (+ trailing readOnly flag, accepted since ZK 3.4).
    req = (struct.pack(">iqiq", 0, 0, timeout_ms, 0)
           + _buf(b"\x00" * 16) + b"\x00")
    sock.sendall(_frame(req))
    resp = _read_frame(sock)
    if len(resp) < 16:
        raise IOError(f"short ZooKeeper connect response ({len(resp)}B)")
    # ConnectResponse: protocolVersion int32, timeOut int32, sessionId int64,
    # passwd buffer[, readOnly byte] — nothing we need beyond "it parsed".


def _request(sock: socket.socket, xid: int, op: int, payload: bytes) -> bytes:
    sock.sendall(_frame(struct.pack(">ii", xid, op) + payload))
    resp = _read_frame(sock)
    got_xid, _zxid, err = struct.unpack(">iqi", resp[:16])
    while got_xid != xid:
        # Skip unsolicited server frames (watch events use xid -1).
        resp = _read_frame(sock)
        got_xid, _zxid, err = struct.unpack(">iqi", resp[:16])
    if err != 0:
        raise IOError(f"ZooKeeper op {op} failed with error {err}")
    return resp[16:]


def _get_children(sock: socket.socket, path: str) -> List[str]:
    body = _request(sock, 1, _GET_CHILDREN, _buf(path.encode()) + b"\x00")
    (count,) = struct.unpack(">i", body[:4])
    out, off = [], 4
    for _ in range(count):
        (n,) = struct.unpack(">i", body[off:off + 4])
        off += 4
        out.append(body[off:off + n].decode())
        off += n
    return out


def _get_data(sock: socket.socket, path: str) -> bytes:
    body = _request(sock, 2, _GET_DATA, _buf(path.encode()) + b"\x00")
    (n,) = struct.unpack(">i", body[:4])
    return body[4:4 + n]


def parse_zk_url(url: str) -> Tuple[List[Tuple[str, int]], str]:
    """``zk://h1:2181,h2:2181/mesos`` -> ([(h1, 2181), (h2, 2181)], "/mesos").

    A ``user:pass@`` userinfo section (digest auth) is accepted and ignored —
    Mesos master znodes are world-readable.
    """
    parsed = urllib.parse.urlparse(url)
    if parsed.scheme != "zk":
        raise ValueError(f"not a zk:// URL: {url}")
    netloc = parsed.netloc.rsplit("@", 1)[-1]
    servers = []
    for part in netloc.split(","):
        host, _, port = part.partition(":")
        if host:
            servers.append((host, int(port or 2181)))
    if not servers or not parsed.path or parsed.path == "/":
        raise ValueError(f"zk:// URL needs servers and a path: {url}")
    return servers, parsed.path.rstrip("/")


def resolve_master(url: str, timeout: float = 10.0) -> str:
    """Resolve a ``zk://`` URL to the leading master's ``host:port``."""
    servers, path = parse_zk_url(url)
    last_err: Exception = IOError("no ZooKeeper servers in URL")
    for host, port in servers:
        try:
            with socket.create_connection((host, port),
                                          timeout=timeout) as sock:
                sock.settimeout(timeout)
                _connect(sock)
                children = [c for c in _get_children(sock, path)
                            if c.startswith("json.info_")]
                if not children:
                    raise IOError(f"no json.info_* master znodes under "
                                  f"{path} — is this a Mesos ensemble?")
                leader = min(children, key=lambda c: int(c.rsplit("_", 1)[1]))
                info = json.loads(_get_data(sock, f"{path}/{leader}"))
                addr = info.get("address", {})
                ip = addr.get("ip") or addr.get("hostname") or info.get(
                    "hostname")
                if not ip:
                    raise IOError(f"master znode {leader} carries no address")
                master = f"{ip}:{addr.get('port', 5050)}"
                log.info("zk: resolved %s -> leading master %s (%s)",
                         url, master, leader)
                return master
        except (OSError, IOError, ValueError, json.JSONDecodeError) as e:
            last_err = e
            log.warning("zk: %s:%d failed: %s", host, port, e)
    raise IOError(f"could not resolve {url}: {last_err}")
