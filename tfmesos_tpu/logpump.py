"""Child-stdout line pump with optional TCP forwarding.

The reference's hottest control-plane loop: every byte of worker output
transits a Python ``for l in iter(p.stdout.readline, b'')`` loop
(server.py:99-102).  We provide a native C++ pump (``native/logpump.cpp``,
loaded via ctypes) that splices child stdout → local stdout (+ forward
socket, with a ``[job:idx]`` prefix) entirely in C, with a pure-Python
fallback when the shared library hasn't been built.
"""

from __future__ import annotations

import ctypes
import os
from typing import BinaryIO, Optional

_LIB_PATH = os.path.join(os.path.dirname(__file__), "native", "liblogpump.so")
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if not _lib_tried:
        _lib_tried = True
        if os.path.exists(_LIB_PATH):
            try:
                lib = ctypes.CDLL(_LIB_PATH)
                lib.tpumesos_pump_lines.argtypes = [
                    ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_char_p, ctypes.c_size_t,
                ]
                lib.tpumesos_pump_lines.restype = ctypes.c_int
                _lib = lib
            except OSError:
                _lib = None
    return _lib


def pump_lines(src: BinaryIO, local_out: BinaryIO, forward_fd: int,
               prefix: bytes) -> None:
    """Pump ``src`` to ``local_out`` line by line until EOF; each line also
    goes to ``forward_fd`` (if >= 0) with ``prefix`` prepended (reference
    behavior: server.py:86-87, 99-102)."""
    lib = _load()
    if lib is not None:
        local_out.flush()
        rc = lib.tpumesos_pump_lines(src.fileno(), local_out.fileno(),
                                     forward_fd, prefix, len(prefix))
        if rc == 0:
            return
        # fall through to Python on native failure
    for line in iter(src.readline, b""):
        local_out.write(line)
        local_out.flush()
        if forward_fd >= 0:
            try:
                os.write(forward_fd, prefix + line)
            except OSError:
                forward_fd = -1
