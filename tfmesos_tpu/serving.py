"""Continuous batching over the paged KV cache.

The reference framework stops at training jobs (its serving story is
"run a session somewhere"); this module is the inference-side scheduler
the paged cache layout exists for: a persistent page pool plus an
admission loop that feeds new prompts into a RUNNING batched decode —
rows free on stop-token, arrivals prefill into freed rows, and
:class:`~tfmesos_tpu.models.transformer.PageAllocator` state persists
across the whole stream (docs/SERVING.md).  Offline batch serving
(``examples/serve.py`` without ``--continuous``) allocates and releases
pages per closed batch; this loop keeps the decode step hot and bounds
memory by LIVE tokens, not by batch-max shapes.

Determinism contract: a request's tokens depend only on (its prompt,
its ``rid``-folded sampling key) — never on what else is in flight.
Greedy streams are bit-identical to a per-request
:func:`~tfmesos_tpu.models.transformer.generate` call; sampled streams
are invariant to batching/staggering because every row draws from its
own fold of the batcher RNG (``fold_in(rng, rid)`` then per-step
``fold_in(key, step)``), not from a shared stream.  The folds happen
IN-GRAPH from ``rid``/``step`` vectors, so the host loop issues no
per-row dispatches.

Two compiled shapes serve everything: one decode step at ``[rows, 1]``
with a fixed-width page table, and one prefill per prompt-length bucket
(lengths round up to ``prefill_bucket``).  Admission reserves each
request's WORST-CASE page count against the pool up front, while the
allocator backs pages incrementally as the row grows — so memory use is
length-proportional but mid-flight pool exhaustion is impossible by
construction.
"""

from __future__ import annotations

import dataclasses
import queue as _queue
import threading
import time
from collections import deque
from functools import partial
from typing import Any, Dict, Iterable, Iterator, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from tfmesos_tpu import prefixhash as _ph
from tfmesos_tpu.compat import shard_map
from tfmesos_tpu.fleet.tracing import FlightRecorder
from tfmesos_tpu.models.transformer import (PageAllocator, TransformerConfig,
                                            decode_step,
                                            greedy_accept_counts,
                                            init_paged_cache,
                                            rejection_accept, sample_logits)
from tfmesos_tpu.ops.quant import QTensor

__all__ = ["Request", "Completion", "Suspended", "Expired",
           "ContinuousBatcher", "SubmissionQueue", "Prefilled",
           "pack_prefilled", "unpack_prefilled",
           "BYPASS_ALLOWLIST", "compute_bypass_reasons"]

# SubmissionQueue.poll's end-of-stream marker (distinct from None, which
# means "nothing available right now, more may come").
_CLOSED = object()
#: SubmissionQueue wake-up sentinel (see SubmissionQueue.kick): wakes
#: an idle-blocked serve loop without submitting work, so a queued
#: weight update (swap_adapter / set_weights) applies promptly on an
#: otherwise idle batcher instead of waiting for the next request.
_KICK = object()

#: THE bypass registry's documented allowlist: every reason string a
#: ``*_bypass_reason`` attribute is allowed to carry, per registry.
#: The burn-down is ENFORCED, not aspirational — the audit test
#: (tests/test_serving.py::test_bypass_registry_audit) enumerates every
#: reachable :class:`ContinuousBatcher` config through
#: :func:`compute_bypass_reasons` and fails on any value not listed
#: here, so a new bypass cannot land silently and a removed one cannot
#: regress.  History: "speculative decoding" was burned out of the
#: ``prefix_cache`` and ``kv_tier`` registries (spec rows are
#: first-class citizens of the paged-KV machinery now), and the former
#: constructor REJECTIONS became composition or enforced entries here:
#: spec+multi_step now COMPOSES (R spec rounds per dispatch — see
#: ``_make_spec_round``), overlap+pipeline and suspend-under-lag are
#: enforced bypasses below.
BYPASS_ALLOWLIST = {
    # An int8 pool's tail-recompute path (chunk writer) is not
    # bit-stable against the cold fused prefill, so shared pages could
    # break the warm==cold equivalence bar; the draft pool's int8 mode
    # shares the same writer, hence the same reason.
    "prefix_cache": ("quantized kv cache",),
    # Mesh data shards pin pages locally (no single-shard scatter to
    # move), and the int8 tail recompute above breaks resume==cold.
    "kv_tier": ("mesh data sharding", "quantized kv cache"),
    # Speculative overlap already carries its round state on device —
    # measured equal-or-better than the pipelined carry would be on
    # the same workload (bench_serving_spec_compose's overlap arm vs
    # bench_serving_pipeline: both remove the per-block host sync, and
    # a spec round retires up to n_draft+1 tokens per sync where the
    # pipelined carry retires multi_step) — so pipeline_depth on a
    # speculative batcher records this instead of double-carrying.
    "pipeline": ("speculative decoding",),
    # pipeline_depth=1's device-resident carry already removes the
    # host round-trip overlap double-buffers away (measured: the
    # pipelined inter-token p50 is asserted strictly below the
    # synchronous loop's in bench_serving_pipeline, the same sync
    # overlap hides), so overlap under an active pipeline is redundant
    # — recorded, not rejected.
    "overlap": ("pipelined decode carry",),
    # Speculative overlap rounds already fuse n_draft+1 tokens per
    # dispatch AND hide the host sync behind the next round
    # (bench_serving_spec_compose measures the round itself at one
    # verify launch per layer); folding extra sync rounds under the
    # in-graph carry would lag commits R rounds behind the host for
    # no additional sync savings, so multi_step collapses to the
    # round's natural width there.
    "multi_step": ("speculative overlap round carry",),
    # Per-row suspend/export needs a host-synchronous row snapshot;
    # overlap/pipelined modes carry in-flight device state the host
    # view lags one block behind (the lag IS the measured win:
    # bench_serving_pipeline's p50 gap), and mesh data shards pin
    # pages locally like the kv_tier/export surface.
    "suspend": ("mesh data sharding", "lagged decode carry"),
    # Stall-free fused prefill+decode ticks (one dispatch covers the
    # decode block AND a budgeted batch of prefill chunk slots).  Mesh
    # data shards dispatch chunks one-hot per shard (the fused slot
    # layout has no shard axis to ride); a speculative round's dispatch
    # is the verify program — its chunk writes advance the DRAFT pool
    # in lockstep, a second fused surface the single-program layout
    # does not cover yet (burn-down: fold the chunk writes into
    # _make_spec_round's body); lagged modes retire a block behind and
    # a chunk slot's first-token sample is host-synchronous by design.
    "fused_prefill": ("mesh data sharding", "speculative decoding",
                      "lagged decode carry"),
}


def compute_bypass_reasons(*, speculative: bool = False,
                           n_shards: int = 1,
                           quantized_cache: bool = False,
                           draft_quantized_cache: bool = False,
                           pipeline_depth: int = 0,
                           overlap: bool = False,
                           multi_step: int = 1
                           ) -> Dict[str, Optional[str]]:
    """The ``*_bypass_reason`` values a :class:`ContinuousBatcher`
    built from these mode flags records — ONE pure function, used by
    ``__init__`` itself, so the bypass-registry audit test can
    enumerate every reachable config without building batchers.  Keys
    mirror :data:`BYPASS_ALLOWLIST`; ``None`` = the feature composes."""
    quant = quantized_cache or (speculative and draft_quantized_cache)
    out: Dict[str, Optional[str]] = {
        "prefix_cache": None, "kv_tier": None, "pipeline": None,
        "overlap": None, "multi_step": None, "suspend": None,
        "fused_prefill": None}
    if quant:
        out["prefix_cache"] = "quantized kv cache"
    if n_shards != 1:
        out["kv_tier"] = "mesh data sharding"
    elif quant:
        out["kv_tier"] = "quantized kv cache"
    if pipeline_depth and speculative:
        out["pipeline"] = "speculative decoding"
    # Effective lag modes AFTER the cross-bypasses above: overlap
    # yields to an ACTIVE pipeline (non-spec), and the pipeline itself
    # yields to speculation.
    pipelined = bool(pipeline_depth) and not speculative
    if pipelined and overlap:
        out["overlap"] = "pipelined decode carry"
    overlap_eff = overlap and out["overlap"] is None
    if speculative and multi_step > 1 and overlap_eff:
        out["multi_step"] = "speculative overlap round carry"
    if n_shards != 1:
        out["suspend"] = "mesh data sharding"
    elif overlap_eff or pipelined:
        out["suspend"] = "lagged decode carry"
    if n_shards != 1:
        out["fused_prefill"] = "mesh data sharding"
    elif speculative:
        out["fused_prefill"] = "speculative decoding"
    elif overlap_eff or pipelined:
        out["fused_prefill"] = "lagged decode carry"
    return out


class SubmissionQueue:
    """Thread-safe incremental :class:`Request` source for
    :meth:`ContinuousBatcher.run` — the online front door's adapter
    around the loop's internal ``pull()``.

    Any thread may :meth:`submit` at any time; :meth:`close` marks the
    end of the stream (submissions after it raise).  The run loop polls
    NON-blocking while rows are decoding — an empty queue never stalls
    in-flight requests the way a blocking iterable would — and blocks
    only when the batcher is otherwise idle.
    """

    def __init__(self) -> None:
        self._q: "_queue.Queue" = _queue.Queue()
        self._closed = False
        self._lock = threading.Lock()

    def submit(self, request) -> None:
        if not isinstance(request, (Request, Prefilled)):
            raise TypeError(f"submit() takes a Request or Prefilled, got "
                            f"{type(request).__name__}")
        with self._lock:
            if self._closed:
                raise RuntimeError("submission queue is closed")
            self._q.put(request)

    def close(self) -> None:
        """End the stream: the serve loop drains what was submitted and
        returns.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(_CLOSED)

    def kick(self) -> None:
        """Wake a blocked serve loop WITHOUT submitting work (the
        weight-update path: an idle loop must notice a queued
        swap_adapter/set_weights now, not at the next request).
        Harmless after close."""
        with self._lock:
            if not self._closed:
                self._q.put(_KICK)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def poll(self, block: bool):
        """Next request; ``None`` when empty (and more may come), the
        ``_CLOSED`` sentinel at end of stream.  ``block=True`` waits for
        one of the two.  Wake-up kicks are swallowed here (they exist
        only to end a blocking poll early)."""
        while True:
            try:
                item = self._q.get(block=block)
            except _queue.Empty:
                return None
            if item is _KICK:
                if block:
                    return None     # woken: let the loop re-check state
                continue
            if item is _CLOSED:
                self._q.put(_CLOSED)  # keep re-polls (and peers) terminal
                return _CLOSED
            return item


@dataclasses.dataclass
class Request:
    """One generation request: ``prompt`` is a 1-D int32 token array.
    ``priority`` is the preemption rank (higher = more important):
    under allocation pressure the batcher may SUSPEND the
    lowest-priority resident row to admit a strictly-higher-priority
    arrival, parking its KV state for later resumption — resumed
    streams are token-identical to uninterrupted ones
    (docs/SERVING.md "Priorities, preemption & migration").

    ``deadline_ms`` is the request's remaining END-TO-END budget at
    construction time (the fleet forwards the shrinking remainder hop
    by hop — absolute clock readings mean nothing across hosts): the
    batcher sheds an arrival whose deadline already passed without
    burning a prefill, and CANCELS an expired resident row like a
    finished one — pages freed immediately, an :class:`Expired` yielded
    in the completion stream — so work the client has abandoned never
    occupies a decode slot.  ``None`` (the default) never expires.

    ``session_id`` (optional) names a multi-turn CONVERSATION: on a
    batcher with a KV tier (``kv_tier=``), the finished request's KV
    parks in the tier under this id, and a later request whose prompt
    EXTENDS the parked history resumes from it — the parked pages
    import and only the new tail prefills, token-identical to a cold
    full-history prefill (docs/SERVING.md "KV tiering & sessions")."""

    prompt: np.ndarray
    max_new_tokens: int
    stop_token: Optional[int] = None
    priority: int = 0
    deadline_ms: Optional[float] = None
    session_id: Optional[str] = None

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32)
        if self.prompt.ndim != 1 or self.prompt.size == 0:
            raise ValueError("Request.prompt must be a non-empty 1-D "
                             "token array (there is no position to "
                             "continue from otherwise)")
        if self.max_new_tokens < 1:
            raise ValueError(f"Request.max_new_tokens must be >= 1, got "
                             f"{self.max_new_tokens}")
        self.priority = int(self.priority)
        if self.session_id is not None:
            self.session_id = str(self.session_id)
        # Request tracing (docs/SERVING.md "Observability"): the fleet
        # replica attaches the hop's TraceContext here; the batcher
        # records its per-request events (admit, preempt, suspend,
        # resume, deadline cancel, finish) onto it when present.  None
        # (the default) costs nothing.
        self.trace = None
        # Incremental token streaming (docs/SERVING.md "Front-door
        # scaling"): ``on_tokens(new_tokens, offset)`` is called from
        # the serve loop once per decode block with the tokens emitted
        # since the last call (``offset`` = tokens already streamed).
        # The Completion still carries the full list — streaming is
        # additive, and a raising callback costs the stream, never the
        # request.  None (the default) costs one attribute read per
        # block.
        self.on_tokens = None
        self.deadline: Optional[float] = None
        if self.deadline_ms is not None:
            if not self.deadline_ms > 0:
                raise ValueError(f"Request.deadline_ms must be > 0, got "
                                 f"{self.deadline_ms}")
            self.deadline = (time.perf_counter()
                             + float(self.deadline_ms) / 1000.0)

    @property
    def expired(self) -> bool:
        """Whether the end-to-end deadline has passed (always False
        without one)."""
        return (self.deadline is not None
                and time.perf_counter() >= self.deadline)


@dataclasses.dataclass
class Prefilled:
    """One IMPORTED prefill — the disaggregated-serving admission unit:
    the original :class:`Request` plus the KV artifact a prefill-role
    batcher exported for it (:meth:`ContinuousBatcher.export_kv`).
    Submit one with ``submit(request, prefilled=artifact)`` (or put it
    on a ``run()`` iterable directly): admission installs the artifact's
    pages into the local pool and the row enters decode with the
    prefill's first token already emitted — no prefill compute runs on
    the importing batcher."""

    request: Request
    artifact: dict

    def __post_init__(self):
        if not isinstance(self.request, Request):
            raise TypeError("Prefilled.request must be a Request")
        if not isinstance(self.artifact, dict):
            raise TypeError("Prefilled.artifact must be an export_kv() "
                            "artifact dict")


# Artifact array leaves, in their fixed wire order (pack/unpack below).
# ``dk``/``dv`` (+ scales) are the DRAFT pool's paired payload on a
# speculative batcher's exports — per-layer draft pages covering the
# same positions as the target's, so a spec row is suspendable,
# migratable, disagg-importable, and KV-tier-parkable like any other.
_KV_ARRAY_KEYS = ("k", "v", "k_scales", "v_scales",
                  "dk", "dv", "dk_scales", "dv_scales")
# Everything else in the artifact is a small scalar/dict header.
# ``step``/``tokens`` carry a SUSPENDED request's mid-stream sampler
# state (tokens emitted so far); a fresh prefill export has step 1 and
# tokens == [first_token], so one artifact shape serves both.  For a
# SPECULATIVE row this (rid, step, tokens) triple is the entire spec
# sampler state too: draft proposals and acceptance/correction draws
# are pure per-(rid, step+j) key folds, so there is no separate draft
# rng position to carry — resuming at ``step`` continues the exact
# streams.  ``draft`` is the draft-side geometry header
# (layers/heads/dim, quantized flag, n_draft) paired with dk/dv.
# ``history`` is the SESSION-park addition (the full conversation —
# prompt + every emitted token — the artifact's pages cover, which is
# what a resume validates the new turn's prompt against); absent on
# plain prefill/suspend artifacts.
_KV_META_KEYS = ("version", "page_size", "prefix_len", "shared_len",
                 "pos", "prompt_len", "first_token", "rid", "quantized",
                 "model", "step", "tokens", "history", "draft")


def pack_prefilled(artifact: dict) -> tuple:
    """Split an :meth:`~ContinuousBatcher.export_kv` artifact into a
    small JSON-encodable ``meta`` dict and one contiguous ``body`` buffer —
    the shape :func:`tfmesos_tpu.wire.send_raw_msg` ships without
    re-encoding multi-MB tensor data.  The caller may merge transport
    fields (``op``/``id``/request params) into ``meta`` before
    sending."""
    meta = {k: artifact[k] for k in _KV_META_KEYS if k in artifact}
    specs, parts = [], []
    for name in _KV_ARRAY_KEYS:
        a = artifact.get(name)
        if a is None:
            continue
        a = np.ascontiguousarray(a)
        specs.append({"name": name, "dtype": str(a.dtype),
                      "shape": list(a.shape)})
        parts.append(a)
    meta["arrays"] = specs

    def buf(a):
        # Zero-copy for buffer-protocol dtypes; extension dtypes
        # (bfloat16) reject memoryview and copy through tobytes —
        # frombuffer on the unpack side reads either encoding.
        try:
            return memoryview(a).cast("B")
        except (ValueError, TypeError):
            return a.tobytes()

    return meta, b"".join(buf(a) for a in parts)


def unpack_prefilled(meta: dict, body) -> dict:
    """Inverse of :func:`pack_prefilled`: rebuild the artifact dict from
    a received raw frame.  Array leaves are zero-copy views into
    ``body``; malformed frames raise ``ValueError`` (the import
    admission path rejects them as bad requests)."""
    art = {k: meta[k] for k in _KV_META_KEYS if k in meta}
    specs = meta.get("arrays")
    if not isinstance(specs, (list, tuple)):
        raise ValueError("prefilled meta carries no array manifest")
    view = memoryview(body).cast("B")
    off = 0
    for spec in specs:
        try:
            name = spec["name"]
            dtype = np.dtype(spec["dtype"])
            shape = tuple(int(d) for d in spec["shape"])
        except (TypeError, KeyError, ValueError) as e:
            raise ValueError(f"bad prefilled array spec {spec!r}") from e
        if name not in _KV_ARRAY_KEYS:
            raise ValueError(f"unexpected prefilled array {name!r}")
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = count * dtype.itemsize
        if off + nbytes > len(view):
            raise ValueError("prefilled body shorter than its manifest")
        art[name] = np.frombuffer(view, dtype=dtype, count=count,
                                  offset=off).reshape(shape)
        off += nbytes
    if off != len(view):
        raise ValueError(f"prefilled body has {len(view) - off} trailing "
                         f"bytes beyond its manifest")
    return art


@dataclasses.dataclass
class Completion:
    """A finished request: ``tokens`` are the generated continuation
    (including the stop token when one was emitted), ``rid`` the
    admission-order id the batcher assigned.  ``ttft_s`` is wall time
    from admission (prefill start) to the first token; ``total_s`` to
    the last."""

    rid: int
    request: Request
    tokens: List[int]
    ttft_s: float = 0.0
    total_s: float = 0.0


@dataclasses.dataclass
class Suspended:
    """An in-flight request the batcher gave BACK instead of finishing —
    yielded by :meth:`ContinuousBatcher.serve`/``run`` after
    :meth:`ContinuousBatcher.preempt_all` (the drain-migration path).

    ``artifact`` is an :meth:`~ContinuousBatcher.export_kv`-shaped dict
    carrying the row's KV pages AND its mid-stream sampler state
    (``step``, ``tokens``): re-admitting it anywhere via
    ``submit(request, prefilled=artifact)`` resumes the stream
    token-identically to an uninterrupted run.  ``artifact`` is ``None``
    when the request held no resumable state (still queued, still
    prefilling, or a serving mode without per-row export) — the caller
    re-runs it from scratch, which is lossless too: nothing was
    delivered, and completions are deterministic functions of the
    request."""

    rid: int
    request: Request
    artifact: Optional[dict] = None


@dataclasses.dataclass
class Expired:
    """A request the batcher CANCELLED because its end-to-end deadline
    passed — yielded in the completion stream wherever the Completion
    would have gone (docs/SERVING.md "Deadlines & failure
    containment").  A resident row's pages are freed the moment it
    expires (dead work never occupies a decode slot); a queued arrival
    is shed before its prefill ever dispatches.  ``rid`` is -1 when the
    request never reached admission."""

    rid: int
    request: Request


@dataclasses.dataclass
class _Row:
    """Host-side state of one in-flight row."""

    rid: int
    req: Request
    pos: int            # next cache position to write (= current length)
    step: int           # tokens generated so far
    last: int           # last emitted token (feeds the next decode step)
    out: List[int]
    worst_pages: int    # admission-time reservation (target pool)
    worst_draft: int = 0    # ... and the draft pool's, in speculative mode
    t_admit: float = 0.0    # perf_counter at prefill start
    t_first: float = 0.0    # ... at first-token availability
    # Chunked-prefill state (prefill_chunk mode): the padded prompt and
    # how much of it has been written; rows decode only once filled.
    padded: Optional[np.ndarray] = None
    filled: int = 0
    decoding: bool = True
    # Absolute position cap the admission reservation covers: multi-step
    # blocks clamp their ensure() calls here so a row's allocations can
    # never exceed its reservation (the headroom() accounting depends on
    # allocated <= worst); in-block overshoot writes past it land on
    # sink columns of the table instead.
    limit: int = 0
    # Incremental streaming (Request.on_tokens): how many of ``out``'s
    # tokens have been flushed to the callback so far — the serve loop
    # pushes the [streamed:] suffix once per block.
    streamed: int = 0


@dataclasses.dataclass
class _PrefixPlan:
    """Admission-time decision to serve a request's leading prompt
    pages from the prefix cache: map ``nodes``' pages read-only and
    prefill only from ``tail_start`` on.  ``cow`` marks the
    page-aligned full hit, where the one-token logits chunk must write
    INTO the deepest cached page — that page is first copied into a
    freshly reserved own page (copy-on-write) so shared state is never
    written."""

    nodes: list
    cow: bool
    tail_start: int     # first ABSOLUTE position the prefill writes

    @property
    def save(self) -> int:
        """Own-page reservations the mapping saves (a COW hit re-backs
        its deepest page with an own copy)."""
        return len(self.nodes) - (1 if self.cow else 0)


class _ShardedAlloc:
    """``PageAllocator``'s surface over per-shard sub-pools: rows are
    partitioned into ``n_shards`` contiguous groups (shard = row //
    rows_per_shard — the layout ``PartitionSpec("dp")`` gives a sharded
    axis), each group allocating from its own shard of the physical
    pool, and every page id handed out is LOCAL to its shard.  With
    ``n_shards=1`` this is exactly one PageAllocator.  Reservations
    (``reserve_page``) are taken symmetrically in every shard and must
    land on the same local id — so a single id names the sink or a
    shared-prefix page in every shard's sub-pool."""

    def __init__(self, n_pages_per_shard: int, page_size: int,
                 n_shards: int = 1, rows_per_shard: int = 0):
        self.page_size = int(page_size)
        self.n_shards = int(n_shards)
        self.rows_per_shard = int(rows_per_shard)
        self.shards = [PageAllocator(n_pages_per_shard, page_size)
                       for _ in range(self.n_shards)]

    def shard_of(self, row: int) -> int:
        return row // self.rows_per_shard if self.n_shards > 1 else 0

    @property
    def rows(self) -> Dict[int, list]:
        """Merged row → local-page-list view (global row ids never
        collide across shards)."""
        out: Dict[int, list] = {}
        for a in self.shards:
            out.update(a.rows)
        return out

    @property
    def free(self) -> list:
        """All shards' free local ids, concatenated (sizing/tests)."""
        return [p for a in self.shards for p in a.free]

    def ensure(self, row: int, length: int) -> None:
        self.shards[self.shard_of(row)].ensure(row, length)

    def release(self, row: int) -> None:
        self.shards[self.shard_of(row)].release(row)

    def allocated(self, row: int) -> int:
        return self.shards[self.shard_of(row)].allocated(row)

    def free_count(self, shard: Optional[int] = None) -> int:
        if shard is not None:
            return self.shards[shard].free_count()
        return sum(a.free_count() for a in self.shards)

    def reserve_page(self) -> int:
        ids = [a.reserve_page() for a in self.shards]
        assert all(i == ids[0] for i in ids), \
            "asymmetric reservation — shards must reserve in lockstep"
        return ids[0]


class _PagedSide:
    """Host-side state of ONE paged pool — the target's, or (speculative
    mode) the draft's: the per-shard allocator, reserved sink/prefix
    pages, the device pool, and the cached page tables the jitted steps
    consume.  Table entries are LOCAL page ids (see
    :class:`_ShardedAlloc`); a row with no allocation is all-sink."""

    def __init__(self, n_pages: int, page_size: int, rows: int,
                 np_max: int, n_shards: int = 1):
        if n_pages % n_shards:
            raise ValueError(f"n_pages ({n_pages}) must divide over "
                             f"{n_shards} mesh data shards")
        if rows % n_shards:
            raise ValueError(f"rows ({rows}) must divide over "
                             f"{n_shards} mesh data shards")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.rows = int(rows)
        self.np_max = int(np_max)
        self.alloc = _ShardedAlloc(n_pages // n_shards, page_size,
                                   n_shards, rows // n_shards)
        # Inactive decode rows still execute the batched paged scatter —
        # their table entries must point somewhere writable that no live
        # request owns.  Reserve one pool page (per shard) as that sink.
        self.sink = self.alloc.reserve_page()
        self.pool = None                  # device arrays, set by owner
        self.shared_pages: List[int] = []  # full prefix pages, read-only
        self.shared_len = 0                # positions they cover
        self.tail_template: Optional[int] = None  # partial-page template
        self.peak = 0                      # observability: high-water mark
        # Cross-request prefix cache (set by the owning batcher): pages
        # a row references READ-ONLY between the global shared prefix
        # and its own allocation (row table = [shared | cached | own]).
        self.pcache = None                        # _PrefixCache or None
        self.row_cached: Dict[int, List[int]] = {}
        self._cache = None        # device table; rebuilt when dirty
        self._cache_np = None     # host master copy of the table
        self._masked = None       # (masked_rows, device table)

    def dirty(self) -> None:
        """Invalidate every derived table (host master, device copy,
        masked variants) after ANY page-mapping change — allocation
        growth, release, cached-prefix (re)mapping, COW remap.  One
        choke point so a new mapping path cannot forget one of the
        three caches (stale device tables are silent wrong-output
        bugs)."""
        self._cache = self._cache_np = self._masked = None

    def ensure(self, row: int, length: int) -> None:
        """Back ABSOLUTE positions [0, length): the shared prefix pages
        cover [0, shared_len), mapped cached-prefix pages the next
        ``len(row_cached[row]) * page_size``; the row's own allocation
        covers the rest."""
        before = self.alloc.allocated(row)
        covered = self.shared_len + self.page_size * len(
            self.row_cached.get(row, ()))
        self.alloc.ensure(row, max(0, length - covered))
        if self.alloc.allocated(row) != before:
            self.dirty()
        used = self.n_pages - self.alloc.free_count()
        if used > self.peak:
            self.peak = used

    def release(self, row: int) -> None:
        if self.pcache is not None:
            self.pcache.release_row(row)
        self.alloc.release(row)
        self.dirty()

    def headroom(self, active: Dict[int, _Row], worst_of,
                 shard: int) -> int:
        """Free pages in ``shard`` not spoken for by in-flight rows'
        admission reservations (``worst_of(row)`` — worst_pages or
        worst_draft).  Zero-ref cached-prefix pages count as free: the
        allocator reclaims them on demand (LRU eviction), so they must
        not block admission."""
        outstanding = sum(
            worst_of(row) - self.alloc.allocated(r)
            for r, row in active.items()
            if self.alloc.shard_of(r) == shard)
        reclaimable = (self.pcache.reclaimable(shard)
                       if self.pcache is not None else 0)
        return self.alloc.free_count(shard) + reclaimable - outstanding

    def table_np(self) -> np.ndarray:
        """Host master copy of the table (chunked prefill masks per-step
        variants off it)."""
        if self._cache_np is None:
            # Rows WITH allocations see [shared prefix pages |
            # cached-prefix pages | own pages]; rows without stay
            # all-sink (an inactive row writes its garbage step at
            # position 0 — that must never land on a shared or live
            # page).
            t = np.full((self.rows, self.np_max), self.sink, np.int32)
            ns = len(self.shared_pages)
            rows_map = self.alloc.rows
            for r in range(self.rows):
                own = rows_map.get(r) or []
                cached = self.row_cached.get(r) or []
                if own or cached:
                    if ns:
                        t[r, :ns] = self.shared_pages
                    nc = len(cached)
                    if nc:
                        t[r, ns:ns + nc] = cached
                    t[r, ns + nc:ns + nc + len(own)] = own
            self._cache_np = t
        return self._cache_np

    def table(self) -> jnp.ndarray:
        """Fixed-shape [rows, np_max] device table, rebuilt only when the
        allocation actually changed (page-boundary growth, admission,
        release) — not every token."""
        if self._cache is None:
            self._cache = jnp.asarray(self.table_np())
        return self._cache

    def bucket_width(self) -> int:
        """Smallest power-of-two table width covering every allocated
        row (shared prefix pages + own pages), capped at ``np_max``.
        The paged kernel's grid iterates the TABLE WIDTH per (row, page)
        — kv heads are folded into each block, and skipped entries still
        cost a grid step through the scalar-prefetched index map — so
        dispatching at the worst-case
        width makes short-lived requests on a long-max_len pool pay for
        context they don't have (measured 3.4x on an 8k pool early in
        generation, v5e round 5).  Power-of-two bucketing bounds the
        jit cache at log2(np_max) decode variants.  Safety: every
        decoding row's reads (kernel block bound <= its allocation) and
        writes stay inside the slice, and the width is STRICTLY greater
        than the widest allocation, so an overrun row's clamped
        out-of-reservation write (quota-finished mid-block) hits a
        column past its own pages — sink — never its last live page
        (at the np_max cap the pre-bucketing invariant already held)."""
        ns = len(self.shared_pages)
        rows_map = self.alloc.rows
        occ = max((ns + len(self.row_cached.get(r, ()))
                   + len(rows_map.get(r, ()))
                   for r in set(rows_map) | set(self.row_cached)
                   if rows_map.get(r) or self.row_cached.get(r)),
                  default=1)
        return self.width_for(occ, self.np_max)

    @staticmethod
    def width_for(occ: int, np_max: int) -> int:
        """The table width dispatched for ``occ`` allocated pages — the
        ONE bucketing formula, shared with ``ContinuousBatcher.
        _decode_widths`` so warmup compiles exactly the widths the
        serve loop will request."""
        return min(1 << occ.bit_length(), np_max)

    def decode_table(self, active: Dict[int, _Row],
                     decoding: Dict[int, _Row]) -> jnp.ndarray:
        """The batched step's device table, sliced to ``bucket_width``
        columns: the plain cached table when every active row
        participates; otherwise a masked variant with non-participating
        rows' entries pinned to the sink (still-filling rows' chunked
        prefill owns their pages; overlap mode's quota-finished rows
        await retire).  Cached keyed on (masked set, width) until the
        allocation changes — steady-state decode must neither re-upload
        nor re-slice the table every block."""
        w = self.bucket_width()
        masked = (frozenset() if len(decoding) == len(active)
                  else frozenset(r for r in active if r not in decoding))
        if self._masked is None or self._masked[0] != (masked, w):
            if masked:
                t = self.table_np().copy()
                for r in masked:
                    t[r, :] = self.sink
                t = t[:, :w]
            else:
                t = self.table_np()[:, :w]
            self._masked = ((masked, w), jnp.asarray(t))
        return self._masked[1]


class _PrefixNode:
    """One cached page-aligned chunk: a trie node owning one resident
    pool page — and, on a speculative batcher, its DRAFT-pool twin
    (``dpage``): the two pools cover the same token chunk, so they
    share one refcount and live or die together.  ``ref`` counts the
    live rows referencing the page read-only; a zero-ref node keeps
    its page(s) RESIDENT (that is the cache) until the LRU evictor
    reclaims it under allocation pressure or the budget."""

    __slots__ = ("digest", "page", "ref", "parent", "children", "last",
                 "shard", "dpage")

    def __init__(self, digest: bytes, page: int, parent, last: int,
                 shard: int, dpage: Optional[int] = None):
        self.digest = digest
        self.page = page
        self.ref = 1
        self.parent = parent        # _PrefixNode or None (root level)
        self.children: Dict[bytes, "_PrefixNode"] = {}
        self.last = last            # LRU tick of the last touch
        self.shard = shard
        self.dpage = dpage          # draft-pool twin (speculative mode)


class _PrefixCache:
    """Cross-request prefix cache over ONE :class:`_PagedSide`: a hash
    trie per mesh data shard (pages are shard-pinned, so a cached page
    is only reachable from rows of its own shard) mapping chain digests
    of page-aligned prompt chunks (:mod:`tfmesos_tpu.prefixhash`) to
    resident pool pages with refcounts.

    Lifecycle: admission walks the trie for the longest cached prefix
    and maps those pages read-only into the row's table (``acquire`` —
    refcount++); the prefill writes only the uncached tail, after which
    the tail's full prompt pages are PUBLISHED into the trie
    (``insert_row`` — ownership moves from the row's allocator list to
    the cache, the row keeping a reference).  ``release_row`` drops the
    references when the request finishes; zero-ref pages stay resident
    and are reclaimed lazily — the allocator's ``reclaim`` hook evicts
    LRU leaves only when an allocation would otherwise fail, and
    ``budget`` caps total cached pages per shard at insert time.

    Twin-pool mode (``dside`` — a speculative batcher's draft pool):
    every node couples one target page with one draft page covering
    the same chunk, under ONE refcount.  Acquire maps both into the
    row's tables, publish moves both sides' leading own pages, COW
    remaps both deepest pages, and eviction frees both — the budget
    counts NODES (so it caps ``budget`` pages per shard on EACH
    side).  Either side's allocation pressure can trigger the
    reclaim, which always frees a page on both.

    Thread safety: all mutation happens on the batcher's serve loop;
    ``summary()``/``stats()`` are read from the replica heartbeat
    thread, so every public method takes the lock.
    """

    def __init__(self, side: _PagedSide, page_size: int, first: int,
                 seed: bytes, budget: int, n_shards: int = 1,
                 dside: Optional[_PagedSide] = None):
        self.side = side
        self.dside = dside
        self.page_size = int(page_size)
        self.first = int(first)     # width of chunk 0 (page - prefix tail)
        self.seed = seed            # chain seed (constant prefix tail)
        self.budget = int(budget)   # max cached pages PER SHARD
        self.n_shards = int(n_shards)
        self.roots: List[Dict[bytes, _PrefixNode]] = [
            {} for _ in range(self.n_shards)]
        self.row_nodes: Dict[int, List[_PrefixNode]] = {}
        # O(1) occupancy counters (the admission hot path reads these
        # per shard per attempt — walking the trie there would be
        # O(cached pages) per tick): total resident nodes, and nodes at
        # ref 0 (= reclaimable; a referenced descendant keeps every
        # ancestor referenced, so zero-ref <=> evictable).
        self._n_nodes = [0] * self.n_shards
        self._n_zero = [0] * self.n_shards
        self._tick = 0
        self._lock = threading.Lock()
        # Eviction-callback seam (the KV-tier spill hook, and anything
        # else that wants the page's content before it returns to the
        # free list): called as ``on_evict(shard, digest, page,
        # dpage)`` (dpage None without a draft twin) BEFORE the pages
        # free, while their pool content is still the published chunk.
        # A raising callback costs the spill, never the eviction —
        # reclaim must always make progress, or the allocation
        # pressure that triggered it deadlocks admission.
        self.on_evict = None
        self._stats = {"hits": 0, "misses": 0, "hit_pages": 0,
                       "hit_tokens": 0, "inserted": 0, "evicted": 0,
                       "cow_copies": 0, "skipped": 0, "promoted": 0}
        side.pcache = self
        for s, alloc in enumerate(side.alloc.shards):
            alloc.reclaim = partial(self._reclaim_cb, s)
        if dside is not None:
            # Draft-side pressure evicts through the SAME trie (one
            # eviction frees a page on both sides), and the draft's
            # headroom() counts the shared zero-ref nodes reclaimable.
            dside.pcache = self
            for s, alloc in enumerate(dside.alloc.shards):
                alloc.reclaim = partial(self._reclaim_cb, s)

    def _dirty(self) -> None:
        self.side.dirty()
        if self.dside is not None:
            self.dside.dirty()

    # -- trie walks (call under the lock) ---------------------------------

    def _walk(self, shard: int):
        stack = list(self.roots[shard].values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            yield n

    def _match(self, shard: int, digests) -> List[_PrefixNode]:
        level = self.roots[shard]
        path: List[_PrefixNode] = []
        for d in digests:
            node = level.get(d)
            if node is None:
                break
            path.append(node)
            level = node.children
        return path

    def match(self, shard: int, digests) -> List[_PrefixNode]:
        """Longest cached path for ``digests`` (read-only; refs are
        taken by ``acquire`` once admission commits to the row)."""
        with self._lock:
            return self._match(shard, digests)

    # -- row mapping -------------------------------------------------------

    def acquire(self, row: int, nodes: List[_PrefixNode]) -> None:
        """Map ``nodes``' pages read-only into ``row``'s table
        (refcount++ each) — the row's table becomes
        [shared | these pages | own] — on BOTH pools in twin mode."""
        with self._lock:
            self._tick += 1
            for n in nodes:
                n.ref += 1
                if n.ref == 1:
                    self._n_zero[n.shard] -= 1
                n.last = self._tick
            self.row_nodes[row] = list(nodes)
            self.side.row_cached[row] = [n.page for n in nodes]
            if self.dside is not None:
                self.dside.row_cached[row] = [n.dpage for n in nodes]
        self._dirty()

    def unmap_last(self, row: int) -> _PrefixNode:
        """Drop the DEEPEST mapped page (both pools' twins in twin
        mode) from ``row``'s table (the copy-on-write remap: its
        content moves into a freshly reserved own page); the node's
        reference is still held — release it via ``release_nodes``
        once the copy has been dispatched so the evictor cannot
        reclaim the source mid-copy."""
        with self._lock:
            node = self.row_nodes[row][-1]
            self.side.row_cached[row].pop()
            if self.dside is not None:
                self.dside.row_cached[row].pop()
        self._dirty()
        return node

    def _drop_ref(self, n: _PrefixNode) -> None:
        n.ref -= 1
        if n.ref == 0:
            self._n_zero[n.shard] += 1
        n.last = self._tick

    def release_nodes(self, row: int, nodes) -> None:
        with self._lock:
            self._tick += 1
            held = self.row_nodes.get(row, [])
            for n in nodes:
                self._drop_ref(n)
                held.remove(n)

    def release_row(self, row: int) -> None:
        """The row finished: drop every reference it holds.  Pages stay
        resident (zero-ref = the reusable cache) up to the budget.
        Idempotent — in twin mode BOTH sides' release() paths call
        here, and the second call finds nothing left to drop."""
        with self._lock:
            self._tick += 1
            for n in self.row_nodes.pop(row, []):
                self._drop_ref(n)
            self.side.row_cached.pop(row, None)
            if self.dside is not None:
                self.dside.row_cached.pop(row, None)

    def insert_row(self, row: int, shard: int, digests, state) -> None:
        """Publish ``row``'s freshly prefilled full prompt pages into
        the trie: ownership of the leading own pages moves to the cache
        (the row keeps referencing them at the SAME table slots, so no
        table rebuild is needed), extending the path the row already
        holds — both pools' pages move together in twin mode.  Stops
        at the first chunk already published by a concurrent twin (its
        pages stay own — never two owners for one trie node) or when
        the per-shard budget cannot be met by evicting."""
        with self._lock:
            self._tick += 1
            held = self.row_nodes.setdefault(row, [])
            own = self.side.alloc.rows.get(row, [])
            down = (self.dside.alloc.rows.get(row, [])
                    if self.dside is not None else None)
            cached = self.side.row_cached.setdefault(row, [])
            dcached = (self.dside.row_cached.setdefault(row, [])
                       if self.dside is not None else None)
            level = (held[-1].children if held else self.roots[shard])
            moved = 0
            for d in digests[len(held):]:
                if not own or (down is not None and not down):
                    break
                if d in level:
                    break       # a twin published this chunk first
                while (self._size(shard) >= self.budget
                       and self._evict_one(shard)):
                    pass
                if self._size(shard) >= self.budget:
                    self._stats["skipped"] += 1
                    break
                node = _PrefixNode(d, own.pop(0),
                                   held[-1] if held else None,
                                   self._tick, shard,
                                   dpage=(down.pop(0)
                                          if down is not None else None))
                level[d] = node
                self._n_nodes[shard] += 1
                held.append(node)
                cached.append(node.page)
                if dcached is not None:
                    dcached.append(node.dpage)
                level = node.children
                moved += 1
            self._stats["inserted"] += moved
        # The row's remaining claim on the pool is unchanged — the
        # moved pages still back its positions — so its reservation
        # shrinks with its allocation to keep headroom() exact (per
        # side: the draft twin's reservation shrinks identically).
        state.worst_pages -= moved
        if self.dside is not None:
            state.worst_draft -= moved

    # -- eviction ----------------------------------------------------------

    def _size(self, shard: int) -> int:
        return self._n_nodes[shard]

    def reclaimable(self, shard: int) -> int:
        """Pages reclaimable on demand: zero-ref nodes (a referenced
        descendant would keep its ancestors referenced too, so a
        zero-ref subtree is entirely evictable).  O(1) — the admission
        path reads this per shard per attempt."""
        return self._n_zero[shard]

    def _evict_one(self, shard: int) -> bool:
        """Reclaim the LRU zero-ref LEAF (deepest-first keeps every
        remaining node's chain valid); its page — and its draft twin —
        return to their shards' free lists.  Caller holds the lock."""
        best = None
        for n in self._walk(shard):
            if n.ref == 0 and not n.children:
                if best is None or n.last < best.last:
                    best = n
        if best is None:
            return False
        if self.on_evict is not None:
            try:
                self.on_evict(shard, best.digest, best.page, best.dpage)
            except Exception:
                pass    # the spill is best-effort; the eviction stands
        level = (best.parent.children if best.parent is not None
                 else self.roots[shard])
        del level[best.digest]
        self._n_nodes[shard] -= 1
        self._n_zero[shard] -= 1
        self.side.alloc.shards[shard].free.append(best.page)
        if self.dside is not None:
            self.dside.alloc.shards[shard].free.append(best.dpage)
        self._stats["evicted"] += 1
        return True

    def _reclaim_cb(self, shard: int) -> bool:
        with self._lock:
            return self._evict_one(shard)

    def clear(self) -> int:
        """Drop EVERY cached node, returning its page (and draft twin)
        to the free lists — the weight-swap invalidation: pages
        prefilled under the OLD weights must neither map into new rows
        nor spill to the KV tier, so the eviction callback is
        deliberately NOT fired.  Only legal with no resident rows
        (every node at ref 0); the batcher's weight-update fence
        guarantees that.  Returns the number of nodes dropped."""
        with self._lock:
            if self.row_nodes:
                raise RuntimeError(
                    "prefix cache clear with live row references — the "
                    "weight-update fence must drain resident rows first")
            dropped = 0
            for shard in range(self.n_shards):
                for n in self._walk(shard):
                    self.side.alloc.shards[shard].free.append(n.page)
                    if self.dside is not None:
                        self.dside.alloc.shards[shard].free.append(
                            n.dpage)
                    dropped += 1
                self.roots[shard] = {}
                self._n_nodes[shard] = 0
                self._n_zero[shard] = 0
            self._stats["evicted"] += dropped
        if dropped:
            self._dirty()
        return dropped

    def insert_chain(self, shard: int, parent_digests, digest: bytes,
                     page: int, dpage: Optional[int] = None) -> bool:
        """Insert ONE already-resident page (plus its draft twin in
        twin mode) as a zero-ref trie node under the path
        ``parent_digests`` — the KV-tier PROMOTION path: the caller
        took ``page`` (and ``dpage``) off the shard's free list(s) and
        scattered the tier's stored content into them; on True the
        cache owns them (zero-ref ⇒ reclaimable, so headroom
        accounting is unchanged: free lost one page per side,
        reclaimable gained one).  False (parent path gone, a twin
        already published the chunk, or the budget cannot be met) —
        the caller returns the page(s) to the free list(s)."""
        with self._lock:
            self._tick += 1
            # Budget FIRST: evicting after the walk could reclaim a
            # zero-ref leaf on the very parent path just validated.
            while (self._size(shard) >= self.budget
                   and self._evict_one(shard)):
                pass
            if self._size(shard) >= self.budget:
                self._stats["skipped"] += 1
                return False
            level = self.roots[shard]
            parent = None
            for d in parent_digests:
                node = level.get(d)
                if node is None:
                    return False
                parent = node
                level = node.children
            if digest in level:
                return False        # already resident (a twin won)
            node = _PrefixNode(digest, int(page), parent, self._tick,
                               shard,
                               dpage=(None if dpage is None
                                      else int(dpage)))
            node.ref = 0            # resident, unreferenced — the cache
            self._n_zero[shard] += 1
            level[digest] = node
            self._n_nodes[shard] += 1
            self._stats["promoted"] += 1
            return True

    # -- accounting / export ----------------------------------------------

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._stats[name] += n

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self._stats)
            out["cached_pages"] = sum(self._n_nodes)
            out["retained_pages"] = sum(self._n_zero)
        return out

    def summary(self, max_entries: int = 64) -> Dict[str, Any]:
        """Wire-facing cache summary for registry heartbeats: the chunk
        geometry plus the most-recently-touched chain digests, which is
        what the gateway's prefix-affinity router matches incoming
        prompts against (fleet/router.py)."""
        with self._lock:
            nodes = [n for s in range(self.n_shards)
                     for n in self._walk(s)]
            nodes.sort(key=lambda n: n.last, reverse=True)
            # ``stats`` rides along for fleet-wide accounting (the
            # shared-prefix bench sums misses across replicas to assert
            # a common prompt prefilled once per FLEET); the router's
            # matcher only reads the geometry + hashes.
            return {"page": self.page_size, "first": self.first,
                    "seed": self.seed.hex(),
                    "hashes": [n.digest.hex()
                               for n in nodes[:max_entries]],
                    "stats": dict(self._stats)}


@jax.jit
def _gather_pages(pool, ids):
    """Gather pool pages ``ids`` (page axis 1) on every layer and leaf —
    K and V, int8 QTensor values and scales alike: the device side of
    :meth:`ContinuousBatcher.export_kv`.  One trace per page count."""
    return jax.tree_util.tree_map(lambda buf: buf[:, ids], pool)


@partial(jax.jit, donate_argnums=0)
def _install_pages(pool, payload, ids):
    """Scatter an imported page payload (same tree structure as the
    pool, page axis 1 sized to ``ids``) into pool pages ``ids`` — the
    device side of the ``submit(prefilled=...)`` import admission.  One
    trace per page count."""
    return jax.tree_util.tree_map(
        lambda buf, src: buf.at[:, ids].set(src), pool, payload)


@partial(jax.jit, donate_argnums=0)
def _copy_page(pool, src, dst):
    """Copy pool page ``src`` into page ``dst`` on every layer and leaf
    (K and V; int8 QTensors copy values and scales alike) — the
    copy-on-write step behind partially-shared prefix tail pages."""
    return jax.tree_util.tree_map(
        lambda buf: buf.at[:, dst].set(buf[:, src]), pool)


class ContinuousBatcher:
    """Admit a stream of :class:`Request`\\ s into a persistent paged
    decode of ``rows`` concurrent sequences.

    ``n_pages`` sizes the shared pool (default: fully backs
    ``rows x max_len``; smaller pools oversubscribe and admission waits
    for pages instead).  ``temperature``/``top_k``/``top_p`` fix the
    sampling config for the whole batcher (greedy at temperature 0);
    ``rng`` takes either key flavor (raw uint32 pair or typed
    ``jax.random.key``) — it is only ever folded in-graph.

    ``draft_cfg``/``draft_params`` (optional) turn on SPECULATIVE
    decoding inside the batcher: every tick, the draft proposes
    ``n_draft`` tokens per row (batched t=1 steps over its OWN paged
    pool — draft HBM tracks live tokens exactly like the target's, and
    a shared prefix occupies shared draft pages once instead of a
    per-row broadcast; ``draft_n_pages`` sizes it, default fully
    backed; ``draft_quantized_cache=True`` stores it int8 like the
    target's ``quantized_cache``) and the target verifies them in ONE
    ragged chunk over the
    paged pool — rows commit their leading accepted run plus the
    target's correction, so each tick emits 1..n_draft+1 tokens per row
    instead of exactly 1.  Greedy outputs equal the target-only
    batcher's (modulo float-tie argmax forks); with ``temperature > 0``
    the round is Leviathan-style rejection sampling (accept with
    min(1, pt/pd), corrections from norm(max(0, pt − pd))) whose draws
    all derive from per-(rid, token-index) key folds — so sampled
    speculative streams stay invariant to row packing, and committed
    tokens are distributed exactly as target-only sampling.  Composes
    with stop tokens, staggered admission, int8 target pools, and
    shared prefixes (the draft prefills the prefix once and broadcasts
    it to every row of its cache), and chunked prefill (the draft's
    chunks advance in lockstep with the target's).

    ``prefill_chunk`` (optional) turns on CHUNKED PREFILL: instead of
    prefilling a whole prompt in one call (stalling every decoding row
    for the full prompt length), admission writes the prompt in
    fixed-size chunks interleaved one-per-tick with the batched decode
    step — the stall per decoded token is bounded by one chunk's
    compute, whatever the prompt length.  Chunks of <= 64 ride the
    chunked flash-decode kernel on TPU.  The chunk size becomes the
    prompt padding bucket.  Note the chunked path runs every chunk
    through cache-attention (not the fused self-attention prefill), so
    greedy outputs can differ from the unchunked batcher only by
    float-tie argmax flips.

    ``overlap=True`` double-buffers the decode loop: tick t+1 is
    dispatched BEFORE tick t's tokens are synced to the host (rows feed
    the previous dispatch's device output straight back in), so the
    device never idles on a per-token host round-trip — the dominant
    serving cost when dispatch latency is high.  Stop tokens and
    admission act one tick late (a stopped row's extra tick writes one
    reserved position past the stop and is discarded); token streams
    are identical to ``overlap=False``.  Composes with SPECULATIVE
    decoding: continuing rows' token/position/step ride on device
    (commit counts are computed in-graph), the host's view lags one
    retire behind for page backing, and ANY ending — quota included —
    surfaces one round late with the overshoot round's up-to-
    ``n_draft+1`` extra positions reserved per row.

    ``pipeline_depth=1`` PIPELINES the decode loop with a
    device-resident carry: where ``overlap`` still re-uploads the
    per-row token/position/step vectors every block, the pipelined loop
    feeds block N+1 straight from the previous dispatch's device
    outputs (tokens, positions, AND steps stay on device; the page
    table and the small host-merge inputs are refreshed only when
    admission/prefill/finish actually changed the dispatch set) and
    syncs block N's tokens one block behind via the in-flight async
    transfer.  Host-side stop/quota detection lags one block; the
    overshoot block's writes land inside the row's clamped reservation
    or on sink columns — the exact mid-block-stop discard semantics
    ``_step`` documents — so token streams are IDENTICAL to
    ``pipeline_depth=0`` (greedy AND sampled: the (rid, step) key folds
    are unchanged).  Composes with ``multi_step``, chunked prefill,
    int8 pools, ``mesh``, ``prefix``, and the prefix cache; speculative
    decoding BYPASSES explicitly (``pipeline_bypass_reason`` — its
    overlap mode already carries state on device), and ``overlap=True``
    plus ``pipeline_depth=1`` records ``overlap_bypass_reason`` (the
    pipelined carry already double-buffers) with overlap collapsing to
    off.  ``0`` preserves the synchronous loop exactly.

    ``multi_step`` composes with speculative decoding synchronously: R
    = ceil(multi_step / (n_draft+1)) rounds fuse into ONE dispatch,
    chained in-graph from each round's commit counts, committed
    round-by-round on the host.  Under speculative ``overlap`` the
    round carry supersedes it (``multi_step_bypass_reason``).  The
    ``suspend`` registry gates :attr:`preemptible` the same enumerable
    way: per-row suspend/export needs the host-synchronous single-shard
    loop, so overlap/pipelined (lagged carry) and mesh-sharded
    batchers record ``suspend_bypass_reason`` and requeue on
    preemption instead of exporting.

    :meth:`warmup` compiles every jitted entry point the configured
    mode can dispatch (admission prefill, chunk prefill, decode block
    per table-width bucket, speculative round, KV export/import
    scatter) against dummy all-sink shapes — call it at boot to move
    first-request compilation off the serving path.  The fleet's
    ``warming`` replica state rides on it: a replica registers as
    warming, warms, and only then advertises itself routable
    (docs/SERVING.md "Warmup & the warming state").

    ``mesh`` (optional) makes the WHOLE serving loop multi-chip: a
    data (dp/fsdp) x tp ``jax.sharding.Mesh`` — possibly spanning
    processes — over which every model call runs sharded.  Rows are
    partitioned into contiguous blocks, one per data shard; each shard
    owns an equal sub-pool of pages (target AND draft) that its rows'
    tables index with shard-LOCAL ids, so the page gather/scatter stays
    a per-shard shard_map island while the matmuls partition under
    GSPMD (heads/ff over tp).  Admission stays host-global and
    deterministic: on a multi-process mesh every process runs the same
    loop and reads the same replicated token outputs.  Prefill, chunked
    prefill, speculative rounds, prefix sharing, and int8 pools all
    ride the same path; outputs are token-identical to the no-mesh
    batcher (modulo float-tie argmax forks from tp partial-sum order).
    ``rows`` must divide over the data axes, tp must divide both
    models' head counts.

    ``prefix`` (1-D int32, optional) is a SHARED prompt prefix (system
    prompt), prefilled ONCE into reserved pool pages that every row's
    page table references read-only — the paged analogue of
    ``generate(prefix=...)``, at zero per-row HBM for the shared part.
    A partial last page (prefix length not a page multiple) is COPIED
    into each admitted row's first own page so per-row writes never
    touch shared pages.  ``max_len`` still bounds the TOTAL sequence
    (prefix + prompt + new tokens); request positions and outputs are
    unchanged — the prefix is invisible except in attention.

    ``prefix_cache_pages`` (> 0 enables; the value caps resident cached
    pages per mesh data shard) turns on the CROSS-REQUEST PREFIX CACHE:
    full page-aligned prompt chunks are published into a per-shard hash
    trie after prefill, and later requests sharing a leading prompt run
    map those pages read-only (refcounted) and prefill only the
    uncached tail — TTFT for a warm shared system prompt drops to the
    tail's compute.  A page-aligned full hit copies its deepest page
    copy-on-write before the one-token logits rewrite; finished
    requests leave zero-ref pages RESIDENT, reclaimed LRU-first only
    under allocation pressure (admission headroom counts them as free,
    so the cache can never deadlock admission).  Unlike the static
    ``prefix`` above, nothing needs declaring up front — any shared
    system/few-shot prompt is discovered at admission.  Greedy warm
    completions match cold-prefill completions exactly up to float-tie
    argmax flips (the tail prefill runs cache-attention, like chunked
    prefill; bit-identical in practice on the CPU test config).
    Composes with ``prefill_chunk``, ``overlap``, ``multi_step``,
    ``mesh``, ``prefix``, and SPECULATIVE decoding — a spec batcher's
    trie couples every target page with its draft-pool twin (one
    refcount, COW on both deepest pages, twin publish after prefill),
    so a warm hit maps BOTH pools and prefills only the uncached tail
    through each side's chunk writer; ``quantized_cache`` (either
    pool's) BYPASSES sharing explicitly
    (``prefix_cache_bypass_reason``, see ``BYPASS_ALLOWLIST``).

    DISAGGREGATED serving splits the two phases across batchers:
    :meth:`export_kv` runs a prompt through (chunked) prefill only and
    returns its paged-KV state as a host artifact; a matching batcher
    imports it with ``submit(request, prefilled=artifact)`` — pages
    install into the local pool, the row enters decode directly, and
    greedy completions equal the unified batcher's token-for-token
    (sampled ones too, when the batchers share an rng: the artifact
    carries the sampler's rid fold).  Imported full prompt pages seed
    the importer's prefix cache like a local prefill's.  Requires a
    single-shard pool; int8 pools export/import bit-exactly.  A
    SPECULATIVE batcher's artifact carries the draft pool's paired
    payload (``dk``/``dv`` + the ``draft`` header) over the same
    positions — spec rows export, import, suspend, migrate, and park
    like any other — and a fresh (step-1) artifact from a draft-less
    prefill tier imports into a spec batcher by rebuilding the draft's
    prompt KV locally (the same chunk write a local spec admission
    dispatches).  The fleet's prefill/decode role split
    (docs/SERVING.md "Disaggregated prefill/decode") rides this surface.
    """

    def __init__(self, cfg: TransformerConfig, params, rows: int = 8,
                 max_len: Optional[int] = None, page_size: int = 64,
                 n_pages: Optional[int] = None, prefill_bucket: int = 64,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, rng=None,
                 quantized_cache: bool = False, prefix=None,
                 prefill_chunk: Optional[int] = None,
                 draft_cfg: Optional[TransformerConfig] = None,
                 draft_params=None, n_draft: int = 4,
                 draft_n_pages: Optional[int] = None, mesh=None,
                 overlap: bool = False,
                 draft_quantized_cache: bool = False,
                 multi_step: int = 1,
                 prefix_cache_pages: int = 0,
                 pipeline_depth: int = 0,
                 kv_tier=None,
                 rid_seed: int = 0,
                 fused_prefill: bool = False,
                 tokens_per_tick: Optional[int] = None):
        if rows < 1:
            raise ValueError(f"rows must be >= 1, got {rows}")
        if not 0 <= int(rid_seed) < 2 ** 30:
            # rids land in int32 arrays and key the (rid, step) sampling
            # folds; the seed must leave increment headroom below 2^31.
            raise ValueError(f"rid_seed must be in [0, 2^30), got "
                             f"{rid_seed}")
        if prefix_cache_pages < 0:
            raise ValueError(f"prefix_cache_pages must be >= 0, got "
                             f"{prefix_cache_pages}")
        if multi_step < 1:
            raise ValueError(f"multi_step must be >= 1, got {multi_step}")
        if pipeline_depth not in (0, 1):
            raise ValueError(f"pipeline_depth must be 0 (synchronous "
                             f"host sync) or 1 (one block of device-"
                             f"resident lag), got {pipeline_depth}")
        if fused_prefill and prefill_chunk is None:
            raise ValueError("fused_prefill requires prefill_chunk "
                             "(chunked prefill is the lane being fused)")
        if tokens_per_tick is not None and tokens_per_tick < 1:
            raise ValueError(f"tokens_per_tick must be >= 1, got "
                             f"{tokens_per_tick}")
        self.multi_step = int(multi_step)
        self.overlap = bool(overlap)
        # Pipelined device-resident decode (pipeline_depth=1): block N+1
        # is dispatched from the device-side carry — tokens, positions,
        # AND steps never round-trip to the host between blocks — and
        # block N's tokens are synced one block behind.  Speculative
        # decoding bypasses explicitly (a round already carries its
        # state on device under overlap=True); the recorded reason makes
        # the bypass observable, like prefix_cache_bypass_reason.  The
        # ``*_bypass_reason`` registries themselves are computed after
        # the mesh parse below (the shard count participates).
        self.pipeline_depth = int(pipeline_depth)
        self._pipe_carry = None     # device (tok, pos, step) carry
        self._pipe_host = None      # cached host-side dispatch inputs
        # Overlap mode: (device outputs of the in-flight dispatch,
        # {row: rid} ticket).  Speculative overlap additionally carries
        # the device-side (positions, steps) the next round continues
        # from — commit counts are decided in-graph, so the host's
        # row.pos/step view lags one retire behind.
        self._inflight = None
        self.cfg = cfg
        self.params = params
        self.rows = rows
        self.mesh = mesh
        self.n_shards = 1
        self._tp = 1
        if mesh is not None:
            real = {a for a, s in mesh.shape.items() if s > 1}
            if not real <= {"dp", "fsdp", "tp"}:
                raise ValueError(
                    f"ContinuousBatcher meshes are data (dp/fsdp) x tp; "
                    f"got axes {sorted(real)}")
            for a in ("dp", "fsdp"):
                self.n_shards *= mesh.shape.get(a, 1)
            self._tp = mesh.shape.get("tp", 1)
            if rows % self.n_shards:
                raise ValueError(
                    f"rows ({rows}) must divide over the mesh data axes "
                    f"({self.n_shards}) — each data shard serves an equal "
                    f"row block")
            if cfg.kv_heads % self._tp or cfg.n_heads % self._tp:
                raise ValueError(
                    f"tp ({self._tp}) must divide kv_heads "
                    f"({cfg.kv_heads}) and n_heads ({cfg.n_heads})")
        # All ``*_bypass_reason`` registries come from ONE pure
        # helper (compute_bypass_reasons) so the audit test can
        # enumerate every reachable value against BYPASS_ALLOWLIST.
        self._bypass = compute_bypass_reasons(
            speculative=draft_cfg is not None, n_shards=self.n_shards,
            quantized_cache=quantized_cache,
            draft_quantized_cache=draft_quantized_cache,
            pipeline_depth=pipeline_depth,
            overlap=overlap, multi_step=multi_step)
        self.pipeline_bypass_reason: Optional[str] = \
            self._bypass["pipeline"]
        # overlap+pipeline and spec-overlap+multi_step are BYPASSES
        # now, not constructor rejections: the requested flag is
        # recorded with its measured reason and the effective mode
        # collapses to the carry that already covers it.
        self.overlap_bypass_reason: Optional[str] = \
            self._bypass["overlap"]
        self.multi_step_bypass_reason: Optional[str] = \
            self._bypass["multi_step"]
        self.suspend_bypass_reason: Optional[str] = \
            self._bypass["suspend"]
        if self.overlap_bypass_reason is not None:
            self.overlap = False
        # Speculative multi_step>1: under overlap the round carry
        # supersedes it (bypass above); synchronously it composes as R
        # fused rounds per dispatch (see _make_spec_round).
        if draft_cfg is not None:
            if self.multi_step_bypass_reason is not None:
                self._spec_rounds = 1
            else:
                self._spec_rounds = max(
                    1, -(-self.multi_step // max(1, n_draft + 1)))
        else:
            self._spec_rounds = 0
        self.max_len = int(max_len or cfg.max_seq_len)
        if self.max_len > cfg.max_seq_len:
            raise ValueError(f"max_len ({self.max_len}) exceeds the "
                             f"config's max_seq_len ({cfg.max_seq_len})")
        self.page_size = int(page_size)
        self.np_max = -(-self.max_len // self.page_size)
        # Default pool: every row's worst case (max_len minus whatever a
        # shared prefix covers read-only) + the prefix's reserved pages +
        # one inactive-row write sink — so the default always fully backs
        # rows x max_len of live data, prefix or not.
        prefix_np = None if prefix is None else np.asarray(prefix, np.int32)
        n_prefix_pages = (0 if prefix_np is None
                          else -(-int(prefix_np.size) // self.page_size))
        shared_full = (0 if prefix_np is None else
                       (int(prefix_np.size) // self.page_size)
                       * self.page_size)
        own_max = -(-(self.max_len - shared_full) // self.page_size)
        # Default pool: per data shard, its row block's worst case plus
        # the shard's own prefix + sink reservations (reservations are
        # PER SHARD — every sub-pool carries the prefix and a sink).
        per_shard = ((rows // self.n_shards) * own_max
                     + n_prefix_pages + 1)
        self.n_pages = int(n_pages or self.n_shards * per_shard)
        if prefill_chunk is not None:
            if prefill_chunk < 1 or prefill_chunk % 8:
                raise ValueError(f"prefill_chunk ({prefill_chunk}) must be "
                                 f"a positive multiple of 8")
            prefill_bucket = prefill_chunk
        self.prefill_chunk = prefill_chunk
        self.prefill_bucket = int(prefill_bucket)
        # Stall-free fused scheduling (docs/SERVING.md "Stall-free
        # fused scheduling"): one dispatch per tick covers every decode
        # row's K-step block AND up to (tokens_per_tick - n_decode*K)/c
        # prefill chunk tokens from still-filling rows — the chunk no
        # longer rides a separate device call ahead of the block, so
        # decoding rows stop paying a full chunk stall per tick.  Modes
        # the single fused program cannot cover BYPASS with a recorded
        # reason (fused_prefill_bypass_reason — same discipline as the
        # other registries), falling back to the phase-split tick.
        self.fused_prefill_bypass_reason: Optional[str] = None
        if fused_prefill:
            self.fused_prefill_bypass_reason = \
                self._bypass["fused_prefill"]
        self._fused = (fused_prefill
                       and self.fused_prefill_bypass_reason is None)
        #: the per-tick token budget the fused dispatch packs to:
        #: defaults to every row decoding a full block plus one chunk
        #: (>= the phase-split tick's work, so fusion never slows the
        #: schedule down; larger budgets coalesce more filling rows).
        self.tokens_per_tick = int(
            tokens_per_tick or rows * self.multi_step
            + (prefill_chunk or 0))
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self._rng = jax.random.PRNGKey(0) if rng is None else rng
        self.t_side = _PagedSide(self.n_pages, self.page_size, rows,
                                 self.np_max, n_shards=self.n_shards)
        self.t_side.pool = init_paged_cache(
            cfg, self.n_pages, self.page_size, quantized=quantized_cache)
        if mesh is not None:
            from tfmesos_tpu.models.transformer import partition_specs
            self.params = self._place(params, partition_specs(cfg, mesh))
        self._init_side_device_state(self.t_side, cfg,
                                     quantized=quantized_cache)
        self.prefix_len = 0
        self._prefill_fns: Dict[int, Any] = {}
        self._decode = self._make_decode()
        self._chunk_prefill = (self._make_chunk_prefill()
                               if prefill_chunk is not None else None)
        self._fused_step = self._make_fused_step() if self._fused else None
        self.draft_cfg = draft_cfg
        self.draft_params = draft_params
        self.n_draft = int(n_draft)
        if (draft_cfg is None) != (draft_params is None):
            raise ValueError("draft_cfg and draft_params come together")
        self.d_side: Optional[_PagedSide] = None
        if draft_cfg is not None:
            if self.n_draft < 1:
                raise ValueError(f"n_draft must be >= 1, got {n_draft}")
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError("draft and target must share a vocab")
            # +1: the backfill draft step writes one past the proposals,
            # and parked rows sit at position max_len.
            depth = self.max_len + self.n_draft + 1
            if draft_cfg.max_seq_len < depth:
                raise ValueError(
                    f"draft max_seq_len ({draft_cfg.max_seq_len}) must "
                    f"cover max_len + n_draft + 1 ({depth}) — rows can "
                    f"overshoot by a draft run")
            # The draft's K/V is PAGED like the target's (same pool/table
            # layout, its own allocator): admitted requests never exceed
            # max_len positions even with the verify overshoot (_worst_pages
            # validates that), so the draft table is np_max wide too, and
            # draft HBM tracks LIVE tokens instead of a rows x
            # (max_len + n_draft + 1) worst-case buffer.  Parked free rows
            # write at position max_len through all-sink table rows (the
            # clamped block gather lands on the sink page).
            if mesh is not None and (draft_cfg.kv_heads % self._tp
                                     or draft_cfg.n_heads % self._tp):
                raise ValueError(
                    f"tp ({self._tp}) must divide the DRAFT's kv_heads "
                    f"({draft_cfg.kv_heads}) and n_heads "
                    f"({draft_cfg.n_heads}) too")
            self.n_draft_pages = int(draft_n_pages
                                     or self.n_shards * per_shard)
            self.d_side = _PagedSide(self.n_draft_pages, self.page_size,
                                     rows, self.np_max,
                                     n_shards=self.n_shards)
            self.d_side.pool = init_paged_cache(
                draft_cfg, self.n_draft_pages, self.page_size,
                quantized=draft_quantized_cache)
            if mesh is not None:
                self.draft_params = self._place(
                    draft_params, partition_specs(draft_cfg, mesh))
            self._init_side_device_state(self.d_side, draft_cfg,
                                         quantized=draft_quantized_cache)
            self._spec_round = self._make_spec_round()
            self._draft_chunk = self._make_draft_chunk()
        # Request-id stream base.  Sampled draws are pure (rid, step) key
        # folds, so two EXPORTERS whose rids collide would share an rng
        # stream across artifacts (the PR 4 caveat); per-replica seeding
        # (derived from the fleet node id) keeps exporter streams
        # disjoint.  Imports still continue the exporter's rid — that is
        # the point of the fold.
        self._next_rid = int(rid_seed)
        # Incremental submission (see submit()/serve()); lazily built so
        # plain run(iterable) batchers never pay for it.
        self._submissions: Optional[SubmissionQueue] = None
        self._submissions_lock = threading.Lock()
        # Disaggregated serving (export_kv / submit(prefilled=...)):
        # prefill-only exports serialize on this lock and borrow row 0,
        # so they must never run concurrently with a serve loop (the
        # loop owns the rows); _loop_active fences that.
        self._export_lock = threading.Lock()
        self._loop_active = False
        # Priority preemption / migration (docs/SERVING.md "Priorities,
        # preemption & migration"): artifacts of rows suspended under
        # allocation pressure, waiting for a free row to resume through
        # the import path; the event asks the serve loop to suspend
        # EVERYTHING (drain-migration) and yield Suspended items.
        self._parked: deque = deque()
        self._preempt_event = threading.Event()
        # Online weight updates (docs/SERVING.md "Model catalog"):
        # queued LoRA-style adapter folds (swap_adapter) and full
        # weight swaps (set_weights, the warm-pool adoption path),
        # applied by the serve loop BETWEEN generations — admission
        # gates while one is pending, resident rows finish on the old
        # weights, then the update folds and admission resumes: every
        # stream is token-identical to an offline run under exactly
        # one weights state.  The prefix cache flushes and the KV tier
        # restamps at apply time (old-weights KV must never feed a
        # new-weights decode).
        self._weight_updates: deque = deque()
        self._weights_lock = threading.Lock()
        #: label of the last adapter delta folded in ("" = base
        #: weights) — rides heartbeats and suspended exports so the
        #: router only ever resumes mid-stream KV under the same
        #: delta.
        self.adapter_version = ""
        self.weight_swaps = 0       # updates applied (folds + sets)
        #: optional hook fired (from the serve loop) after each update
        #: applies: ``on_weights_applied(kind, version)`` — the
        #: replica process uses it to refresh heartbeat fields.
        self.on_weights_applied = None
        self.preemptions = 0        # rows suspended for a higher class
        self.resumes = 0            # parked rows re-admitted locally
        # End-to-end deadlines: arrivals shed expired + resident rows
        # cancelled mid-decode (pages freed, Expired yielded) — the
        # replica-side half of fleet deadline conformance.
        self.deadline_cancels = 0
        # Speculative observability (see acceptance_rate).
        self.spec_rounds = 0        # jitted rounds executed
        self.spec_row_rounds = 0    # row-rounds (rows decoding per round)
        self.spec_committed = 0     # tokens committed across them
        # Fused-tick observability (see fused_tokens_per_tick).
        self.fused_ticks = 0          # fused prefill+decode dispatches
        self.fused_chunk_tokens = 0   # prefill tokens they coalesced
        self.fused_decode_tokens = 0  # decode tokens they covered
        # The batcher's flight recorder (docs/SERVING.md
        # "Observability"): a bounded ring of recent component events —
        # notably per-block decode timing from every step mode,
        # pipelined included — that survives even when no request-level
        # trace was retained.
        self.flight = FlightRecorder(256)
        if prefix_np is not None:
            self._init_prefix(prefix_np)
        # Cross-request prefix cache (prefix_cache_pages > 0 enables;
        # the value caps resident cached pages PER SHARD — per POOL in
        # speculative mode, where every trie node couples a target page
        # with its draft-pool twin under one refcount).  Modes whose
        # pages the cache cannot share bitwise-safely BYPASS explicitly
        # (prefix_cache_bypass_reason, from BYPASS_ALLOWLIST): an int8
        # pool's tail-recompute path is not bit-stable against the cold
        # fused prefill (target or draft side alike).
        self._pcache: Optional[_PrefixCache] = None
        self._tail_prefill = None
        self.prefix_cache_bypass_reason: Optional[str] = None
        if prefix_cache_pages:
            self.prefix_cache_bypass_reason = \
                self._bypass["prefix_cache"]
            if self.prefix_cache_bypass_reason is None:
                off = self.prefix_len - self.t_side.shared_len
                seed = (b"" if not off else _ph.chunk_digest(
                    b"", prefix_np[self.t_side.shared_len:]))
                self._pcache = _PrefixCache(
                    self.t_side, self.page_size, self.page_size - off,
                    seed, prefix_cache_pages, n_shards=self.n_shards,
                    dside=self.d_side)
                self._tail_prefill = (self._chunk_prefill
                                      or self._make_chunk_prefill())
        # Tiered KV store (fleet/kvtier.py; docs/SERVING.md "KV tiering
        # & sessions"): prefix pages evicted from the device pool SPILL
        # into it (promoting back on the next matching admission), and
        # finished session-labeled requests PARK their KV artifacts in
        # it for leading-KV resumption next turn.  A speculative
        # batcher's spills and parks carry the draft pool's paired
        # payload, so spec sessions resume like any other.  Modes whose
        # per-row state the single-shard export/import scatter cannot
        # move BYPASS explicitly (kv_tier_bypass_reason — same
        # discipline as the other bypass registries).
        self.kv_tier = kv_tier
        self.kv_tier_bypass_reason: Optional[str] = None
        if kv_tier is not None:
            self.kv_tier_bypass_reason = self._bypass["kv_tier"]
            if self.kv_tier_bypass_reason is None:
                if self._tail_prefill is None:
                    self._tail_prefill = (self._chunk_prefill
                                          or self._make_chunk_prefill())
                if self._pcache is not None:
                    self._pcache.on_evict = self._spill_page
                    kv_tier.prefix_geometry = {
                        "page": self.page_size,
                        "first": self._pcache.first,
                        "seed": self._pcache.seed.hex()}

    @property
    def prefix_cache_active(self) -> bool:
        return self._pcache is not None

    @property
    def _pipelined(self) -> bool:
        """Pipelined decode is actually in effect (requested AND not
        bypassed)."""
        return self.pipeline_depth > 0 and \
            self.pipeline_bypass_reason is None

    @property
    def preemptible(self) -> bool:
        """Whether this batcher can SUSPEND a resident row (priority
        preemption, per-row drain migration): requires the same
        single-shard pool as the disaggregated export/import surface
        (a suspended request IS a KV export — a speculative batcher's
        export carries the draft pool's paired payload, so spec rows
        suspend like any other), and a host-synchronous decode loop —
        overlap/pipelined modes carry in-flight device state the host
        view lags behind, so their rows cannot be snapshotted between
        blocks.  Non-preemptible batchers still honor
        :meth:`preempt_all`, by REQUEUEING every in-flight request
        (lossless through deterministic re-execution) instead of
        exporting it.  The gate IS the registry: ``suspend``'s
        bypass-reason entry (None = suspendable), so the audit test
        enumerates exactly when rows can be snapshotted."""
        return self.suspend_bypass_reason is None

    def paged_launches_per_block(self, block_tokens: int = 16) -> int:
        """Paged-attention kernel launches PER LAYER needed to retire
        ``block_tokens`` decode tokens of one row under this batcher's
        mode — the device-floor metric bench_decode_paged_call tracks
        (BASELINE.md's "8 launches x ~0.54 ms" block cost).  Analytic
        rather than counter-sampled because jit traces the kernel call
        once per compiled step regardless of how many times the XLA
        loop replays it.  Synchronous decode pays one launch per token;
        a speculative round retires up to n_draft+1 tokens through ONE
        fused (t=n_draft+1) verify launch, so 16-token blocks need
        ceil(16 / (n_draft+1)) launches — <= 2 at n_draft >= 7."""
        if self.draft_cfg is not None:
            return -(-int(block_tokens) // (self.n_draft + 1))
        return int(block_tokens)

    def fused_tokens_per_tick(self, n_decode: Optional[int] = None) -> int:
        """Tokens ONE device dispatch covers on a tick with ``n_decode``
        decoding rows (default: all rows) — the analytic twin of
        :meth:`paged_launches_per_block` for the stall-free scheduler.
        Phase-split ticks dispatch only the decode block (the prefill
        chunk rides a SECOND call the decode rows stall behind); a
        fused tick packs the same block plus however many chunk slots
        the ``tokens_per_tick`` budget leaves room for — floored at one
        slot, so a saturated decode set still makes prefill progress
        exactly like the phase-split tick did."""
        n = self.rows if n_decode is None else int(n_decode)
        dt = n * self.multi_step
        if not self._fused:
            return dt
        c = self.prefill_chunk
        return dt + max(1, (self.tokens_per_tick - dt) // c) * c

    def preempt_all(self) -> None:
        """Ask the serve loop to give back EVERY in-flight request as a
        :class:`Suspended` item on its next tick — the victim side of
        cross-replica drain migration: suspended artifacts re-placed on
        another replica (``submit(request, prefilled=artifact)``) resume
        token-identically; requests with no resumable state requeue with
        ``artifact=None``.  Thread-safe; a no-op until the serve loop
        runs (an idle loop processes it on its next submission)."""
        self._preempt_event.set()

    # -- online weight updates (adapter hot-swap / warm-pool adoption) ------

    def swap_adapter(self, delta: Dict[str, Any], version: str,
                     on_applied=None) -> None:
        """Fold a LoRA-style weight DELTA into the serving params with
        zero downtime: ``delta`` maps ``/``-joined param paths (e.g.
        ``"layers/wq"``) to arrays added onto the matching leaves.
        Validated NOW (unknown path / shape mismatch raises
        ``ValueError``); applied by the serve loop once every resident
        row has finished — new admissions wait behind the fence, so
        in-flight requests finish on the OLD delta and every stream is
        token-identical to an offline run under exactly one delta
        version.  ``version`` labels the resulting cumulative state
        (:attr:`adapter_version`); ``on_applied()`` fires from the
        serve loop after the fold (the replica replies to the control
        op from it).  On a batcher with no serve loop (prefill role,
        direct use) the fold applies synchronously."""
        if not isinstance(version, str) or not version:
            raise ValueError("adapter version must be a non-empty "
                             "string")
        resolved = self._resolve_delta(delta)
        self._queue_weight_update(("fold", resolved, version,
                                   on_applied))

    def set_weights(self, params, version: str = "",
                    on_applied=None) -> None:
        """Replace the FULL parameter tree (the warm-pool adoption
        path: a pre-warmed replica installs another model's weights —
        same config/shapes, so nothing recompiles).  Same fence and
        invalidation discipline as :meth:`swap_adapter`; ``version``
        feeds the KV tier's restamp so entries parked under the old
        weights read as version misses, never stale KV."""
        self._queue_weight_update(("set", params, str(version or ""),
                                   on_applied))

    def _resolve_delta(self, delta: Dict[str, Any]):
        """Validate a path->array delta against the live param tree;
        returns ``[(key_path_tuple, np_array), ...]``."""
        if not isinstance(delta, dict) or not delta:
            raise ValueError("adapter delta must be a non-empty dict "
                             "of param-path -> array")
        resolved = []
        for path in sorted(delta):
            keys = tuple(k for k in str(path).split("/") if k)
            node = self.params
            for k in keys:
                if not isinstance(node, dict) or k not in node:
                    raise ValueError(
                        f"adapter delta names unknown param path "
                        f"{path!r}")
                node = node[k]
            if not keys or isinstance(node, dict):
                # An empty path or an interior tree node is not a
                # foldable leaf — reject with the documented error,
                # not an AttributeError on .shape below.
                raise ValueError(
                    f"adapter delta path {path!r} does not name a "
                    f"param array (it is "
                    f"{'empty' if not keys else 'an interior node'})")
            arr = np.asarray(delta[path])
            if tuple(arr.shape) != tuple(node.shape):
                raise ValueError(
                    f"adapter delta shape mismatch at {path!r}: delta "
                    f"{tuple(arr.shape)} vs param {tuple(node.shape)}")
            resolved.append((keys, arr))
        return resolved

    def _queue_weight_update(self, update) -> None:
        with self._export_lock:
            if self._loop_active:
                # The serve loop owns the rows: it applies the update
                # at its next between-generations point; kick wakes an
                # idle-blocked loop so the apply never waits for
                # traffic.
                with self._weights_lock:
                    self._weight_updates.append(update)
                src = self._submissions
                if src is not None:
                    src.kick()
            else:
                # No loop (prefill role, direct export use): apply in
                # place, serialized against export_kv by the lock.
                self._apply_weight_update(update)

    def _apply_pending_weight_updates(self) -> None:
        while True:
            with self._weights_lock:
                if not self._weight_updates:
                    return
                update = self._weight_updates.popleft()
            self._apply_weight_update(update)

    def _apply_weight_update(self, update) -> None:
        kind, payload, version, cb = update
        if kind == "fold":
            new = self.params
            for keys, arr in payload:
                # Copy-on-write along the path only; the fold stays on
                # device for single-host batchers.
                node = new = dict(new)
                for k in keys[:-1]:
                    child = dict(node[k])
                    node[k] = child
                    node = child
                leaf = node[keys[-1]]
                node[keys[-1]] = leaf + jnp.asarray(arr).astype(
                    leaf.dtype)
            self.adapter_version = version
        else:
            new = payload
            self.adapter_version = ""
        if self.mesh is not None:
            from tfmesos_tpu.models.transformer import partition_specs
            new = self._place(new, partition_specs(self.cfg, self.mesh))
        self.params = new
        # The weights changed: every cached KV artifact computed under
        # the old ones is now WRONG for new decodes.  Flush the prefix
        # trie (no spill — stale pages must not enter the tier) and
        # restamp the KV tier so parked sessions/spilled pages from
        # before the update read as version misses (cold re-prefill,
        # never a silently wrong stream).
        if self._pcache is not None:
            self._pcache.clear()
        if self.kv_tier is not None \
                and self.kv_tier_bypass_reason is None:
            restamp = getattr(self.kv_tier, "restamp", None)
            if restamp is not None:
                if kind == "fold":
                    restamp(adapter=version)
                else:
                    restamp(weights_version=version or None, adapter="")
        self.weight_swaps += 1
        hook = self.on_weights_applied
        if hook is not None:
            try:
                hook(kind, version)
            except Exception:
                pass    # observer hook: never costs the update
        if cb is not None:
            try:
                cb()
            except Exception:
                pass    # a broken waiter costs its reply, not the loop

    def prefix_cache_stats(self) -> Optional[Dict[str, int]]:
        """Hit/miss/eviction counters plus current occupancy of the
        cross-request prefix cache (None when disabled or bypassed).
        Thread-safe — the replica heartbeat reads it live."""
        return None if self._pcache is None else self._pcache.stats()

    def prefix_cache_summary(self,
                             max_entries: int = 64) -> Optional[dict]:
        """Wire-facing summary of what the prefix cache holds (chunk
        geometry + recent chain digests) — piggybacked on registry
        heartbeats so the fleet router can steer shared-prefix traffic
        here (prefix-affinity routing).  None when disabled."""
        return (None if self._pcache is None
                else self._pcache.summary(max_entries))

    @property
    def acceptance_rate(self) -> Optional[float]:
        """Fraction of DRAFT proposals accepted: every row-round commits
        its accepted run plus exactly one non-draft token (the
        correction, or the bonus after a full accept), so accepted
        drafts = committed - row_rounds over row_rounds x n_draft
        opportunities.  1.0 = every proposal accepted (perfect draft);
        0.0 = the draft never helped; None before any speculative round
        ran (or without a draft)."""
        if self.d_side is None or not self.spec_row_rounds:
            return None
        return ((self.spec_committed - self.spec_row_rounds)
                / (self.spec_row_rounds * self.n_draft))

    # Back-compat accessors: the paged-side refactor (draft paging) moved
    # the target pool's state into ``t_side``; callers and tests keep the
    # original names.
    @property
    def pool(self):
        return self.t_side.pool

    @pool.setter
    def pool(self, v):
        self.t_side.pool = v

    @property
    def alloc(self) -> _ShardedAlloc:
        return self.t_side.alloc

    @property
    def peak_pages_used(self) -> int:
        return self.t_side.peak

    @property
    def _sink_page(self) -> int:
        return self.t_side.sink

    def _place(self, tree, specs):
        """Place ``tree`` onto the mesh per a PartitionSpec tree —
        through ``place_tree`` so host-identical values assemble into
        global arrays even when the mesh spans processes."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from tfmesos_tpu.parallel.sharding import place_tree
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda n: isinstance(n, P))
        return place_tree(self.mesh, tree, shardings)

    def _init_side_device_state(self, side: _PagedSide, cfg,
                                quantized: bool = False) -> None:
        """Mesh mode: place the side's pool per ``paged_cache_specs`` and
        build its shard-aware copy-on-write page-copy fn (each shard
        copies the — symmetrically reserved — template page onto its own
        slot of a per-shard destination vector; shards not admitting the
        row scribble their sink).  Single-host mode keeps the plain
        module-level copy."""
        if self.mesh is None:
            side.copy = (lambda pool, src, dst:
                         _copy_page(pool, int(src), int(dst[0])))
            return
        from jax.sharding import PartitionSpec as P
        from tfmesos_tpu.models.transformer import paged_cache_specs
        from tfmesos_tpu.parallel.sharding import data_axes
        specs = paged_cache_specs(cfg, self.mesh, quantized=quantized)
        side.pool = self._place(side.pool, specs)
        mesh = self.mesh
        da = data_axes(mesh)

        @partial(jax.jit, donate_argnums=0)
        def copy(pool, src, dst):
            def local(pool, src, dst):
                return jax.tree_util.tree_map(
                    lambda buf: buf.at[:, dst[0]].set(buf[:, src[0]]),
                    pool)
            return shard_map(local, mesh=mesh,
                             in_specs=(specs, P(), P(da)),
                             out_specs=specs, check_vma=False)(
                pool, src, dst)

        side.copy = (lambda pool, src, dst, _c=copy:
                     _c(pool, jnp.asarray([src], jnp.int32),
                        jnp.asarray(dst, jnp.int32)))

    def _init_prefix(self, prefix: np.ndarray) -> None:
        """Reserve pages for the shared prefix and prefill it once —
        into the target pool, and (speculative mode) into the draft's
        paged pool the same way: both sides then reference the prefix
        read-only, with a partially-filled last page kept as a
        copy-on-write TEMPLATE copied into each admitted row's first own
        page so row writes never touch shared state."""
        if prefix.ndim != 1 or prefix.size == 0:
            raise ValueError("prefix must be a non-empty 1-D token array")
        if prefix.size >= self.max_len:
            raise ValueError(f"prefix ({prefix.size} tokens) leaves no "
                             f"room under max_len ({self.max_len})")
        self.prefix_len = int(prefix.size)
        full = self.prefix_len // self.page_size
        tail = self.prefix_len % self.page_size
        n_reserve = full + (1 if tail else 0)
        sides = [(self.t_side, self.cfg, self.params)]
        if self.d_side is not None:
            sides.append((self.d_side, self.draft_cfg, self.draft_params))
        sharded = self.mesh is not None
        for side, cfg, params in sides:
            pages = [side.alloc.reserve_page() for _ in range(n_reserve)]
            # One prefill row PER SHARD, all with the same tokens and the
            # same (symmetric) local page ids: every shard's sub-pool gets
            # its own copy of the prefix, which its rows then reference
            # read-only.
            table = np.full((self.n_shards, side.np_max), side.sink,
                            np.int32)
            table[:, :n_reserve] = pages
            toks = np.tile(prefix[None], (self.n_shards, 1))

            @partial(jax.jit, donate_argnums=1)
            def prefill_prefix(params, pool, t, toks, cfg=cfg):
                cache = dict(pool, pages=t)
                _, cache = decode_step(cfg, params, cache, toks, 0,
                                       sharded=sharded, mesh=self.mesh)
                return {"k": cache["k"], "v": cache["v"]}

            side.pool = prefill_prefix(params, side.pool,
                                       jnp.asarray(table),
                                       jnp.asarray(toks))
            if tail:
                side.tail_template = pages[-1]
                side.shared_pages = pages[:-1]
            else:
                side.shared_pages = pages
            side.shared_len = len(side.shared_pages) * self.page_size

    # -- compiled shapes --------------------------------------------------

    def _host_read(self, x):
        """Replicate a jit output the HOST loop reads (tokens, commit
        counts): on a (possibly multi-process) mesh a sharded global
        array is not fully addressable from every host, and the loop
        must see identical values on every process."""
        if self.mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P()))

    def _sample(self, last, rids, steps):
        """[n, V] logits -> [n] int32 tokens; sampling keys are folded
        in-graph per (rid, step) so the host loop never dispatches
        per-row fold_ins and either PRNG key flavor works."""
        if self.temperature <= 0.0:
            return jnp.argmax(last.astype(jnp.float32), axis=-1).astype(
                jnp.int32)

        def one(l, r, s):
            key = jax.random.fold_in(jax.random.fold_in(self._rng, r), s)
            return sample_logits(l, key, self.temperature, self.top_k,
                                 self.top_p)

        return jax.vmap(one)(last, rids, steps)

    def _make_decode(self):
        """K decode steps fused into ONE dispatch (``lax.scan``): the host
        syncs a [rows, K] token block instead of one [rows] vector per
        token, so the per-dispatch + device-to-host round-trip cost —
        the dominant serving cost on remote-attached runtimes, and a real
        tax everywhere — amortizes over K tokens.  Stops and quota
        endings are detected at block granularity: in-block steps past a
        row's end compute garbage the host discards, and their cache
        writes land either inside the row's reservation-clamped own
        pages or on sink columns (the ensure() clamp at ``_Row.limit``
        guarantees allocations never exceed the admission reservation).
        Token streams are IDENTICAL across K: the scan body runs the
        same decode_step + per-(rid, step)-folded sample ops in the same
        order, only the host sync point moves.  ``multi_step=1`` is the
        classic per-token tick (a length-1 scan)."""
        sharded = self.mesh is not None
        K = self.multi_step
        max_len = self.max_len

        def block(params, pool, table, tok0, positions, rids, steps):
            def body(carry, _):
                pool, tok, pos, stp = carry
                cache = dict(pool, pages=table)
                logits, cache = decode_step(
                    self.cfg, params, cache, tok[:, None],
                    jnp.minimum(pos, max_len), sharded=sharded,
                    mesh=self.mesh)
                nxt = self._sample(logits[:, -1], rids, stp)
                pool = {"k": cache["k"], "v": cache["v"]}
                return (pool, nxt, pos + 1, stp + 1), nxt

            (pool, _, _, _), toks_all = jax.lax.scan(
                body, (pool, tok0, positions, steps), None, length=K)
            return pool, toks_all.T                         # [rows, K]

        if self._pipelined:
            # Device-resident pipelined blocks: tokens, positions, AND
            # steps ride the carry the previous dispatch returned, so a
            # steady-state block uploads NOTHING — the host merges fresh
            # admissions in via ``use_host`` (a cached device constant
            # while the dispatch set is unchanged) and reads block N's
            # tokens one block behind.  Carries clamp at max_len + K so
            # a parked (finished) row's garbage positions saturate
            # instead of overflowing int32 in a long-lived server; live
            # rows never reach the clamp (their reservations cap pos at
            # max_len).
            @partial(jax.jit, donate_argnums=1)
            def fn(params, pool, table, use_host, toks, positions, steps,
                   carry_tok, carry_pos, carry_steps, rids):
                tok0 = jnp.where(use_host, toks, carry_tok)
                pos0 = jnp.where(use_host, positions, carry_pos)
                stp0 = jnp.where(use_host, steps, carry_steps)
                pool, out = block(params, pool, table, tok0, pos0, rids,
                                  stp0)
                cap = max_len + K
                return (pool, self._host_read(out), out[:, -1],
                        jnp.minimum(pos0 + K, cap),
                        jnp.minimum(stp0 + K, cap))

            return fn

        if self.overlap:
            # Double-buffered blocks: rows in the previous dispatch chain
            # from its device-resident LAST token; the host never waits
            # on it before dispatching the next block.
            @partial(jax.jit, donate_argnums=1)
            def fn(params, pool, table, toks, prev, use_dev, positions,
                   rids, steps):
                merged = jnp.where(use_dev, prev[:, -1], toks)
                pool, out = block(params, pool, table, merged, positions,
                                  rids, steps)
                return pool, self._host_read(out)

            return fn

        @partial(jax.jit, donate_argnums=1)
        def fn(params, pool, table, toks, positions, rids, steps):
            pool, out = block(params, pool, table, toks, positions, rids,
                              steps)
            return pool, self._host_read(out)

        return fn

    def _make_spec_round(self):
        """Jitted speculative round: k batched draft steps over the
        draft's OWN paged pool (its page table fixed across the scan —
        the caller pre-ensures pages for the round's writes), then one
        ragged (k+1)-token target verify over the target pool.  Returns
        the commit candidates [rows, k+1] and each row's commit count.

        Greedy (temperature 0): candidates are the target's greedy
        tokens, count = leading draft==target run + 1.  Sampling:
        Leviathan rejection — proposal j draws with key fold(rid,
        step+j) (the SAME stream the non-speculative batcher uses, so a
        perfect draft reproduces its proposals), acceptance uses an
        independent salted fold, and the correction/bonus at the
        rejection index draws from norm(max(0, pt − pd)) with another
        salted fold — every draw a pure function of (rid, token index),
        hence invariant to row packing."""
        k = self.n_draft
        T, tk_, tp_ = self.temperature, self.top_k, self.top_p
        sharded = self.mesh is not None
        sampling = T > 0.0
        if sampling:
            from tfmesos_tpu.models.transformer import filter_logits

        def keyf(rid, s):
            return jax.random.fold_in(jax.random.fold_in(self._rng, rid),
                                      s)

        def body(params, pool, dparams, dpool, table, dtable, toks,
                 positions, rids, steps):
            b = toks.shape[0]

            def dstep(carry, j):
                dc, dtok, dpos = carry
                lg, dc = decode_step(self.draft_cfg, dparams,
                                     dict(dc, pages=dtable),
                                     dtok[:, None], dpos,
                                     sharded=sharded, mesh=self.mesh)
                dc = {"k": dc["k"], "v": dc["v"]}
                if not sampling:
                    nxt = jnp.argmax(lg[:, -1], -1).astype(jnp.int32)
                    return (dc, nxt, dpos + 1), (nxt, jnp.zeros(()))
                f = filter_logits(lg[:, -1], T, tk_, tp_)
                nxt = jax.vmap(
                    lambda fr, r, s: jax.random.categorical(
                        keyf(r, s + j), fr).astype(jnp.int32))(
                    f, rids, steps)
                return (dc, nxt, dpos + 1), (nxt, jax.nn.softmax(f, -1))

            # k+1 steps: the extra step writes the LAST proposal's K/V
            # at pos+k (its proposal is discarded) — otherwise a fully
            # accepted round advances past pos+k with that draft-cache
            # slot never written, and the draft conditions on a hole for
            # the rest of the request (silent acceptance-rate decay on
            # exactly the requests where the draft is best).
            (dpool, _, _), (drafts, pd) = jax.lax.scan(
                dstep, ({"k": dpool["k"], "v": dpool["v"]}, toks,
                        positions),
                jnp.arange(k + 1, dtype=jnp.int32))
            drafts = jnp.moveaxis(drafts, 0, 1)[:, :k]      # [rows, k]
            chunk = jnp.concatenate([toks[:, None], drafts], axis=1)
            cache = dict(pool, pages=table)
            lg, cache = decode_step(self.cfg, params, cache, chunk,
                                    positions, sharded=sharded,
                                    mesh=self.mesh)
            pool_out = {"k": cache["k"], "v": cache["v"]}
            if not sampling:
                g = jnp.argmax(lg, -1).astype(jnp.int32)    # [rows, k+1]
                return pool_out, dpool, g, greedy_accept_counts(drafts, g)

            pd = jnp.moveaxis(pd, 0, 1)[:, :k]              # [rows, k, V]
            pt = jax.nn.softmax(filter_logits(lg, T, tk_, tp_), -1)
            u = jax.vmap(lambda r, s: jax.vmap(
                lambda j: jax.random.uniform(
                    jax.random.fold_in(keyf(r, s + j), 1)))(
                jnp.arange(k, dtype=jnp.int32)))(rids, steps)
            # Accept/correct via the shared rejection math
            # (transformer.rejection_accept — same code path
            # speculative_generate's sampling_round runs).
            a, dist = rejection_accept(drafts, pd, pt, u)
            repl = jax.vmap(
                lambda dr, r, s, ar: jax.random.categorical(
                    jax.random.fold_in(keyf(r, s + ar), 2),
                    jnp.log(dr + 1e-20)).astype(jnp.int32))(
                dist, rids, steps, a)
            j = jnp.arange(k + 1, dtype=jnp.int32)[None]
            cand = jnp.concatenate(
                [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1)
            vals = jnp.where(j == a[:, None], repl[:, None], cand)
            return pool_out, dpool, vals, a + 1

        if not self.overlap:
            # multi_step>1 composes with synchronous speculation as R =
            # ceil(multi_step/(k+1)) rounds fused in ONE dispatch: each
            # round chains from the previous round's last-committed
            # token/positions IN-GRAPH (the same take_along_axis chain
            # the overlap carry uses), so the host syncs once per R
            # rounds.  Rows that finish (stop/quota) mid-dispatch keep
            # executing later rounds on device; their writes land on
            # sink-clamped table columns and the host discards their
            # tokens at commit — the same overrun argument the plain
            # multi_step path documents at _worst_pages.
            R = max(1, self._spec_rounds)

            @partial(jax.jit, donate_argnums=(1, 3))
            def fn(params, pool, dparams, dpool, table, dtable, toks,
                   positions, rids, steps):
                if R == 1:
                    pool_out, dpool_out, g, counts = body(
                        params, pool, dparams, dpool, table, dtable,
                        toks, positions, rids, steps)
                    return (pool_out, dpool_out, self._host_read(g),
                            self._host_read(counts))
                gs, ns = [], []
                for _ in range(R):
                    pool, dpool, g, counts = body(
                        params, pool, dparams, dpool, table, dtable,
                        toks, positions, rids, steps)
                    gs.append(g)
                    ns.append(counts)
                    last = jnp.maximum(counts - 1, 0)
                    toks = jnp.take_along_axis(
                        g, last[:, None], axis=1)[:, 0]
                    positions = positions + counts
                    steps = steps + counts
                # [R, rows, k+1] / [R, rows] — _step_spec commits
                # round-by-round so quota/stop truncation stays exact.
                return (pool, dpool, self._host_read(jnp.stack(gs)),
                        self._host_read(jnp.stack(ns)))

            return fn

        # Overlap variant: rows that were in the PREVIOUS round continue
        # from its DEVICE outputs — the last committed token is
        # prev_g[r, prev_nc-1], and positions/steps advance by prev_nc,
        # all computed in-graph (commit counts never round-trip to the
        # host before the next dispatch).  Freshly admitted rows take
        # host values; the merged positions/steps return as the carry
        # for round t+1.
        @partial(jax.jit, donate_argnums=(1, 3))
        def fn_ov(params, pool, dparams, dpool, table, dtable, toks,
                  positions, rids, steps, use_dev, prev_g, prev_nc,
                  prev_pos, prev_steps):
            last_idx = jnp.maximum(prev_nc - 1, 0)
            dev_tok = jnp.take_along_axis(prev_g, last_idx[:, None],
                                          axis=1)[:, 0]
            toks = jnp.where(use_dev, dev_tok, toks)
            positions = jnp.where(use_dev, prev_pos + prev_nc, positions)
            steps = jnp.where(use_dev, prev_steps + prev_nc, steps)
            pool_out, dpool_out, g, counts = body(
                params, pool, dparams, dpool, table, dtable, toks,
                positions, rids, steps)
            return (pool_out, dpool_out, self._host_read(g),
                    self._host_read(counts), self._host_read(positions),
                    self._host_read(steps))

        return fn_ov

    def _make_draft_chunk(self):
        """Jitted DRAFT prompt writer over the draft's paged pool: serves
        both the whole-prompt prefill (offset prefix_len — the prefix
        pages are shared, so only the prompt is written) and chunked
        prefill's per-chunk advance.  The caller passes a one-hot
        n_shards-row batch (``_one_hot_call``); one compile per chunk
        width."""
        sharded = self.mesh is not None

        @partial(jax.jit, donate_argnums=1)
        def fn(dparams, dpool, t, chunk, pos):
            cache = dict(dpool, pages=t)
            _, cache = decode_step(self.draft_cfg, dparams, cache, chunk,
                                   pos, sharded=sharded, mesh=self.mesh)
            return {"k": cache["k"], "v": cache["v"]}

        return fn

    def _one_hot_call(self, side: _PagedSide, row: int, chunk: np.ndarray):
        """(shard, [nd, w] tokens, [nd, np] table) for a per-row model
        call batched one row per mesh data shard: the admitted row's
        tokens and table ride its shard's slot; every other shard's slot
        is an all-sink dummy whose writes land on that shard's sink page
        (and whose sampled token is discarded).  With one shard this is
        exactly the old single-row call."""
        nd = self.n_shards
        s = side.alloc.shard_of(row)
        table = np.full((nd, side.np_max), side.sink, np.int32)
        table[s] = side.table_np()[row]
        toks = np.zeros((nd, chunk.shape[1]), np.int32)
        toks[s] = chunk[0]
        return s, jnp.asarray(toks), jnp.asarray(table)

    def _make_chunk_prefill(self):
        """Jitted one-chunk prefill: writes chunk tokens at a TRACED
        offset (so one compile serves every chunk of every request) and
        samples the first token when this chunk contains the prompt's
        last position (cap_idx in range; callers ignore it otherwise).
        Batched one row per mesh data shard (``_one_hot_call``); returns
        the [nd] sampled-token vector, the caller indexes its shard."""
        sharded = self.mesh is not None

        @partial(jax.jit, donate_argnums=1)
        def fn(params, pool, table, chunk, pos, cap_idx, rid):
            cache = dict(pool, pages=table)
            logits, cache = decode_step(self.cfg, params, cache, chunk,
                                        pos, sharded=sharded,
                                        mesh=self.mesh)
            cap = jnp.clip(cap_idx, 0, chunk.shape[1] - 1)
            last = jnp.take_along_axis(
                logits, cap[:, None, None], axis=1)[:, 0]
            nxt = self._sample(last, rid, jnp.zeros_like(rid))
            return {"k": cache["k"], "v": cache["v"]}, self._host_read(nxt)

        return fn

    def _make_fused_step(self):
        """ONE jitted program per tick over the ragged [decode rows |
        prefill chunk slots] layout: a budgeted batch of chunk slots
        (each slot = one still-filling row's next ``prefill_chunk``
        tokens at its own traced offset — the SAME chunk-writer ops
        :meth:`_make_chunk_prefill` runs, batched [S, c] instead of
        one-hot) followed by the decode block's K-step scan, threading
        one donated pool through both.  Decode rows therefore never
        stall behind a separate chunk dispatch, and the host syncs ONE
        result per tick ([rows, K] decode tokens + [S] first-token
        samples) instead of two.  Slot writes land on each slot row's
        own pages (dummy slots: all-sink tables, sampled token
        discarded), decode writes behave exactly as in
        :meth:`_make_decode` — same ops, same (rid, step) sample folds,
        so token streams are identical to the phase-split tick.  One
        compile per (decode table width, slot-count bucket) pair."""
        sharded = self.mesh is not None
        K = self.multi_step
        max_len = self.max_len

        @partial(jax.jit, donate_argnums=1)
        def fn(params, pool, table, toks, positions, rids, steps,
               ctable, chunks, cpos, caps, crids):
            # Chunk slots first (mirroring the phase-split tick's
            # chunk-then-block order — the sets touch disjoint pages,
            # but the donated pool threads through in program order).
            cache = dict(pool, pages=ctable)
            logits, cache = decode_step(self.cfg, params, cache, chunks,
                                        cpos, sharded=sharded,
                                        mesh=self.mesh)
            pool = {"k": cache["k"], "v": cache["v"]}
            cap = jnp.clip(caps, 0, chunks.shape[1] - 1)
            last = jnp.take_along_axis(
                logits, cap[:, None, None], axis=1)[:, 0]
            first = self._sample(last, crids, jnp.zeros_like(crids))

            def body(carry, _):
                pool, tok, pos, stp = carry
                cache = dict(pool, pages=table)
                lg, cache = decode_step(
                    self.cfg, params, cache, tok[:, None],
                    jnp.minimum(pos, max_len), sharded=sharded,
                    mesh=self.mesh)
                nxt = self._sample(lg[:, -1], rids, stp)
                pool = {"k": cache["k"], "v": cache["v"]}
                return (pool, nxt, pos + 1, stp + 1), nxt

            (pool, _, _, _), toks_all = jax.lax.scan(
                body, (pool, toks, positions, steps), None, length=K)
            return (pool, self._host_read(toks_all.T),
                    self._host_read(first))

        return fn

    def _fused_slot_buckets(self) -> List[int]:
        """Every chunk-slot count the fused dispatch can pad to (powers
        of two up to ``rows`` — at most ``rows`` rows can be filling),
        for warmup and the live dispatch's shared bucketing."""
        return sorted({self._pow2(s) for s in range(1, self.rows + 1)})

    def _prefill_fn(self, width: int):
        """Jitted prefill at one padded-width bucket, batched one row per
        mesh data shard (``_one_hot_call``)."""
        if width not in self._prefill_fns:
            sharded = self.mesh is not None

            @partial(jax.jit, donate_argnums=1)
            def fn(params, pool, table, prompt, length, rid):
                cache = dict(pool, pages=table)
                # With a shared prefix the chunk prefills AT OFFSET
                # prefix_len: rope positions, causal bounds, and page
                # writes all follow (token tt of the chunk sees cache
                # positions <= prefix_len + tt).
                logits, cache = decode_step(self.cfg, params, cache, prompt,
                                            self.prefix_len,
                                            sharded=sharded, mesh=self.mesh)
                last = jnp.take_along_axis(
                    logits, (length - 1)[:, None, None], axis=1)[:, 0]
                nxt = self._sample(last, rid, jnp.zeros_like(rid))
                return {"k": cache["k"], "v": cache["v"]}, \
                    self._host_read(nxt)

            self._prefill_fns[width] = fn
        return self._prefill_fns[width]

    # -- host-side bookkeeping --------------------------------------------

    def _worst_pages(self, req: Request) -> tuple:
        """Worst-case OWN pages beyond the shared prefix pages, per side,
        plus the absolute position cap the reservation covers:
        ``(target, draft, need_len)`` (draft 0 without speculative
        mode)."""
        width = -(-req.prompt.size // self.prefill_bucket) * \
            self.prefill_bucket
        need_len = self.prefix_len + max(
            width, req.prompt.size + req.max_new_tokens - 1)
        if self.draft_cfg is not None:
            # A speculative round at the final position still verifies a
            # (k+1)-token chunk: its writes overshoot by up to n_draft
            # (and the draft's k+1 scan steps write the same positions).
            need_len += self.n_draft
        if self.overlap or self._pipelined:
            if self.draft_cfg is not None:
                # Speculative overlap: ANY ending (quota included —
                # commit counts are decided on device) surfaces one
                # ROUND late, and the overshoot round writes up to
                # n_draft+1 positions past the end.
                need_len += self.n_draft + 1
            elif req.stop_token is not None:
                # A stop is detected one block late (overlap and
                # pipelined modes alike): reserve one position
                # past the stop so the overshoot write can land in an own
                # page.  With multi_step > 1 the overshoot can reach K-1
                # further positions (and quota overruns up to K-1 exist
                # too) — those are NOT reserved here: the ensure() clamp
                # at _Row.limit keeps allocations within this
                # reservation, and writes past it land on sink columns.
                need_len += 1
        if need_len > self.max_len:
            raise ValueError(
                f"request needs {need_len} cache positions (prefix "
                f"{self.prefix_len} + prompt {req.prompt.size} padded to "
                f"{width}, plus {req.max_new_tokens} new tokens) > "
                f"max_len ({self.max_len})")
        wt = -(-(need_len - self.t_side.shared_len) // self.page_size)
        wd = 0
        if self.d_side is not None:
            wd = -(-(need_len - self.d_side.shared_len) // self.page_size)
        return wt, wd, need_len

    def _req_digests(self, req: Request) -> list:
        """Chain digests of ``req``'s complete page-aligned prompt
        chunks (memoized on the request, keyed by the chunk geometry so
        a request replayed into a differently-paged batcher rehashes —
        without the memo, a request waiting for a row would rehash its
        prompt every admission tick)."""
        pc = self._pcache
        key = (pc.page_size, pc.first, pc.seed)
        memo = getattr(req, "_pfx_digests", None)
        if memo is None or memo[0] != key:
            memo = (key, _ph.prompt_digests(req.prompt, pc.page_size,
                                            pc.first, pc.seed))
            req._pfx_digests = memo
        return memo[1]

    def _prefix_plan(self, req: Request, shard: int,
                     max_nodes: Optional[int] = None
                     ) -> Optional[_PrefixPlan]:
        """The longest USABLE cached prefix for ``req`` on ``shard``
        (capped at ``max_nodes`` — _admit_row retries shallower when a
        deep plan doesn't fit the shard's headroom): the trie match,
        trimmed until the uncached tail's padded prefill window fits
        inside the page table (``np_max * page_size`` positions — the
        allocation itself is clamped at the reservation by
        _admit_cached's ensure, and pad writes past it land in
        reserved-but-unread positions or on sink columns, exactly like
        the cold path's prompt padding) and, in chunked mode, starts on
        the chunk grid.  A page-aligned full hit keeps its deepest page
        and marks it COW: the one-token logits chunk rewrites position
        E-1 inside a private copy."""
        digs = self._req_digests(req)
        if not digs:
            return None
        nodes = self._pcache.match(shard, digs)
        if not nodes:
            return None
        E = self.prefix_len + int(req.prompt.size)
        sl = self.t_side.shared_len
        ps, bucket = self.page_size, self.prefill_bucket
        n = len(nodes)
        if max_nodes is not None:
            n = min(n, max_nodes)
            if not n:
                return None
        if self.prefill_chunk is not None:
            c = self.prefill_chunk
            while n and (sl + n * ps > E - 1
                         or (sl + n * ps - self.prefix_len) % c):
                n -= 1
            return (_PrefixPlan(nodes[:n], False, sl + n * ps)
                    if n else None)
        while n:
            cow = sl + n * ps >= E
            ts = E - 1 if cow else sl + n * ps
            w = -(-(E - ts) // bucket) * bucket
            if ts + w <= self.np_max * ps:
                return _PrefixPlan(nodes[:n], cow, ts)
            n -= 1
        return None

    def _admit_row(self, free_rows: List[int], active: Dict[int, _Row],
                   wt: int, wd: int, req: Request,
                   use_cache: bool = True) -> tuple:
        """Pop a free row whose shard's pool(s) can take both worst-case
        reservations, preferring the shard with the longest cached
        prefix for ``req`` (pages are shard-pinned, so a hit is only a
        hit on its own shard), then the most target headroom (load
        balance across mesh data shards; with one shard and no cache
        this is just a headroom check).  Returns ``(row, plan)``;
        ``(None, None)`` means wait for in-flight rows to release
        pages.  Raises when some free row's shard has NO in-flight work
        and still can't fit — waiting would deadlock."""
        best = None
        empty_shard = None
        by_shard: Dict[int, tuple] = {}     # s -> (ok, headroom, plan)
        for i, r in enumerate(free_rows):
            s = self.t_side.alloc.shard_of(r)
            if s not in by_shard:        # headroom is a per-SHARD fact
                ht = self.t_side.headroom(active,
                                          lambda x: x.worst_pages, s)
                plan = (self._prefix_plan(req, s)
                        if self._pcache is not None and use_cache
                        else None)
                hd = (self.d_side.headroom(
                          active, lambda x: x.worst_draft, s)
                      if self.d_side is not None else None)
                while True:
                    save = plan.save if plan is not None else 0
                    zref = (sum(1 for n in plan.nodes if n.ref == 0)
                            if plan is not None else 0)
                    # headroom() counts zero-ref cached pages as
                    # reclaimable, but accepting THIS plan references
                    # its nodes — they can no longer be evicted to
                    # satisfy the same admission.  Discounting wt by
                    # plan.save AND counting those pages reclaimable
                    # would double-count them and over-admit (a "page
                    # pool exhausted" crash out of the serve loop,
                    # exactly what reservations exist to prevent).
                    ok = (wt - save) <= ht - zref
                    if ok and hd is not None:
                        # Twin-pool plans save the SAME page count on
                        # the draft side (coupled nodes), and the same
                        # zero-ref double-count adjustment applies.
                        ok = (wd - save) <= hd - zref
                    if ok or plan is None:
                        break
                    # A deep plan that doesn't fit (the COW full hit
                    # needs a fresh copy page ON TOP of referencing
                    # every reclaimable cached page) must not condemn
                    # the request: retry shallower — down to the plain
                    # cold admission, which evicts the unused cached
                    # pages on demand.
                    depth = len(plan.nodes) - 1
                    plan = (self._prefix_plan(req, s, max_nodes=depth)
                            if depth else None)
                by_shard[s] = (ok, ht, plan)
            ok, ht, plan = by_shard[s]
            if ok:
                key = (plan.save if plan is not None else 0, ht)
                if best is None or key > best[1]:
                    best = (i, key, plan)
            elif not any(self.t_side.alloc.shard_of(rr) == s
                         for rr in active):
                empty_shard = s
        if best is not None:
            return free_rows.pop(best[0]), best[2]
        if empty_shard is not None:
            s = empty_shard
            free_t = self.t_side.alloc.free_count(s)
            if self._pcache is not None:
                free_t += self._pcache.reclaimable(s)
            free_d = (0 if self.d_side is None
                      else self.d_side.alloc.free_count(s))
            raise RuntimeError(
                f"request needs {wt} target pages (+ {wd} draft) but "
                f"shard {s} only has {free_t} target / {free_d} draft "
                f"free with nothing in flight to wait for — raise "
                f"n_pages")
        return None, None

    # -- incremental (online) submission ----------------------------------

    def validate(self, req) -> None:
        """Raise ``ValueError`` if ``req`` (a :class:`Request` or
        :class:`Prefilled`) can never be served by this batcher
        (prefix + padded prompt + new tokens exceed max_len; for an
        import, an artifact whose geometry does not match this pool).
        Online front doors call this at ingress so an un-servable
        request is rejected immediately instead of via run()'s
        drain-then-raise path."""
        if isinstance(req, Prefilled):
            self._worst_pages(req.request)
            self._validate_artifact(req.artifact, req.request)
            return
        self._worst_pages(req)

    # -- ahead-of-time warmup ----------------------------------------------

    def _decode_widths(self) -> List[int]:
        """Every table width ``bucket_width`` can hand the batched
        step — one jit trace each.  Derived by enumerating occupancies
        through the SAME ``_PagedSide.width_for`` the live dispatch
        buckets with, so warmup can never drift from the widths the
        serve loop actually requests."""
        np_max = self.t_side.np_max
        return sorted({_PagedSide.width_for(occ, np_max)
                       for occ in range(1, np_max + 1)})

    def _prefill_widths(self) -> List[int]:
        """Every padded prompt width non-chunked admission can dispatch:
        ``_admit_dispatch`` pads prompts to multiples of
        ``prefill_bucket``, and ``_worst_pages`` admits only widths
        whose reservation (``prefix_len + width`` at minimum) fits
        ``max_len`` — one jit trace each, mirroring the linear
        ``_prefill_fns`` cache the live path fills lazily.  (Chunked
        mode has ONE chunk width and doesn't use this.)"""
        b = self.prefill_bucket
        cap = ((self.max_len - self.prefix_len) // b) * b
        return list(range(b, cap + 1, b)) or [b]

    def warmup(self, decode: bool = True,
               prefill: bool = True) -> Dict[str, Any]:
        """Compile every jitted entry point this batcher's serving mode
        dispatches — admission prefill at every reachable padded prompt
        width (or the single chunked/tail prefill writer), the batched
        decode block at every bucketed table width (or the speculative
        round + draft chunk writer with a draft), and the disaggregated
        KV export/import scatter where the mode supports it — against
        dummy all-sink shapes, and block until the executables are
        built.  ``decode=False`` skips the per-width decode/spec-round
        blocks: a prefill-ROLE fleet replica never decodes, and
        compiling log2(np_max) executables it cannot dispatch would
        only lengthen its warming window on every elastic relaunch.
        ``prefill=False`` is the mirror for decode-ROLE replicas —
        they only import exported KV (rows enter decode directly;
        plain generates route to the unified tier), so the per-width
        prefill/tail/draft-chunk compiles are skipped the same way.

        Every write a warmup call dispatches lands on the sink page
        (the table is all-sink), so no live row, shared-prefix page, or
        prefix-cache state is touched: a warmed batcher's outputs are
        bit-identical to a cold one's.  Call at boot, before
        :meth:`serve`/:meth:`run` — moving first-request compilation
        off the serving path is what the fleet's ``warming`` replica
        state exists for (a replica only advertises itself routable
        once this returns).  Coverage is every first-request shape the
        configured mode can dispatch (a mixed spec table-width pair can
        still compile lazily); non-chunked prefill has one trace per
        reachable width, so a long-``max_len`` pool that cares about
        warmup time should serve with ``prefill_chunk`` (one trace).

        Returns ``{"compiled": [...], "seconds": float}``."""
        t0 = time.perf_counter()
        compiled: List[str] = []
        with self._export_lock:
            if self._loop_active:
                raise RuntimeError(
                    "warmup() cannot run while the batcher's serve loop "
                    "is active — warm at boot, before serve()/run()")
            nd = self.n_shards
            zrow = jnp.asarray(np.zeros((nd,), np.int32))

            def sink_table(side):
                return jnp.asarray(np.full((nd, side.np_max), side.sink,
                                           np.int32))

            if prefill and self._chunk_prefill is None:
                for w in self._prefill_widths():
                    self.pool, tok = self._prefill_fn(w)(
                        self.params, self.pool, sink_table(self.t_side),
                        jnp.asarray(np.zeros((nd, w), np.int32)),
                        jnp.asarray(np.ones((nd,), np.int32)), zrow)
                    np.asarray(tok)
                    compiled.append(f"prefill[{w}]")
            cfn = self._chunk_prefill or self._tail_prefill
            if prefill and cfn is not None:
                # The chunk loop always feeds the fixed chunk width,
                # but the prefix-cache TAIL path dispatches this same
                # callable at every multiple-of-bucket tail width (one
                # retrace each, like the live path) — cover them all,
                # or a warmed replica's first multi-bucket warm-cache
                # hit pays a live XLA trace.
                # Session resume dispatches the same writer at every
                # tail width too, so a KV tier widens the set the same
                # way the prefix cache does.
                tiered = (self.kv_tier is not None
                          and self.kv_tier_bypass_reason is None)
                widths = (self._prefill_widths()
                          if self._pcache is not None or tiered
                          else [self.prefill_chunk or self.prefill_bucket])
                for w in widths:
                    self.pool, tok = cfn(
                        self.params, self.pool, sink_table(self.t_side),
                        jnp.asarray(np.zeros((nd, w), np.int32)),
                        jnp.asarray(0, jnp.int32),
                        jnp.asarray(np.full((nd,), -1, np.int32)), zrow)
                    np.asarray(tok)
                    compiled.append(f"chunk_prefill[{w}]")
            zt = jnp.asarray(np.zeros((self.rows,), np.int32))
            no_host = jnp.asarray(np.zeros((self.rows,), bool))
            for w in self._decode_widths() if decode else ():
                table = jnp.asarray(np.full((self.rows, w),
                                            self.t_side.sink, np.int32))
                if self.draft_cfg is not None:
                    dtable = jnp.asarray(np.full(
                        (self.rows, w), self.d_side.sink, np.int32))
                    parked = jnp.asarray(np.full(
                        (self.rows,), self.max_len, np.int32))
                    if self.overlap:
                        k1 = self.n_draft + 1
                        carry = (jnp.zeros((self.rows, k1), jnp.int32),
                                 zt, zt, zt)
                        (self.pool, self.d_side.pool, g, nc, _,
                         _) = self._spec_round(
                            self.params, self.pool, self.draft_params,
                            self.d_side.pool, table, dtable, zt, parked,
                            zt, zt, no_host, *carry)
                    else:
                        (self.pool, self.d_side.pool, g,
                         nc) = self._spec_round(
                            self.params, self.pool, self.draft_params,
                            self.d_side.pool, table, dtable, zt, parked,
                            zt, zt)
                    np.asarray(nc)
                    compiled.append(f"spec_round[{w}]")
                elif self._pipelined:
                    self.pool, out, _, _, _ = self._decode(
                        self.params, self.pool, table, no_host, zt, zt,
                        zt, zt, zt, zt, zt)
                    np.asarray(out)
                    compiled.append(f"decode[{w}]")
                elif self.overlap:
                    prev = jnp.zeros((self.rows, self.multi_step),
                                     jnp.int32)
                    self.pool, out = self._decode(
                        self.params, self.pool, table, zt, prev, no_host,
                        zt, zt, zt)
                    np.asarray(out)
                    compiled.append(f"decode[{w}]")
                else:
                    self.pool, out = self._decode(
                        self.params, self.pool, table, zt, zt, zt, zt)
                    np.asarray(out)
                    compiled.append(f"decode[{w}]")
                if self._fused:
                    # The fused tick's (decode width x slot bucket)
                    # grid — every shape _step_fused can dispatch.
                    c = self.prefill_chunk
                    for S in self._fused_slot_buckets():
                        ctable = jnp.asarray(np.full(
                            (S, self.t_side.np_max), self.t_side.sink,
                            np.int32))
                        self.pool, out, first = self._fused_step(
                            self.params, self.pool, table, zt, zt, zt,
                            zt, ctable,
                            jnp.asarray(np.zeros((S, c), np.int32)),
                            jnp.asarray(np.zeros((S,), np.int32)),
                            jnp.asarray(np.full((S,), -1, np.int32)),
                            jnp.asarray(np.zeros((S,), np.int32)))
                        np.asarray(out)
                        np.asarray(first)
                        compiled.append(f"fused[{w},{S}]")
            if prefill and self.draft_cfg is not None:
                # Chunked mode feeds the draft the fixed chunk width;
                # non-chunked admission feeds it the PADDED PROMPT
                # width — every multiple-of-bucket trace the live path
                # would fill lazily.
                dws = ([self.prefill_chunk] if self._chunk_prefill
                       is not None else self._prefill_widths())
                for w in dws:
                    self.d_side.pool = self._draft_chunk(
                        self.draft_params, self.d_side.pool,
                        sink_table(self.d_side),
                        jnp.asarray(np.zeros((nd, w), np.int32)),
                        jnp.asarray(self.prefix_len, jnp.int32))
                    jax.block_until_ready(self.d_side.pool)
                    compiled.append(f"draft_chunk[{w}]")
            for side in (self.t_side, self.d_side):
                if side is None:
                    continue
                if side.tail_template is not None or side.pcache is not None:
                    dst = np.full((nd,), side.sink, np.int32)
                    side.pool = side.copy(side.pool, side.sink, dst)
                    jax.block_until_ready(side.pool)
                    compiled.append("page_copy")
            if self.n_shards == 1:
                # The disaggregated surface (export gather + import
                # scatter) — compiled at the one-page count; larger
                # transfers trace lazily per page count.  A KV tier
                # buckets its session park/resume transfers to
                # power-of-two counts, so warm those too — log2(np_max)
                # traces, and a resumed turn's TTFT never carries one.
                # A speculative batcher's exports carry the DRAFT
                # pool's paired payload, so its gather/scatter pair is
                # warmed at the same counts.
                counts = [1]
                if self.kv_tier is not None \
                        and self.kv_tier_bypass_reason is None:
                    counts = sorted({self._pow2(c) for c in
                                     range(1, self.t_side.np_max + 1)})
                for c in counts:
                    ids = jnp.asarray([self.t_side.sink] * c, jnp.int32)
                    payload = _gather_pages(self.pool, ids)
                    jax.block_until_ready(payload)
                    self.pool = _install_pages(self.pool, payload, ids)
                    jax.block_until_ready(self.pool)
                    if self.d_side is not None:
                        dids = jnp.asarray([self.d_side.sink] * c,
                                           jnp.int32)
                        dpayload = _gather_pages(self.d_side.pool, dids)
                        jax.block_until_ready(dpayload)
                        self.d_side.pool = _install_pages(
                            self.d_side.pool, dpayload, dids)
                        jax.block_until_ready(self.d_side.pool)
                    compiled.append(f"kv_export_import[{c}]")
        return {"compiled": compiled,
                "seconds": round(time.perf_counter() - t0, 3)}

    # -- disaggregated serving: KV export / import -------------------------

    def _check_disagg_mode(self, what: str) -> None:
        # Speculative batchers compose: their exports carry the draft
        # pool's paired payload (dk/dv + the ``draft`` header) and the
        # spec sampler state is already the (rid, step, tokens) triple.
        if self.n_shards != 1:
            raise ValueError(f"{what} requires a single-shard pool "
                             f"(mesh data shards pin pages locally)")

    def kv_headroom(self) -> int:
        """Free KV pool pages this batcher could hand to a new request
        right now: the free list plus zero-ref cached prefix pages (the
        allocator reclaims those on demand).  A heartbeat-grade load
        signal — it does NOT subtract in-flight rows' unallocated
        reservations — which decode-tier routing uses to place imported
        prefills where the pages are."""
        free = self.t_side.alloc.free_count()
        if self._pcache is not None:
            free += sum(self._pcache.reclaimable(s)
                        for s in range(self.n_shards))
        return free

    def export_kv(self, request: Request) -> dict:
        """PREFILL-ONLY execution: run ``request``'s prompt through this
        batcher's (chunked) prefill on a borrowed row and return its
        paged-KV state as a compact host artifact — per-layer page
        buffers for every position past the shared prefix (int8 pools
        export values AND scales bit-exactly), page-table/geometry
        metadata, and the sampler state (first token, the ``rid`` whose
        in-graph key folds produced it).  The row's pages are released
        before returning; a matching batcher imports the artifact with
        ``submit(request, prefilled=artifact)`` and enters decode
        directly, token-for-token equivalent to admitting the request
        here.  Prefix-cache hits apply (a warm shared system prompt
        prefills only its tail) and the freshly prefilled pages are
        published for later exports.

        This is the prefill-role replica's serving surface: it must not
        run concurrently with this batcher's own serve loop (exports
        borrow row 0); concurrent export_kv calls serialize."""
        if not isinstance(request, Request):
            raise TypeError(f"export_kv() takes a Request, got "
                            f"{type(request).__name__}")
        self._check_disagg_mode("export_kv")
        with self._export_lock:
            if self._loop_active:
                raise RuntimeError(
                    "export_kv cannot run concurrently with this "
                    "batcher's serve loop (prefill-role batchers never "
                    "start one)")
            wt, wd, need = self._worst_pages(request)
            self._tier_promote(request)
            active: Dict[int, _Row] = {}
            row, plan = self._admit_row([0], active, wt, wd, request)
            assert row == 0     # nothing in flight: fit, or _admit_row raised
            rid = self._next_rid
            self._next_rid += 1
            try:
                res = self._admit_dispatch(row, rid, request, wt, wd,
                                           need, active, plan)
                state = active[row]
                if res is not None:
                    _, st, tok, s = res
                    st.t_first = time.perf_counter()
                    first = int(np.asarray(tok)[s])
                    st.last = first
                    st.out = [first]
                else:
                    # Chunked mode: drive the per-tick chunk writer to
                    # completion (no decode interleaves here — the whole
                    # point of a dedicated prefill tier).
                    while not state.decoding:
                        if self._advance_prefill(active) is not None:
                            break
                return self._export_row(row, state)
            finally:
                # Unconditional: a failed dispatch may have allocated
                # pages before raising, and _finish releases safely
                # even when the row never became active.
                self._finish(row, active, [])

    @staticmethod
    def _pow2(n: int) -> int:
        """Smallest power of two >= n (the tier transfer bucket: the
        gather/scatter jits trace per page count, and bucketing bounds
        the compile set at log2 like the decode-table widths)."""
        return 1 << max(0, int(n) - 1).bit_length()

    def _draft_geom(self) -> Dict[str, Any]:
        """The draft-side geometry contract: stamped on every export's
        ``draft`` header and checked field-for-field at every
        import/resume site — ONE source, so a new header field cannot
        be added and forgotten in a validator.  (``_tier_geom``'s
        draft sub-dict is deliberately different: spilled PAGES need
        the dtype and not n_draft.)"""
        return {"n_layers": int(self.draft_cfg.n_layers),
                "kv_heads": int(self.draft_cfg.kv_heads),
                "head_dim": int(self.draft_cfg.head_dim),
                "quantized": isinstance(self.d_side.pool["k"], QTensor),
                "n_draft": int(self.n_draft)}

    def _side_page_export(self, side: _PagedSide, pool, row: int,
                          n: int, pad_pow2: bool):
        """Gather ``row``'s pages covering [shared_len, shared_len +
        n*page_size) from ``pool`` to host — one side of an export.
        ``pad_pow2`` buckets the gather's page count to a power of two
        (padding with sink reads, sliced off host-side)."""
        ns = len(side.shared_pages)
        ids = np.asarray(side.table_np()[row, ns:ns + n], np.int32)
        if pad_pow2:
            m = self._pow2(n)
            if m > n:
                ids = np.concatenate(
                    [ids, np.full((m - n,), side.sink, np.int32)])
        kv = _gather_pages(pool, jnp.asarray(ids))
        if pad_pow2 and len(ids) > n:
            kv = jax.tree_util.tree_map(lambda a: a[:, :n], kv)
        return kv

    def _export_row(self, row: int, state: _Row,
                    pad_pow2: bool = False,
                    final: bool = False) -> dict:
        """Snapshot ``row``'s post-prefill KV into a host artifact: the
        pages covering absolute positions [shared_len, pos) — cached
        prefix pages and own pages alike, in table order — pulled to
        host in one gather.  A speculative batcher's artifact carries
        the DRAFT pool's paired payload over the same positions
        (``dk``/``dv`` + the ``draft`` geometry header), so a spec row
        moves whole.  Shared-prefix pages are NOT exported: a
        same-``prefix`` importer already holds identical ones (both
        sides prefilled the same tokens with the same params).
        ``pad_pow2`` buckets the GATHER's page count to a power of two
        (padding with sink reads, sliced off host-side) so the tier's
        park path dispatches log2(np_max) compiled gathers instead of
        one per exact count; the artifact itself is unchanged.

        ``final=True`` exports a FINISHED row at its COMMITTED
        boundary: the lagged decode modes (overlap/pipelined, spec
        rounds mid-flight) advance ``pos``/``step`` at dispatch, so a
        finished row's host view can overshoot the committed stream by
        the in-flight block — but every position below
        ``prefix + prompt + len(out) - 1`` was written exactly once
        with the true token sequence (positions only move forward), so
        clamping there exports exactly the resumable state.  This is
        what lets session parking work in every decode mode instead of
        silently missing cold in the lagged ones."""
        side = self.t_side
        ps = self.page_size
        E = state.pos
        step = int(state.step)
        toks = [int(t) for t in state.out]
        if final:
            step = len(toks)
            E = self.prefix_len + int(state.req.prompt.size) + step - 1
        n = -(-(E - side.shared_len) // ps)
        kv = self._side_page_export(side, self.pool, row, n, pad_pow2)
        quantized = isinstance(self.pool["k"], QTensor)
        art = {
            "version": 1,
            "page_size": ps,
            "prefix_len": self.prefix_len,
            "shared_len": side.shared_len,
            "pos": int(E),
            "prompt_len": int(state.req.prompt.size),
            "first_token": int(state.out[0]),
            # Mid-stream sampler state: a SUSPENDED row carries the
            # tokens it already emitted (step > 1) so the importer
            # resumes exactly where this row stopped; a fresh prefill
            # export is the step-1 degenerate case.  For speculative
            # rows this triple is the whole spec sampler state too:
            # draft proposals and acceptance draws are pure
            # per-(rid, step+j) key folds — no separate draft rng
            # position exists to carry.
            "step": step,
            "tokens": toks,
            "rid": int(state.rid),
            "quantized": quantized,
            "model": {"n_layers": int(self.cfg.n_layers),
                      "kv_heads": int(self.cfg.kv_heads),
                      "head_dim": int(self.cfg.head_dim)},
        }
        if quantized:
            art["k"] = np.asarray(kv["k"].values)
            art["k_scales"] = np.asarray(kv["k"].scales)
            art["v"] = np.asarray(kv["v"].values)
            art["v_scales"] = np.asarray(kv["v"].scales)
        else:
            art["k"] = np.asarray(kv["k"])
            art["v"] = np.asarray(kv["v"])
        if self.d_side is not None:
            # The paired draft-side payload: same positions, the draft
            # pool's pages (draft shared_len equals the target's — both
            # sides prefilled the same prefix at the same page size).
            dkv = self._side_page_export(self.d_side, self.d_side.pool,
                                         row, n, pad_pow2)
            art["draft"] = self._draft_geom()
            dquant = art["draft"]["quantized"]
            if dquant:
                art["dk"] = np.asarray(dkv["k"].values)
                art["dk_scales"] = np.asarray(dkv["k"].scales)
                art["dv"] = np.asarray(dkv["v"].values)
                art["dv_scales"] = np.asarray(dkv["v"].scales)
            else:
                art["dk"] = np.asarray(dkv["k"])
                art["dv"] = np.asarray(dkv["v"])
        return art

    def _validate_artifact(self, art: dict, req: Request) -> None:
        """Reject an import whose artifact cannot drop into THIS pool
        bit-exactly — every mismatch is a loud ``ValueError`` (the
        fleet's bad_request), never a silently wrong decode."""
        self._check_disagg_mode("submit(prefilled=...)")
        if art.get("version") != 1:
            raise ValueError(f"unknown KV artifact version "
                             f"{art.get('version')!r}")
        quantized = isinstance(self.pool["k"], QTensor)
        for key, want in (("page_size", self.page_size),
                          ("prefix_len", self.prefix_len),
                          ("shared_len", self.t_side.shared_len),
                          ("quantized", quantized)):
            if art.get(key) != want:
                raise ValueError(
                    f"KV artifact {key} {art.get(key)!r} does not match "
                    f"this batcher's {want!r}")
        model = art.get("model") or {}
        for key, want in (("n_layers", int(self.cfg.n_layers)),
                          ("kv_heads", int(self.cfg.kv_heads)),
                          ("head_dim", int(self.cfg.head_dim))):
            if model.get(key) != want:
                raise ValueError(
                    f"KV artifact model {key} {model.get(key)!r} does "
                    f"not match this config's {want}")
        # Mid-stream (suspended) artifacts carry step/tokens; a fresh
        # prefill export is step 1.  Every inconsistency is a loud
        # rejection — resuming from mismatched state would be a
        # silently wrong stream, the one failure mode this surface
        # must never have.
        try:
            step = int(art.get("step", 1))
        except (TypeError, ValueError):
            raise ValueError(f"KV artifact step {art.get('step')!r} is "
                             f"not an int") from None
        if step < 1:
            raise ValueError(f"KV artifact step {step} must be >= 1")
        toks = art.get("tokens")
        if step > 1 or toks is not None:
            if not isinstance(toks, (list, tuple)) or len(toks) != step:
                raise ValueError(
                    f"KV artifact tokens must list exactly step "
                    f"({step}) emitted tokens, got {toks!r}")
            if int(toks[0]) != int(art.get("first_token", -1)):
                raise ValueError("KV artifact tokens[0] does not match "
                                 "its first_token")
        if step > 1:
            if step >= req.max_new_tokens:
                raise ValueError(
                    f"suspended KV artifact already emitted {step} of "
                    f"{req.max_new_tokens} tokens — a finished request "
                    f"is never suspended")
            if req.stop_token is not None \
                    and int(toks[-1]) == int(req.stop_token):
                raise ValueError("suspended KV artifact ends at the "
                                 "stop token — nothing to resume")
        E = art.get("pos")
        if E != self.prefix_len + int(req.prompt.size) + step - 1 \
                or art.get("prompt_len", -1) != int(req.prompt.size):
            raise ValueError(
                f"KV artifact covers {E!r} positions; this request needs "
                f"prefix {self.prefix_len} + prompt {req.prompt.size} "
                f"(+ {step - 1} resumed tokens)")
        n = -(-(E - self.t_side.shared_len) // self.page_size)
        pool_k = self.pool["k"].values if quantized else self.pool["k"]
        self._check_payload_arrays(art, quantized, n, self.cfg, pool_k)
        self._validate_artifact_draft(art, n, step)

    def _check_payload_arrays(self, art: dict, quantized: bool, n: int,
                              mcfg, pool_k, prefix: str = "") -> None:
        """ONE shape/dtype contract for one side's page payload:
        ``prefix`` '' checks ``k``/``v`` (+ scales) against the target
        config, ``'d'`` checks ``dk``/``dv`` against the draft's — the
        two sides' validators cannot silently diverge."""
        want_shape = (int(mcfg.n_layers), n, int(mcfg.kv_heads),
                      self.page_size, int(mcfg.head_dim))
        names = (("k", "v", "k_scales", "v_scales") if quantized
                 else ("k", "v"))
        side = "draft " if prefix else ""
        for name in names:
            key = prefix + name
            a = art.get(key)
            if not isinstance(a, np.ndarray):
                raise ValueError(f"KV artifact is missing {side}array "
                                 f"{key!r}")
            if name.endswith("_scales"):
                want = want_shape[:3] + (1, self.page_size)
                dtype = np.float32
            else:
                want = want_shape
                dtype = np.dtype(pool_k.dtype)
            if a.shape != want:
                raise ValueError(f"KV artifact {key} shape {a.shape} != "
                                 f"expected {want}")
            if a.dtype != dtype:
                raise ValueError(f"KV artifact {key} dtype {a.dtype} != "
                                 f"{side}pool dtype {dtype}")

    def _validate_artifact_draft(self, art: dict, n: int,
                                 step: int) -> None:
        """The draft half of :meth:`_validate_artifact`.  A draft-less
        batcher rejects artifacts carrying a draft payload (resuming a
        spec row without its draft state would fork sampled streams —
        loud beats subtly different); a speculative batcher requires a
        matching draft payload for MID-STREAM artifacts, but accepts a
        fresh (step-1) prefill export without one: the import rebuilds
        the draft's prompt KV with exactly the chunk write a local spec
        admission dispatches, which is what lets a draft-less prefill
        tier feed draft-equipped decode replicas."""
        draft = art.get("draft")
        has_payload = isinstance(art.get("dk"), np.ndarray)
        if self.d_side is None:
            if draft is not None or has_payload:
                raise ValueError(
                    "KV artifact carries a draft-side payload but this "
                    "batcher has no draft model (speculative exports "
                    "resume on speculative batchers)")
            return
        if not has_payload:
            if step > 1:
                raise ValueError(
                    "suspended KV artifact has no draft-side payload; "
                    "a speculative batcher cannot rebuild mid-stream "
                    "draft state bit-exactly")
            return      # fresh prefill: the import rebuilds the draft
        if not isinstance(draft, dict):
            raise ValueError("KV artifact has draft arrays but no "
                             "'draft' geometry header")
        geom = self._draft_geom()
        for key, want in geom.items():
            if draft.get(key) != want:
                raise ValueError(
                    f"KV artifact draft {key} {draft.get(key)!r} does "
                    f"not match this batcher's {want!r}")
        dquant = geom["quantized"]
        dpool_k = (self.d_side.pool["k"].values if dquant
                   else self.d_side.pool["k"])
        self._check_payload_arrays(art, dquant, n, self.draft_cfg,
                                   dpool_k, prefix="d")

    def _admit_import(self, row: int, pre: Prefilled, wt: int,
                      wd: int, need: int, active: Dict[int, _Row]
                      ) -> tuple:
        """Admission of an imported prefill: back the payload's
        positions with own pages, scatter the artifact's page buffers
        into them, and enter the row straight into decode at the
        exported position with the exported first token — the
        disaggregated analogue of _admit_dispatch, with no model call.
        The imported full prompt pages then seed the prefix cache
        exactly like a local prefill's (insert_row already refuses a
        chunk a twin published, so pages never gain two owners)."""
        t_admit = time.perf_counter()
        art = pre.artifact
        req = pre.request
        self._trace_event(req, "import", rid=int(art.get("rid", -1)),
                          row=row, pos=int(art.get("pos", 0)),
                          resumed=int(art.get("step", 1)) > 1)
        side = self.t_side
        n = art["k"].shape[1]
        side.ensure(row, side.shared_len + n * self.page_size)
        ids = side.alloc.rows[row]
        if art["quantized"]:
            payload = {
                "k": QTensor(jnp.asarray(art["k"]),
                             jnp.asarray(art["k_scales"])),
                "v": QTensor(jnp.asarray(art["v"]),
                             jnp.asarray(art["v_scales"])),
            }
        else:
            payload = {"k": jnp.asarray(art["k"]),
                       "v": jnp.asarray(art["v"])}
        self.pool = _install_pages(self.pool, payload,
                                   jnp.asarray(ids, jnp.int32))
        if self.d_side is not None:
            self._admit_import_draft(row, req, art, n, need)
        # The exported rid keeps the row's in-graph sampling folds on
        # the stream the prefill side started (greedy never reads it;
        # with equal batcher rngs, sampled disaggregated streams equal
        # the unified batcher's exactly).  Caveat: rids from DIFFERENT
        # exporters (or an exporter and this batcher's own counter) can
        # coincide, correlating the sampled draws of unrelated rows —
        # deployments sampling across several prefill replicas should
        # give them distinct seeds/rngs.
        #
        # A SUSPENDED artifact (step > 1) resumes mid-stream: the row
        # re-enters decode with the exported emitted-token list, last
        # token, and step — the (rid, step) sample folds continue on
        # exactly the stream the suspension interrupted, so the resumed
        # completion is token-identical to an uninterrupted run.
        step = int(art.get("step", 1))
        toks = [int(t) for t in (art.get("tokens") or ())]
        resumed = step > 1
        state = _Row(rid=int(art["rid"]), req=req, pos=int(art["pos"]),
                     step=step, last=(toks[-1] if resumed else 0),
                     out=(list(toks) if resumed else []), worst_pages=wt,
                     worst_draft=wd, t_admit=t_admit, limit=need)
        active[row] = state
        self._pcache_insert(row, state)
        return row, state, np.asarray([int(art["first_token"])]), 0

    def _admit_import_draft(self, row: int, req: Request, art: dict,
                            n: int, need: int) -> None:
        """The draft half of :meth:`_admit_import`: scatter the
        artifact's paired draft payload into own draft pages — or, for
        a fresh (step-1) export from a draft-less prefill tier, rebuild
        the draft's prompt KV with EXACTLY the chunk write a local spec
        admission dispatches (same widths, same offsets), so the draft
        cache is bit-identical to a local admission's."""
        dside = self.d_side
        if isinstance(art.get("dk"), np.ndarray):
            dside.ensure(row, dside.shared_len + n * self.page_size)
            dids = dside.alloc.rows[row]
            if art["draft"]["quantized"]:
                dpayload = {
                    "k": QTensor(jnp.asarray(art["dk"]),
                                 jnp.asarray(art["dk_scales"])),
                    "v": QTensor(jnp.asarray(art["dv"]),
                                 jnp.asarray(art["dv_scales"])),
                }
            else:
                dpayload = {"k": jnp.asarray(art["dk"]),
                            "v": jnp.asarray(art["dv"])}
            dside.pool = _install_pages(dside.pool, dpayload,
                                        jnp.asarray(dids, jnp.int32))
            return
        # Rebuild (validated: only fresh step-1 artifacts reach here).
        length = int(req.prompt.size)
        bucket = self.prefill_chunk or self.prefill_bucket
        width = -(-length // bucket) * bucket
        fresh = dside.alloc.allocated(row) == 0
        dside.ensure(row, min(self.prefix_len + width, need))
        if dside.tail_template is not None and fresh \
                and not dside.row_cached.get(row) \
                and dside.alloc.allocated(row):
            dst = np.full((self.n_shards,), dside.sink, np.int32)
            dst[dside.alloc.shard_of(row)] = dside.alloc.rows[row][0]
            dside.pool = dside.copy(dside.pool, dside.tail_template, dst)
        padded = np.zeros((1, width), np.int32)
        padded[0, :length] = req.prompt
        if self._chunk_prefill is not None:
            # Chunked admission writes the draft chunk by chunk; mirror
            # it so the rebuilt cache is bit-identical.
            c = self.prefill_chunk
            for off in range(0, width, c):
                _, dtoks, dtable = self._one_hot_call(
                    dside, row, padded[:, off:off + c])
                dside.pool = self._draft_chunk(
                    self.draft_params, dside.pool, dtable, dtoks,
                    jnp.asarray(self.prefix_len + off, jnp.int32))
        else:
            _, dtoks, dtable = self._one_hot_call(dside, row, padded)
            dside.pool = self._draft_chunk(
                self.draft_params, dside.pool, dtable, dtoks,
                jnp.asarray(self.prefix_len, jnp.int32))

    # -- the KV tier: prefix spill/promote + session park/resume -----------

    @property
    def _tier_active(self) -> bool:
        return (self.kv_tier is not None
                and self.kv_tier_bypass_reason is None)

    def _tier_geom(self) -> Dict[str, Any]:
        """The geometry stamped on every spilled prefix page and
        checked on promotion — a tier entry cut for a different pool
        layout or model must read as a miss, never install.  The
        ``draft`` sub-geometry (None without one) makes a speculative
        batcher's twin-page spills unreadable by draft-less peers and
        vice versa."""
        geom: Dict[str, Any] = {
            "page_size": self.page_size,
            "n_layers": int(self.cfg.n_layers),
            "kv_heads": int(self.cfg.kv_heads),
            "head_dim": int(self.cfg.head_dim),
            "dtype": str(np.dtype(self.pool["k"].dtype)),
            "draft": None}
        if self.d_side is not None:
            geom["draft"] = {
                "n_layers": int(self.draft_cfg.n_layers),
                "kv_heads": int(self.draft_cfg.kv_heads),
                "head_dim": int(self.draft_cfg.head_dim),
                "dtype": str(np.dtype(self.d_side.pool["k"].dtype))}
        return geom

    def _spill_page(self, shard: int, digest: bytes, page: int,
                    dpage: Optional[int] = None) -> None:
        """The prefix cache's eviction callback: gather the evicted
        page's content to host and park it in the KV tier,
        content-addressed by its chain digest — the device→host spill
        of the memory hierarchy.  In speculative mode the node's DRAFT
        twin rides the same entry (body = target k+v then draft k+v),
        so a promotion restores both pools.  Runs on the serve-loop
        thread (the eviction happens under its allocation pressure)
        while the pages still hold the published chunk; any failure
        costs the spill, never the eviction."""
        tier = self.kv_tier
        if tier is None:
            return
        # Pre-check the tier's hard bounds BEFORE paying the device-
        # to-host gather: a page that can never fit must not cost a
        # blocking transfer on the reclaim path (which runs mid-
        # admission, under the cache lock).
        nbytes = (2 * int(self.cfg.n_layers) * int(self.cfg.kv_heads)
                  * self.page_size * int(self.cfg.head_dim)
                  * np.dtype(self.pool["k"].dtype).itemsize)
        if self.d_side is not None:
            nbytes += (2 * int(self.draft_cfg.n_layers)
                       * int(self.draft_cfg.kv_heads) * self.page_size
                       * int(self.draft_cfg.head_dim)
                       * np.dtype(self.d_side.pool["k"].dtype).itemsize)
        accept = getattr(tier, "would_accept", None)
        if accept is not None and not accept(nbytes + 512):
            tier.count("evictions")
            return
        kv = _gather_pages(self.pool, jnp.asarray([int(page)], jnp.int32))
        k = np.ascontiguousarray(np.asarray(kv["k"]))
        v = np.ascontiguousarray(np.asarray(kv["v"]))
        meta = dict(self._tier_geom())
        meta["k_bytes"] = int(k.nbytes)
        body = k.tobytes() + v.tobytes()
        if self.d_side is not None and dpage is not None:
            dkv = _gather_pages(self.d_side.pool,
                                jnp.asarray([int(dpage)], jnp.int32))
            dk = np.ascontiguousarray(np.asarray(dkv["k"]))
            dv = np.ascontiguousarray(np.asarray(dkv["v"]))
            meta["dk_bytes"] = int(dk.nbytes)
            body += dk.tobytes() + dv.tobytes()
        tier.put_prefix(digest.hex(), meta, body)

    def _tier_page_payload(self, meta: dict, body: bytes):
        """Rebuild one spilled page's device payload(s): ``(target,
        draft)`` — each a ``{"k", "v"}`` tree of shape [layers, 1,
        kv_heads, page, dim], draft None without one; None (the whole
        result) when the entry was cut for a different geometry or is
        malformed."""
        geom = self._tier_geom()
        if any(meta.get(k) != geom[k] for k in geom):
            return None
        shape = (int(self.cfg.n_layers), 1, int(self.cfg.kv_heads),
                 self.page_size, int(self.cfg.head_dim))
        dtype = np.dtype(geom["dtype"])
        kb = meta.get("k_bytes")
        count = int(np.prod(shape, dtype=np.int64))
        want = 2 * count * dtype.itemsize
        if not isinstance(kb, int) or 2 * kb != want \
                or len(body) < want:
            return None
        k = np.frombuffer(body, dtype=dtype, count=count).reshape(shape)
        v = np.frombuffer(body, dtype=dtype, count=count,
                          offset=kb).reshape(shape)
        target = {"k": jnp.asarray(k), "v": jnp.asarray(v)}
        if self.d_side is None:
            if len(body) != want:
                return None
            return target, None
        dshape = (int(self.draft_cfg.n_layers), 1,
                  int(self.draft_cfg.kv_heads), self.page_size,
                  int(self.draft_cfg.head_dim))
        ddtype = np.dtype(geom["draft"]["dtype"])
        dkb = meta.get("dk_bytes")
        dcount = int(np.prod(dshape, dtype=np.int64))
        if not isinstance(dkb, int) or dkb != dcount * ddtype.itemsize \
                or len(body) != want + 2 * dkb:
            return None
        dk = np.frombuffer(body, dtype=ddtype, count=dcount,
                           offset=want).reshape(dshape)
        dv = np.frombuffer(body, dtype=ddtype, count=dcount,
                           offset=want + dkb).reshape(dshape)
        return target, {"k": jnp.asarray(dk), "v": jnp.asarray(dv)}

    def _tier_promote(self, req: Request) -> None:
        """Opportunistic tier→device promotion at admission: for each
        of ``req``'s prompt chunks just past the trie's longest match,
        a tier hit installs the spilled page into a FREE pool page and
        re-inserts it as a zero-ref trie node — the normal prefix-plan
        path then maps it like any resident hit.  Free pages only
        (promotion never evicts resident cache to make room — that
        would just rotate the working set through the tier); checked
        once per request (memoized), so a queued arrival does not
        re-scan the tier every admission tick."""
        if not self._tier_active or self._pcache is None:
            return
        if getattr(req, "_tier_checked", False):
            return
        req._tier_checked = True
        digs = self._req_digests(req)
        if not digs:
            return
        pc = self._pcache
        alloc = self.t_side.alloc.shards[0]
        dalloc = (self.d_side.alloc.shards[0]
                  if self.d_side is not None else None)
        n = len(pc.match(0, digs))
        while n < len(digs):
            d = digs[n]
            got = self.kv_tier.get_prefix(d.hex())
            if got is None:
                break
            payloads = self._tier_page_payload(got[0], got[1])
            if payloads is None or not alloc.free \
                    or (dalloc is not None and not dalloc.free):
                break
            payload, dpayload = payloads
            page = alloc.free.pop()
            dpage = dalloc.free.pop() if dalloc is not None else None
            if not pc.insert_chain(0, digs[:n], d, page, dpage):
                alloc.free.append(page)
                if dalloc is not None:
                    dalloc.free.append(dpage)
                break
            self.pool = _install_pages(self.pool, payload,
                                       jnp.asarray([page], jnp.int32))
            if dpayload is not None:
                self.d_side.pool = _install_pages(
                    self.d_side.pool, dpayload,
                    jnp.asarray([dpage], jnp.int32))
            self.kv_tier.count("promotions")
            self._trace_event(req, "tier_promote", digest=d.hex()[:16],
                              depth=n + 1)
            n += 1

    def _validate_session(self, art: dict, req: Request) -> None:
        """Reject a parked session artifact that cannot resume THIS
        request bit-exactly (every mismatch → ``ValueError`` → the
        lookup treats it as a miss and the turn re-prefills cold —
        deterministic, never stale KV)."""
        if art.get("version") != 1:
            raise ValueError(f"unknown session artifact version "
                             f"{art.get('version')!r}")
        for key, want in (("page_size", self.page_size),
                          ("prefix_len", self.prefix_len),
                          ("shared_len", self.t_side.shared_len),
                          ("quantized", False)):
            if art.get(key) != want:
                raise ValueError(
                    f"session artifact {key} {art.get(key)!r} does not "
                    f"match this batcher's {want!r}")
        model = art.get("model") or {}
        for key, want in (("n_layers", int(self.cfg.n_layers)),
                          ("kv_heads", int(self.cfg.kv_heads)),
                          ("head_dim", int(self.cfg.head_dim))):
            if model.get(key) != want:
                raise ValueError(
                    f"session artifact model {key} {model.get(key)!r} "
                    f"does not match this config's {want}")
        hist = art.get("history")
        if not isinstance(hist, (list, tuple)) or len(hist) < 2:
            raise ValueError("session artifact carries no usable "
                             "history")
        if req.prompt.size < len(hist):
            raise ValueError(
                f"request prompt ({req.prompt.size} tokens) does not "
                f"extend the parked history ({len(hist)} tokens)")
        if not np.array_equal(req.prompt[:len(hist)],
                              np.asarray(hist, np.int32)):
            raise ValueError("request prompt diverges from the parked "
                             "session history")
        covered = len(hist) - 1     # the last token is the tail's input
        E_art = art.get("pos")
        if E_art != self.prefix_len + covered:
            raise ValueError(
                f"session artifact covers {E_art!r} positions; its "
                f"history implies {self.prefix_len + covered}")
        ps = self.page_size
        n = -(-(E_art - self.t_side.shared_len) // ps)
        want_shape = (int(self.cfg.n_layers), n, int(self.cfg.kv_heads),
                      ps, int(self.cfg.head_dim))
        dtype = np.dtype(self.pool["k"].dtype)
        for key in ("k", "v"):
            a = art.get(key)
            if not isinstance(a, np.ndarray) or a.shape != want_shape \
                    or a.dtype != dtype:
                raise ValueError(
                    f"session artifact {key} is not a "
                    f"{want_shape}/{dtype} array")
        # Speculative sessions: the parked artifact must carry (or not
        # carry) a draft payload matching THIS batcher — a mismatch is
        # a miss (the caller re-prefills cold), never a half-resume.
        draft = art.get("draft")
        if self.d_side is None:
            if draft is not None or isinstance(art.get("dk"),
                                               np.ndarray):
                raise ValueError("session artifact carries a draft "
                                 "payload this batcher has no draft "
                                 "model for")
        else:
            # The tier bypasses quantized pools, so _draft_geom()'s
            # quantized field is necessarily False here.
            for key, want in self._draft_geom().items():
                if not isinstance(draft, dict) \
                        or draft.get(key) != want:
                    raise ValueError(
                        f"session artifact draft geometry does not "
                        f"match this batcher ({key})")
            dshape = (int(self.draft_cfg.n_layers), n,
                      int(self.draft_cfg.kv_heads), ps,
                      int(self.draft_cfg.head_dim))
            ddtype = np.dtype(self.d_side.pool["k"].dtype)
            for key in ("dk", "dv"):
                a = art.get(key)
                if not isinstance(a, np.ndarray) \
                        or a.shape != dshape or a.dtype != ddtype:
                    raise ValueError(
                        f"session artifact {key} is not a "
                        f"{dshape}/{ddtype} array")
        # The tail's padded prefill window must fit the page table
        # (same bound the prefix-plan trimmer enforces).
        E = self.prefix_len + int(req.prompt.size)
        w = -(-(E - E_art) // self.prefill_bucket) * self.prefill_bucket
        if E_art + w > self.np_max * ps:
            raise ValueError("session tail window exceeds the page "
                             "table; resuming cold instead")

    def _session_lookup(self, req: Request) -> Optional[dict]:
        """The usable parked artifact for ``req.session_id``, or None
        (no tier, no entry, stale weights, corrupt, or it does not
        cover this prompt — every miss path means a cold full-history
        prefill, which is always correct).  Memoized per request so a
        queued arrival does not re-read the tier every tick."""
        if not self._tier_active or not req.session_id:
            return None
        memo = getattr(req, "_session_art", None)
        if memo is not None:
            return memo[0]
        art = None
        got = self.kv_tier.resume(req.session_id)
        if got is not None:
            try:
                art = unpack_prefilled(dict(got[0]), got[1])
                self._validate_session(art, req)
            except ValueError:
                art = None
        if art is not None:
            self.kv_tier.count("resume")
        req._session_art = (art,)
        return art

    def _admit_session(self, row: int, rid: int, req: Request, wt: int,
                       wd: int, need: int, active: Dict[int, _Row],
                       art: dict) -> tuple:
        """Admission of a session RESUME: install the parked artifact's
        pages (they back the conversation so far) and prefill only the
        new turn's tail at its true offset — the cross-turn analogue of
        a prefix-cache hit, built from the import scatter plus the
        traced-offset chunk writer.  Returns the burst tuple like
        ``_admit_dispatch``."""
        t_admit = time.perf_counter()
        side = self.t_side
        n = art["k"].shape[1]
        self._trace_event(req, "session_resume", rid=rid, row=row,
                          session=str(req.session_id),
                          covered=int(art["pos"]))
        # The artifact's first own page embeds any shared-prefix tail
        # template (the parking row's copy), so the plain ensure is
        # right — no template re-copy, exactly like _admit_import.
        side.ensure(row, side.shared_len + n * self.page_size)
        ids = list(side.alloc.rows[row])
        # Bucket the install to a power-of-two page count (pad slots
        # scatter zeros onto the sink page — a write dump by
        # construction) so resume dispatches one of log2(np_max)
        # compiled scatters, never a fresh trace on the TTFT path.
        def pow2_install(pool, sink, page_ids, k, v):
            m = self._pow2(n)
            page_ids = list(page_ids)
            if m > n:
                pad = np.zeros(k.shape[:1] + (m - n,) + k.shape[2:],
                               k.dtype)
                k = np.concatenate([k, pad], axis=1)
                v = np.concatenate([v, pad], axis=1)
                page_ids = page_ids[:n] + [sink] * (m - n)
            payload = {"k": jnp.asarray(k), "v": jnp.asarray(v)}
            return _install_pages(pool, payload,
                                  jnp.asarray(page_ids, jnp.int32))

        self.pool = pow2_install(self.pool, side.sink, ids,
                                 art["k"], art["v"])
        if self.d_side is not None:
            # The paired draft payload backs the same positions of the
            # draft pool (validated present and shape-matched).
            dside = self.d_side
            dside.ensure(row, dside.shared_len + n * self.page_size)
            dside.pool = pow2_install(dside.pool, dside.sink,
                                      dside.alloc.rows[row],
                                      art["dk"], art["dv"])
        E = self.prefix_len + int(req.prompt.size)
        ts = int(art["pos"])
        tlen = E - ts
        w = -(-tlen // self.prefill_bucket) * self.prefill_bucket
        # Clamp at the reservation: pad writes past ``need`` land on
        # reserved-but-unread slots or sink columns (the cold path's
        # prompt padding discipline).
        side.ensure(row, min(ts + w, need))
        padded = np.zeros((1, w), np.int32)
        padded[0, :tlen] = req.prompt[req.prompt.size - tlen:]
        s, toks, table = self._one_hot_call(side, row, padded)
        caps = np.full((self.n_shards,), -1, np.int32)
        caps[s] = tlen - 1
        rids = np.zeros((self.n_shards,), np.int32)
        rids[s] = rid
        self.pool, tok = self._tail_prefill(
            self.params, self.pool, table, toks,
            jnp.asarray(ts, jnp.int32), jnp.asarray(caps),
            jnp.asarray(rids))
        if self.d_side is not None:
            # The draft's tail advances in lockstep (same tokens, same
            # offset) so the next spec round proposes from a complete
            # draft cache.
            dside = self.d_side
            dside.ensure(row, min(ts + w, need))
            _, dtoks, dtable = self._one_hot_call(dside, row, padded)
            dside.pool = self._draft_chunk(
                self.draft_params, dside.pool, dtable, dtoks,
                jnp.asarray(ts, jnp.int32))
        tok.copy_to_host_async()    # transfer overlaps later dispatches
        state = _Row(rid=rid, req=req, pos=E, step=1, last=0, out=[],
                     worst_pages=wt, worst_draft=wd, t_admit=t_admit,
                     limit=need)
        active[row] = state
        self._pcache_insert(row, state)
        return row, state, tok, s

    def _park_session(self, r: int, state: _Row) -> None:
        """Park a FINISHED session-labeled row's KV in the tier (called
        before its pages release): the artifact is the row's export
        plus the full conversation history, so the next turn can resume
        from it on this replica — or, through a shared disk tier, on
        any same-weights replica of the host.  EVERY decode mode parks
        — the lagged ones (overlap/pipelined, spec) export at the
        COMMITTED boundary (``_export_row(final=True)`` clamps the
        overshooting host view to ``prefix + prompt + len(out) - 1``,
        below which every position holds the true stream), fixing the
        PR 13 gap where they silently missed cold.  A full tier is an
        explicit rejected park, never a failed request."""
        if not self._tier_active:
            return
        sid = state.req.session_id
        if not sid or not state.out or state.t_first <= 0:
            return
        try:
            art = self._export_row(r, state, pad_pow2=True, final=True)
            art["history"] = ([int(t) for t in state.req.prompt]
                              + [int(t) for t in state.out])
            meta, body = pack_prefilled(art)
        except Exception:
            return      # parking is best-effort; the completion stands
        try:
            self.kv_tier.park(str(sid), meta, body)
        except Exception:
            # KVTierFull (counted park_rejected by the store) or an
            # unexpected failure: explicit and observable, and the
            # request's completion is unaffected.
            return
        self._trace_event(state.req, "session_park", session=str(sid),
                          bytes=len(body))

    def _finish_completed(self, r: int, active: Dict[int, _Row],
                          free_rows: List[int]) -> None:
        """Finish a COMPLETED row: park its session KV (when labeled
        and parkable) before the pages release, then the normal
        finish."""
        state = active.get(r)
        if state is not None:
            self._park_session(r, state)
        self._finish(r, active, free_rows)

    def _submission_source(self) -> SubmissionQueue:
        with self._submissions_lock:
            if self._submissions is None:
                self._submissions = SubmissionQueue()
            return self._submissions

    def submit(self, request: Request, prefilled: Optional[dict] = None
               ) -> None:
        """Thread-safe online admission: queue ``request`` for the
        :meth:`serve` loop.  May be called from any thread, before or
        while serve() runs; raises after :meth:`close`.

        ``prefilled`` (an :meth:`export_kv` artifact) switches the
        request onto the IMPORT path: its KV pages install into the
        local pool and the row enters decode directly — the decode half
        of disaggregated serving."""
        if prefilled is not None:
            request = Prefilled(request, prefilled)
        self._submission_source().submit(request)

    def close(self) -> None:
        """End the online stream: serve() drains everything submitted
        and returns (or, called before serve(), makes it return
        immediately).  Idempotent."""
        self._submission_source().close()

    def serve(self) -> Iterator[Completion]:
        """:meth:`run` over the incremental submission queue: yields
        Completions in finish order as submit()ted requests finish,
        decoding continuously while the queue is empty, blocking only
        when fully idle, and returning once :meth:`close` is called and
        the stream drains.  One serve() loop per batcher."""
        return self.run(self._submission_source())

    # -- the loop ---------------------------------------------------------

    def run(self, requests: Iterable[Request]) -> Iterator[Completion]:
        """Serve ``requests`` (any iterable — a generator staggers
        arrivals naturally — or a :class:`SubmissionQueue` for online
        thread-safe submission, see :meth:`serve`), yielding
        :class:`Completion`\\ s in FINISH order.  Pulls from the
        iterable lazily: a request is consumed only when a row and
        pages are available for it.  Abandoning the
        iterator early releases every in-flight row's pages.  An invalid
        request (longer than ``max_len`` allows) raises — but only AFTER
        every already-admitted request has drained and yielded, so one
        malformed arrival never discards valid in-flight work."""
        incremental = isinstance(requests, SubmissionQueue)
        source = None if incremental else iter(requests)
        pending: deque = deque()
        active: Dict[int, _Row] = {}
        free_rows = list(range(self.rows))
        exhausted = False
        bad_request: Optional[Exception] = None

        def rank_of(item):
            return (item.request if isinstance(item, Prefilled)
                    else item).priority

        def rank_insert(item):
            # Class-aware admission order (the batcher-side twin of the
            # gateway's WFQ): pending stays sorted by priority rank,
            # FIFO within a rank — an outranking arrival admits before
            # earlier lower-class ones, and single-class traffic keeps
            # the exact FIFO of old.  Stable: insert BEHIND every item
            # of equal-or-higher rank.
            p = rank_of(item)
            i = len(pending)
            while i > 0 and rank_of(pending[i - 1]) < p:
                i -= 1
            pending.insert(i, item)

        def pull(block=True):
            # ``block`` only matters for a SubmissionQueue source: the
            # admission loop polls non-blocking so an empty online queue
            # never stalls rows that are mid-decode, while the idle
            # branch blocks (there is nothing else to do).  An
            # incremental pull drains EVERYTHING already submitted (the
            # items are in host memory either way, and admission cannot
            # rank-order arrivals it has not seen); iterables keep their
            # original lazy one-at-a-time semantics — next() blocks when
            # the generator does, and a generator's order is its order.
            nonlocal exhausted
            if exhausted:
                return
            if incremental:
                want_block = block and not pending
                while True:
                    item = requests.poll(want_block)
                    want_block = False
                    if item is _CLOSED:
                        exhausted = True
                        return
                    if item is None:
                        return
                    rank_insert(item)
            if pending:
                return
            try:
                pending.append(next(source))
            except StopIteration:
                exhausted = True

        # Fences export_kv's row borrowing: taken under _export_lock so
        # the check-then-borrow in export_kv and this set cannot
        # interleave (a loop starting mid-export waits the export out;
        # an export starting after this sees the flag and raises).
        with self._export_lock:
            self._loop_active = True
        try:
            while True:
                if self._preempt_event.is_set():
                    # Drain-migration: every in-flight request (resident
                    # rows, parked artifacts, queued arrivals) is given
                    # back as a Suspended item for re-placement
                    # elsewhere; the loop itself keeps serving whatever
                    # arrives after.
                    yield from self._preempt_everything(
                        pending, active, free_rows,
                        requests if incremental else None)
                # Admit while a row is free and the pool can take the
                # newcomer's worst case.  Prefills DISPATCH inside the
                # loop but their first-token fetches are deferred to one
                # burst sync after it — admitting W requests costs one
                # device-to-host round-trip, not W (the round-trip is
                # the dominant per-call cost on remote-attached hosts).
                # End-to-end deadlines: cancel expired resident rows
                # NOW, before admission — their pages free this tick,
                # so dead work never holds a decode slot a live arrival
                # could take.
                yield from self._cancel_expired(active, free_rows)
                burst = []
                # Parked (preempted) artifacts resume FIRST: they
                # arrived before anything still queued, so a sustained
                # same-class arrival stream must not starve them.  A
                # strictly-OUTRANKING queued arrival still goes first
                # (the gate below — and past it, the preemption rule
                # itself); one eager pull makes such an arrival visible.
                if self._parked and incremental:
                    pull(block=False)
                while free_rows and self._parked and bad_request is None:
                    pre = self._parked[0]
                    if pre.request.expired:
                        # The client gave up while the artifact was
                        # parked: drop it without re-importing.
                        self._parked.popleft()
                        self.deadline_cancels += 1
                        self._trace_event(pre.request, "deadline_cancel",
                                          where="parked")
                        yield Expired(rid=int(pre.artifact.get("rid",
                                                               -1)),
                                      request=pre.request)
                        continue
                    if pending:
                        h = pending[0]
                        hreq = h.request if isinstance(h, Prefilled) \
                            else h
                        if hreq.priority > pre.request.priority:
                            break
                    try:
                        wt, wd, need = self._worst_pages(pre.request)
                        row, _ = self._admit_row(free_rows, active, wt,
                                                 wd, pre.request,
                                                 use_cache=False)
                    except RuntimeError:
                        # The resume can never fit this pool (e.g. the
                        # original admission rode a prefix-cache plan
                        # the full-page import cannot): fall back to a
                        # from-scratch re-run through the normal path —
                        # deterministic, the waiter's callback intact —
                        # instead of killing the serve loop.
                        # (_maybe_preempt's fit check keeps this
                        # unreachable in practice.)
                        self._parked.popleft()
                        pending.appendleft(pre.request)
                        continue
                    if row is None:
                        break       # resume once pages free up
                    self._parked.popleft()
                    self.resumes += 1
                    self._trace_event(pre.request, "resume")
                    burst.append(self._admit_import(row, pre, wt, wd,
                                                    need, active))
                while free_rows and bad_request is None \
                        and not self._weight_updates:
                    # (A pending weight update gates NEW admissions —
                    # resident rows and parked resumes finish on the
                    # old weights first; see _apply_weight_update.)
                    if not pending and not exhausted and burst \
                            and not incremental:
                        # pull() may BLOCK in next(source) (a staggered
                        # stream): settle the in-flight admissions first
                        # so their first tokens (and any instant
                        # completions) are not held hostage to the next
                        # arrival — this also keeps t_first honest.
                        # (A SubmissionQueue source never blocks here.)
                        yield from self._finalize_burst(burst, active,
                                                        free_rows)
                    pull(block=False)
                    if not pending:
                        break
                    item = pending[0]
                    imported = isinstance(item, Prefilled)
                    req0 = item.request if imported else item
                    if req0.expired:
                        # Shed BEFORE any prefill work (or import)
                        # dispatches: the deadline passed while the
                        # request waited, and serving it would burn
                        # device time nobody is waiting for.
                        pending.popleft()
                        self.deadline_cancels += 1
                        self._trace_event(req0, "deadline_cancel",
                                          where="queued")
                        yield Expired(
                            rid=(int(item.artifact.get("rid", -1))
                                 if imported else -1),
                            request=req0)
                        continue
                    try:
                        wt, wd, need = self._worst_pages(req0)
                        if imported:
                            self._validate_artifact(item.artifact, req0)
                    except ValueError as e:
                        bad_request = e     # raise after draining
                        break
                    # KV tier: a usable parked session artifact takes
                    # the resume path instead of prefilling the whole
                    # history — checked FIRST, because a resume
                    # installs those positions from the artifact and
                    # promoting their spilled prefix pages too would
                    # be a second, unused device install.  Otherwise,
                    # promote any spilled prefix pages this prompt
                    # could map (they re-enter the trie as zero-ref
                    # nodes, so the prefix plan below sees them).
                    sess_art = (None if imported
                                else self._session_lookup(req0))
                    if not imported and sess_art is None:
                        self._tier_promote(req0)
                    # Imports (and session resumes) skip the
                    # prefix-plan mapping: their pages arrive in the
                    # payload (installing everything, then publishing,
                    # is what keeps import admission one code path
                    # with local prefill).
                    row, plan = self._admit_row(
                        free_rows, active, wt, wd, req0,
                        use_cache=not imported and sess_art is None)
                    if row is None:
                        # Allocation pressure: a strictly-higher-
                        # priority head may suspend the lowest-priority
                        # resident row (its pages free, its artifact
                        # parks for resumption) and retry.
                        if self._maybe_preempt(req0.priority, active,
                                               free_rows):
                            continue
                        break   # wait for an in-flight row to finish
                    pending.popleft()
                    if imported:
                        # Imports keep their exporter's rid (the
                        # sampling folds must continue that stream) —
                        # the local counter is neither consulted nor
                        # burned.
                        res = self._admit_import(row, item, wt, wd,
                                                 need, active)
                    elif sess_art is not None:
                        rid = self._next_rid
                        self._next_rid += 1
                        res = self._admit_session(row, rid, item, wt,
                                                  wd, need, active,
                                                  sess_art)
                    else:
                        rid = self._next_rid
                        self._next_rid += 1
                        res = self._admit_dispatch(row, rid, item, wt,
                                                   wd, need, active,
                                                   plan)
                    if res is not None:
                        burst.append(res)
                # Every row busy: an incremental arrival of strictly
                # higher priority must not wait a full request behind
                # lower-priority residents — one eager non-blocking
                # pull (pending stays <= 1, preserving the lazy-pull
                # bound) makes it visible, and a successful preemption
                # loops back to admit it before the next decode block.
                if (not free_rows and incremental and self.preemptible
                        and bad_request is None
                        and not self._weight_updates):
                    pull(block=False)
                    if pending:
                        it0 = pending[0]
                        r0 = it0.request if isinstance(it0, Prefilled) \
                            else it0
                        if self._maybe_preempt(r0.priority, active,
                                               free_rows):
                            yield from self._finalize_burst(
                                burst, active, free_rows)
                            continue
                yield from self._finalize_burst(burst, active, free_rows)
                # Streaming flush point 1: freshly admitted rows' first
                # tokens (prefill output) go out NOW — the streamed
                # TTFT is the prefill latency, not prefill + one block.
                self._flush_streams(active)
                if not active:
                    if bad_request is not None:
                        raise bad_request
                    if self._parked:
                        continue    # resume parked work before idling
                    if self._weight_updates:
                        # Between generations, nothing resident: THE
                        # weight-update point — fold/replace, flush
                        # stale KV caches, then resume admission.
                        self._apply_pending_weight_updates()
                        continue
                    pull()
                    if not pending and exhausted:
                        return
                    continue
                if (self._fused
                        and any(row.decoding for row in active.values())
                        and any(not row.decoding
                                for row in active.values())):
                    # Stall-free tick: decode block + budgeted chunk
                    # slots in ONE dispatch (see _step_fused).  Ticks
                    # with only one phase live take the plain paths
                    # below — there is nothing to fuse.
                    yield from self._step_fused(active, free_rows)
                    self._flush_streams(active)
                    continue
                if self._chunk_prefill is not None:
                    done_row = self._advance_prefill(active)
                    if done_row is not None:
                        done = self._completion(active[done_row])
                        self._finish_completed(done_row, active,
                                               free_rows)
                        yield done
                if any(row.decoding for row in active.values()):
                    if self.draft_cfg is not None and self.overlap:
                        yield from self._step_spec_overlap(active,
                                                           free_rows)
                    elif self.draft_cfg is not None:
                        yield from self._step_spec(active, free_rows)
                    elif self._pipelined:
                        yield from self._step_pipelined(active, free_rows)
                    elif self.overlap:
                        yield from self._step_overlap(active, free_rows)
                    else:
                        yield from self._step(active, free_rows)
                    # Streaming flush point 2: this block's tokens, one
                    # call per still-resident streaming row (rows that
                    # FINISHED inside the block already yielded their
                    # Completion — the full list — so their tail never
                    # needs a partial).
                    self._flush_streams(active)
        finally:
            # A consumer that stops early (break / close) must not leak
            # the in-flight rows' pages (or a stale overlap/pipelined
            # dispatch and its device carry).
            self._inflight = None
            self._pipe_carry = self._pipe_host = None
            self._parked.clear()    # pages already released at suspend
            for row in list(active):
                self._finish(row, active, free_rows)
            # Dropped only after the rows are released, so an export
            # admitted the instant the fence clears can never borrow a
            # row the dying loop still owns.  Weight updates still
            # queued apply HERE (under the same lock a new
            # swap_adapter would take) so their waiters always get
            # their callback — a dying loop must not strand a swap.
            with self._export_lock:
                self._loop_active = False
                self._apply_pending_weight_updates()

    def _flush_streams(self, active: Dict[int, "_Row"]) -> None:
        """Push each streaming row's not-yet-streamed ``out`` suffix to
        its ``Request.on_tokens`` callback (per-token incremental
        replies on the serving path).  Token STREAMS are not touched —
        this only reads ``out`` — so every mode's equivalence contract
        is unaffected; in the lagged modes (overlap/pipelined) tokens
        stream when they RETIRE, exactly when the host learns them.  A
        raising callback is disarmed: a broken consumer costs its
        stream, never the request or the loop."""
        for row in active.values():
            cb = row.req.on_tokens
            if cb is None:
                continue
            n = len(row.out)
            if n <= row.streamed:
                continue
            chunk = [int(t) for t in row.out[row.streamed:n]]
            off = row.streamed
            row.streamed = n
            try:
                cb(chunk, off)
            except Exception:
                row.req.on_tokens = None

    def _ensure_sides(self, row: int, length: int) -> None:
        """Back ABSOLUTE positions [0, length) of ``row`` on the target
        (and, speculative mode, draft) side.  The first time a row gains
        own pages, a partially-shared prefix tail page is copied into its
        first own page (copy-on-write) before any row write can land in
        it."""
        sides = ([self.t_side] if self.d_side is None
                 else [self.t_side, self.d_side])
        for side in sides:
            fresh = side.alloc.allocated(row) == 0
            side.ensure(row, length)
            # A row holding CACHED prefix pages skips the template copy:
            # its first cacheable page (which embeds the template
            # content) came from the cache — its first OWN page covers a
            # later position range entirely.
            if (side.tail_template is not None and fresh
                    and not side.row_cached.get(row)
                    and side.alloc.allocated(row)):
                dst = np.full((self.n_shards,), side.sink, np.int32)
                dst[side.alloc.shard_of(row)] = side.alloc.rows[row][0]
                side.pool = side.copy(side.pool, side.tail_template, dst)

    def _admit_dispatch(self, row: int, rid: int, req: Request, wt: int,
                        wd: int, need: int, active: Dict[int, _Row],
                        plan: Optional[_PrefixPlan] = None
                        ) -> Optional[tuple]:
        """Reserve + DISPATCH ``req``'s prefill into ``row`` without the
        first-token host sync; ``wt``/``wd``/``need`` are the per-side
        page reservations (and the position cap they cover) run()
        admitted it under, ``plan`` the prefix-cache mapping it chose
        the row's shard for.  Returns ``(row, state, device_token,
        shard)`` for run()'s burst finalize — ``None`` in chunked mode,
        which makes no model call here."""
        t_admit = time.perf_counter()
        self._trace_event(req, "admit", rid=rid, row=row,
                          prompt_len=int(req.prompt.size),
                          cached=plan is not None)
        length = req.prompt.size
        width = -(-length // self.prefill_bucket) * self.prefill_bucket
        if plan is not None:
            # Map the cached prefix pages read-only BEFORE any ensure()
            # call: the references protect them from the LRU evictor
            # while this admission allocates its own pages.
            self._pcache.acquire(row, plan.nodes)
            self._pcache.count("hits")
            self._pcache.count("hit_pages", len(plan.nodes))
            self._pcache.count("hit_tokens",
                               plan.tail_start - self.prefix_len)
            wt -= plan.save
            if self.d_side is not None:
                # Coupled nodes: the draft-side reservation shrinks by
                # the same mapped-page count.
                wd -= plan.save
        elif self._pcache is not None and self._req_digests(req):
            self._pcache.count("misses")
        if self._chunk_prefill is not None:
            # Chunked mode: no model call here — the run loop advances
            # one chunk per tick, interleaved with the batched decode
            # step.  On a cache hit, filling starts AT THE TAIL (the
            # mapped pages already hold chunks [0, filled)).
            self._ensure_sides(row, self.prefix_len + width)
            padded = np.zeros((1, width), np.int32)
            padded[0, :length] = req.prompt
            state = _Row(rid=rid, req=req, pos=self.prefix_len + length,
                         step=1, last=0, out=[], worst_pages=wt,
                         worst_draft=wd, t_admit=t_admit, padded=padded,
                         filled=(0 if plan is None
                                 else plan.tail_start - self.prefix_len),
                         decoding=False, limit=need)
            active[row] = state
            return None
        if plan is not None:
            return self._admit_cached(row, rid, req, wt, wd, need,
                                      active, plan, t_admit)
        self._ensure_sides(row, self.prefix_len + width)
        padded = np.zeros((1, width), np.int32)
        padded[0, :length] = req.prompt
        s, toks, table = self._one_hot_call(self.t_side, row, padded)
        lengths = np.ones((self.n_shards,), np.int32)
        lengths[s] = length
        rids = np.zeros((self.n_shards,), np.int32)
        rids[s] = rid
        self.pool, tok = self._prefill_fn(width)(
            self.params, self.pool, table, toks,
            jnp.asarray(lengths), jnp.asarray(rids))
        if self.d_side is not None:
            _, dtoks, dtable = self._one_hot_call(self.d_side, row, padded)
            self.d_side.pool = self._draft_chunk(
                self.draft_params, self.d_side.pool, dtable, dtoks,
                jnp.asarray(self.prefix_len, jnp.int32))
        tok.copy_to_host_async()    # transfer overlaps later dispatches
        state = _Row(rid=rid, req=req, pos=self.prefix_len + length, step=1,
                     last=0, out=[], worst_pages=wt, worst_draft=wd,
                     t_admit=t_admit, limit=need)
        active[row] = state
        self._pcache_insert(row, state)
        return row, state, tok, s

    def _admit_cached(self, row: int, rid: int, req: Request, wt: int,
                      wd: int, need: int, active: Dict[int, _Row],
                      plan: _PrefixPlan, t_admit: float) -> tuple:
        """Admission with a mapped cached prefix: prefill ONLY the
        uncached tail at its true offset (the jitted traced-offset
        chunk writer — one compile per tail-width bucket) and sample
        the first token from the prompt's last position.  A
        page-aligned full hit first copies the deepest cached page into
        a fresh own page (``_copy_page`` copy-on-write) so the
        last-token rewrite never touches shared state."""
        side = self.t_side
        E = self.prefix_len + int(req.prompt.size)
        if plan.cow:
            cow_node = plan.nodes[-1]
            src = cow_node.page
            self._pcache.unmap_last(row)
            side.ensure(row, side.shared_len
                        + len(plan.nodes) * self.page_size)
            dst = np.full((self.n_shards,), side.sink, np.int32)
            dst[side.alloc.shard_of(row)] = side.alloc.rows[row][0]
            side.pool = side.copy(side.pool, src, dst)
            if self.d_side is not None:
                # The deepest page's DRAFT twin gets the same one-token
                # rewrite at E-1 (the spec round's draft scan writes
                # it), so it is copied-on-write symmetrically.
                dside = self.d_side
                dside.ensure(row, dside.shared_len
                             + len(plan.nodes) * self.page_size)
                ddst = np.full((self.n_shards,), dside.sink, np.int32)
                ddst[dside.alloc.shard_of(row)] = dside.alloc.rows[row][0]
                dside.pool = dside.copy(dside.pool, cow_node.dpage, ddst)
            # The reference protected the source page(s) through the
            # ensure() above (eviction runs under allocation pressure);
            # the copies are dispatched, so it can be dropped now.
            self._pcache.release_nodes(row, [cow_node])
            self._pcache.count("cow_copies")
        ts = plan.tail_start
        tlen = E - ts
        w = -(-tlen // self.prefill_bucket) * self.prefill_bucket
        # Clamp the allocation at the reservation: pad positions past
        # ``need`` write reserved-but-unread slots or sink columns (the
        # cold path's prompt padding behaves identically), and
        # allocations beyond ``worst_pages`` would corrupt headroom().
        self._ensure_sides(row, min(ts + w, need))
        padded = np.zeros((1, w), np.int32)
        padded[0, :tlen] = req.prompt[req.prompt.size - tlen:]
        s, toks, table = self._one_hot_call(side, row, padded)
        caps = np.full((self.n_shards,), -1, np.int32)
        caps[s] = tlen - 1
        rids = np.zeros((self.n_shards,), np.int32)
        rids[s] = rid
        self.pool, tok = self._tail_prefill(
            self.params, self.pool, table, toks,
            jnp.asarray(ts, jnp.int32), jnp.asarray(caps),
            jnp.asarray(rids))
        if self.d_side is not None:
            # The draft pool's tail: the same uncached suffix written
            # at the same offset through the draft chunk writer — its
            # cached prefix pages (the twins mapped above) already
            # cover [shared_len, ts).
            _, dtoks, dtable = self._one_hot_call(self.d_side, row,
                                                  padded)
            self.d_side.pool = self._draft_chunk(
                self.draft_params, self.d_side.pool, dtable, dtoks,
                jnp.asarray(ts, jnp.int32))
        tok.copy_to_host_async()    # transfer overlaps later dispatches
        state = _Row(rid=rid, req=req, pos=E, step=1, last=0, out=[],
                     worst_pages=wt, worst_draft=wd, t_admit=t_admit,
                     limit=need)
        active[row] = state
        self._pcache_insert(row, state)
        return row, state, tok, s

    def _pcache_insert(self, row: int, state: _Row) -> None:
        """Publish ``row``'s freshly prefilled full prompt pages into
        the prefix cache (no-op without one)."""
        if self._pcache is None:
            return
        digs = self._req_digests(state.req)
        if digs:
            self._pcache.insert_row(
                row, self.t_side.alloc.shard_of(row), digs, state)

    def _admit_finalize(self, state: _Row,
                        tok: int) -> Optional[Completion]:
        """Record a burst-synced first token; Completion when it already
        finishes the request."""
        state.t_first = time.perf_counter()
        if state.out:
            # Resumed suspended import: the stream up to the suspension
            # point is already in place (and a finished row is never
            # suspended, so no instant completion here either).
            return None
        state.last = tok
        state.out = [tok]
        if tok == state.req.stop_token or state.req.max_new_tokens == 1:
            return self._completion(state)
        return None

    def _finalize_burst(self, burst: list, active: Dict[int, _Row],
                        free_rows: List[int]) -> Iterator[Completion]:
        """Drain a dispatch burst: fetch each admission's first token
        (the async transfers have been in flight since dispatch, so
        these mostly find the data ready) and yield any instant
        completions.  Clears ``burst`` in place."""
        for row, state, tok, s in burst:
            done = self._admit_finalize(state, int(np.asarray(tok)[s]))
            if done is not None:
                self._finish_completed(row, active, free_rows)
                yield done
        burst.clear()

    def _advance_prefill(self, active: Dict[int, _Row]) -> Optional[int]:
        """Write ONE chunk of the oldest still-prefilling row; flips the
        row to decoding once its whole padded prompt is in.  Returns the
        row id when that row just finished a request outright (first
        token == stop, or max_new_tokens == 1)."""
        filling = [(row.rid, r) for r, row in active.items()
                   if not row.decoding]
        if not filling:
            return None
        _, r = min(filling)
        row = active[r]
        c = self.prefill_chunk
        chunk = row.padded[:, row.filled:row.filled + c]
        length = row.req.prompt.size
        cap = length - 1 - row.filled       # in-range only on last chunk
        s, ctoks, table = self._one_hot_call(self.t_side, r, chunk)
        caps = np.full((self.n_shards,), -1, np.int32)
        caps[s] = cap
        rids = np.zeros((self.n_shards,), np.int32)
        rids[s] = row.rid
        self.pool, tok = self._chunk_prefill(
            self.params, self.pool, table, ctoks,
            jnp.asarray(self.prefix_len + row.filled, jnp.int32),
            jnp.asarray(caps), jnp.asarray(rids))
        if self.d_side is not None:
            # The draft's prompt chunks advance in lockstep so it is
            # ready to propose the moment the row flips to decoding.
            _, dtoks, dtable = self._one_hot_call(self.d_side, r, chunk)
            self.d_side.pool = self._draft_chunk(
                self.draft_params, self.d_side.pool, dtable, dtoks,
                jnp.asarray(self.prefix_len + row.filled, jnp.int32))
        row.filled += c
        if row.filled < row.padded.shape[1]:
            return None
        tok = int(np.asarray(tok)[s])       # the capture chunk's sample
        row.t_first = time.perf_counter()
        row.last = tok
        row.out.append(tok)
        row.decoding = True
        # Publish the now fully-dispatched prompt pages; chunked mode
        # must wait until here — at admission the chunks had not been
        # written, and a concurrent hit would have mapped garbage.
        self._pcache_insert(r, row)
        if tok == row.req.stop_token or row.req.max_new_tokens == 1:
            return r
        return None

    def _step_fused(self, active: Dict[int, _Row],
                    free_rows: List[int]) -> Iterator[Completion]:
        """One FUSED tick: the decode block over every decoding row
        plus up to ``(tokens_per_tick - n_decode*K) // c`` prefill
        chunk slots (oldest filling rows first, at most one chunk per
        row — chunk N+1's attention reads chunk N's cache writes, so a
        row cannot coalesce with itself), all in ONE dispatch and ONE
        host sync.  The budget floor is one slot, so a saturated
        decode set still fills exactly as fast as the phase-split tick;
        the budget ceiling is what stops a burst of long prompts from
        monopolizing ticks.  Chunk bookkeeping mirrors
        :meth:`_advance_prefill` (a row whose last chunk lands here
        flips to decoding with its sampled first token and joins the
        NEXT tick's block — tokens are pure (rid, step) functions, so
        the stream is unchanged); decode commits mirror :meth:`_step`."""
        K = self.multi_step
        c = self.prefill_chunk
        decoding = {r: row for r, row in active.items() if row.decoding}
        filling = sorted((row.rid, r) for r, row in active.items()
                         if not row.decoding)
        slots = max(1, (self.tokens_per_tick - len(decoding) * K) // c)
        picks = [r for _, r in filling[:slots]]
        S = self._pow2(len(picks))
        ctable = np.full((S, self.t_side.np_max), self.t_side.sink,
                         np.int32)
        chunks = np.zeros((S, c), np.int32)
        cpos = np.zeros((S,), np.int32)
        caps = np.full((S,), -1, np.int32)
        crids = np.zeros((S,), np.int32)
        tbl = self.t_side.table_np()
        for i, r in enumerate(picks):
            row = active[r]
            ctable[i] = tbl[r]
            chunks[i] = row.padded[0, row.filled:row.filled + c]
            cpos[i] = self.prefix_len + row.filled
            caps[i] = row.req.prompt.size - 1 - row.filled
            crids[i] = row.rid
        toks = np.zeros((self.rows,), np.int32)
        positions = np.zeros((self.rows,), np.int32)
        rids = np.zeros((self.rows,), np.int32)
        steps = np.zeros((self.rows,), np.int32)
        for r, row in decoding.items():
            self._ensure_sides(r, min(row.pos + K, row.limit))
            toks[r] = row.last
            positions[r] = row.pos
            rids[r] = row.rid
            steps[r] = row.step
        table = self.t_side.decode_table(active, decoding)
        tb0 = time.perf_counter()
        self.pool, nxt, first = self._fused_step(
            self.params, self.pool, table, jnp.asarray(toks),
            jnp.asarray(positions), jnp.asarray(rids),
            jnp.asarray(steps), jnp.asarray(ctable),
            jnp.asarray(chunks), jnp.asarray(cpos), jnp.asarray(caps),
            jnp.asarray(crids))
        nxt = np.asarray(nxt)       # ONE sync covers chunks AND block
        first = np.asarray(first)
        self.fused_ticks += 1
        self.fused_chunk_tokens += len(picks) * c
        self.fused_decode_tokens += len(decoding) * K
        self.flight.record(
            {"name": "decode.block", "mode": "fused",
             "dur": round((time.perf_counter() - tb0) * 1000.0, 3),
             "rows": len(decoding), "k": K, "chunks": len(picks)})
        for i, r in enumerate(picks):
            row = active[r]
            row.filled += c
            if row.filled < row.padded.shape[1]:
                continue
            tok = int(first[i])     # the capture chunk's sample
            row.t_first = time.perf_counter()
            row.last = tok
            row.out.append(tok)
            row.decoding = True
            self._pcache_insert(r, row)
            if tok == row.req.stop_token or row.req.max_new_tokens == 1:
                done = self._completion(row)
                self._finish_completed(r, active, free_rows)
                yield done
        for r in list(decoding):
            row = active[r]
            for j in range(K):
                tok = int(nxt[r, j])
                row.out.append(tok)
                row.step += 1
                row.pos += 1
                row.last = tok
                if tok == row.req.stop_token or row.step >= \
                        row.req.max_new_tokens:
                    done = self._completion(row)
                    self._finish_completed(r, active, free_rows)
                    yield done
                    break

    def _step(self, active: Dict[int, _Row],
              free_rows: List[int]) -> Iterator[Completion]:
        """One K-step block (``multi_step``; K=1 = classic per-token
        tick): a single dispatch decodes K tokens per decoding row and
        the host syncs one [rows, K] block.  Rows that stop (or exhaust
        quota) mid-block have their remaining in-block tokens discarded
        here; the corresponding device writes landed inside the row's
        reservation (ensure clamped at ``row.limit``) or on sink
        columns, so no live state was touched.  Admission and
        chunked-prefill advance happen between blocks.  (Chunked prefill
        keeps still-filling rows out: their table rows mask to the sink
        so the batched scatter cannot touch their pages.)"""
        K = self.multi_step
        toks = np.zeros((self.rows,), np.int32)
        positions = np.zeros((self.rows,), np.int32)
        rids = np.zeros((self.rows,), np.int32)
        steps = np.zeros((self.rows,), np.int32)
        decoding = {r: row for r, row in active.items() if row.decoding}
        for r, row in decoding.items():
            self._ensure_sides(r, min(row.pos + K, row.limit))
            toks[r] = row.last
            positions[r] = row.pos
            rids[r] = row.rid
            steps[r] = row.step
        table = self.t_side.decode_table(active, decoding)
        tb0 = time.perf_counter()
        self.pool, nxt = self._decode(
            self.params, self.pool, table, jnp.asarray(toks),
            jnp.asarray(positions), jnp.asarray(rids), jnp.asarray(steps))
        nxt = np.asarray(nxt)               # ONE host sync per K tokens
        self.flight.record(
            {"name": "decode.block", "mode": "sync",
             "dur": round((time.perf_counter() - tb0) * 1000.0, 3),
             "rows": len(decoding), "k": K})
        for r in list(decoding):
            row = active[r]
            for j in range(K):
                tok = int(nxt[r, j])
                row.out.append(tok)
                row.step += 1
                row.pos += 1
                row.last = tok
                if tok == row.req.stop_token or row.step >= \
                        row.req.max_new_tokens:
                    done = self._completion(row)
                    self._finish_completed(r, active, free_rows)
                    yield done
                    break

    def _step_overlap(self, active: Dict[int, _Row],
                      free_rows: List[int]) -> Iterator[Completion]:
        """One OVERLAP K-block tick (K=1 = the classic double-buffered
        tick): dispatch the next K-step block without waiting for the
        previous one — rows in the previous dispatch chain from its
        device-resident LAST token (``use_dev``), so the device never
        idles on a host round-trip — then retire the previous block
        (host bookkeeping one block late).  Deterministic state (pos,
        step) advances at dispatch; token-dependent state (out, last,
        stop detection) at retire.  Stops surface one block late: the
        extra dispatched block's writes stay inside the row's
        reservation clamp or on sink columns and its tokens fail the
        rid-checked ticket.  Quota gating at dispatch uses
        dispatched-token counts, so a block may overrun a quota by up to
        K-1 tokens; retire truncates.  Token streams are IDENTICAL to
        the non-overlapping batcher's — same ops, same inputs, only the
        sync point moves."""
        K = self.multi_step
        dispatch = {r: row for r, row in active.items()
                    if row.decoding and row.step < row.req.max_new_tokens}
        prev = self._inflight
        if dispatch:
            toks = np.zeros((self.rows,), np.int32)
            use_dev = np.zeros((self.rows,), bool)
            positions = np.zeros((self.rows,), np.int32)
            rids = np.zeros((self.rows,), np.int32)
            steps = np.zeros((self.rows,), np.int32)
            prev_ticket = {} if prev is None else prev[1]
            for r, row in dispatch.items():
                self._ensure_sides(r, min(row.pos + K, row.limit))
                if prev_ticket.get(r) == row.rid:
                    use_dev[r] = True   # token = prev block's last output
                else:
                    toks[r] = row.last  # fresh admission / chunk flip
                positions[r] = row.pos
                rids[r] = row.rid
                steps[r] = row.step
            table = self.t_side.decode_table(active, dispatch)
            prev_nxt = (prev[0] if prev is not None
                        else jnp.zeros((self.rows, K), jnp.int32))
            self.pool, nxt = self._decode(
                self.params, self.pool, table, jnp.asarray(toks),
                prev_nxt, jnp.asarray(use_dev), jnp.asarray(positions),
                jnp.asarray(rids), jnp.asarray(steps))
            nxt.copy_to_host_async()    # transfer overlaps the block
            self._inflight = (nxt,
                              {r: row.rid for r, row in dispatch.items()})
            for row in dispatch.values():
                row.pos += K
                row.step += K
        else:
            self._inflight = None
        if prev is not None:
            yield from self._retire(prev, active, free_rows)

    def _step_pipelined(self, active: Dict[int, _Row],
                        free_rows: List[int]) -> Iterator[Completion]:
        """One PIPELINED K-block tick (``pipeline_depth=1``): dispatch
        block N+1 BEFORE syncing block N, like :meth:`_step_overlap`,
        but with the whole decode carry — last token, positions, AND
        steps — resident on device: the jitted block returns them as
        outputs that feed the next dispatch directly, so a steady-state
        block uploads nothing at all (the overlap path re-uploads four
        [rows] vectors per block).  Host-side inputs (fresh admissions'
        token/position/step, the rid vector, the ``use_host`` merge
        mask) are rebuilt only when the dispatch set actually changed —
        admission, a finish, a chunked-prefill flip — exactly like the
        page table, and are cached device constants otherwise.

        Stop/quota detection lags one block; the overshoot block's
        writes land inside the row's clamped reservation or on sink
        columns and its tokens fail :meth:`_retire`'s rid-checked
        ticket — the discard semantics ``_step`` already documents for
        mid-block stops — so token streams are IDENTICAL to
        ``pipeline_depth=0`` (same ops, same (rid, step) sample folds,
        only the sync point moves)."""
        K = self.multi_step
        dispatch = {r: row for r, row in active.items()
                    if row.decoding and row.step < row.req.max_new_tokens}
        prev = self._inflight
        if dispatch:
            prev_ticket = {} if prev is None else prev[1]
            ticket = {r: row.rid for r, row in dispatch.items()}
            # Rows entering this block from HOST values: fresh
            # admissions, chunked-prefill flips, re-admissions into a
            # freed row — anything the device carry does not cover.
            fresh = frozenset(r for r, rid in ticket.items()
                              if prev_ticket.get(r) != rid)
            for r, row in dispatch.items():
                self._ensure_sides(r, min(row.pos + K, row.limit))
            table = self.t_side.decode_table(active, dispatch)
            key = (tuple(sorted(ticket.items())), fresh)
            host = self._pipe_host
            if host is None or host[0] != key:
                toks = np.zeros((self.rows,), np.int32)
                use_host = np.zeros((self.rows,), bool)
                positions = np.zeros((self.rows,), np.int32)
                steps = np.zeros((self.rows,), np.int32)
                rids = np.zeros((self.rows,), np.int32)
                for r, row in dispatch.items():
                    rids[r] = row.rid
                    if r in fresh:
                        use_host[r] = True
                        toks[r] = row.last
                        positions[r] = row.pos
                        steps[r] = row.step
                host = (key, jnp.asarray(use_host), jnp.asarray(toks),
                        jnp.asarray(positions), jnp.asarray(steps),
                        jnp.asarray(rids))
                self._pipe_host = host
            carry = self._pipe_carry
            if carry is None:       # pipeline start: fresh rows only
                carry = (jnp.zeros((self.rows,), jnp.int32),
                         jnp.zeros((self.rows,), jnp.int32),
                         jnp.zeros((self.rows,), jnp.int32))
            self.pool, nxt, ct, cp, cs = self._decode(
                self.params, self.pool, table, host[1], host[2], host[3],
                host[4], carry[0], carry[1], carry[2], host[5])
            nxt.copy_to_host_async()    # transfer overlaps the block
            self._pipe_carry = (ct, cp, cs)
            self._inflight = (nxt, ticket)
            for row in dispatch.values():
                row.pos += K
                row.step += K
        else:
            self._inflight = None
            self._pipe_carry = self._pipe_host = None
        if prev is not None:
            yield from self._retire(prev, active, free_rows)

    def _retire(self, inflight, active: Dict[int, _Row],
                free_rows: List[int]) -> Iterator[Completion]:
        """Sync ONE overlap K-block (a block behind the newest) and do
        its token-dependent bookkeeping; rows that stopped at the
        previous retire (or were re-admitted since) fail the rid check
        and their block is dropped."""
        nxt, ticket = inflight
        tb0 = time.perf_counter()
        nxt = np.asarray(nxt)           # host sync: one block behind
        # The lagged-block sync time IS the pipelined loop's per-block
        # cost (dispatch is a non-blocking enqueue): one flight entry
        # per block, like _step's synchronous one.
        self.flight.record(
            {"name": "decode.block",
             "mode": "pipelined" if self._pipelined else "overlap",
             "dur": round((time.perf_counter() - tb0) * 1000.0, 3),
             "rows": len(ticket), "k": self.multi_step})
        for r, rid in ticket.items():
            row = active.get(r)
            if row is None or row.rid != rid:
                continue                # overshoot block of a freed row
            for j in range(self.multi_step):
                tok = int(nxt[r, j])
                row.out.append(tok)
                row.last = tok
                if (tok == row.req.stop_token
                        or len(row.out) >= row.req.max_new_tokens):
                    done = self._completion(row)
                    # _finish_completed parks session KV first: the
                    # export clamps to the committed boundary, so the
                    # lagged host view cannot overshoot the artifact.
                    self._finish_completed(r, active, free_rows)
                    yield done
                    break

    def _step_spec(self, active: Dict[int, _Row],
                   free_rows: List[int]) -> Iterator[Completion]:
        """One speculative dispatch over every decoding row: commit
        each row's leading accepted run + correction (1..n_draft+1
        tokens) — times R in-graph rounds when multi_step composes
        (R = _spec_rounds > 1), committed round-by-round so stop/quota
        truncation is exact per round."""
        R = max(1, self._spec_rounds)
        toks = np.zeros((self.rows,), np.int32)
        # Rows with no live request still run the jitted round: park their
        # positions at max_len (within the draft cache's +n_draft slack,
        # clamped onto the sink page in the paged target) so their dummy
        # draft writes can never clobber the broadcast prefix at positions
        # 0..n_draft-1 of a draft-cache row a future request will reuse.
        positions = np.full((self.rows,), self.max_len, np.int32)
        rids = np.zeros((self.rows,), np.int32)
        steps = np.zeros((self.rows,), np.int32)
        decoding = {r: row for r, row in active.items() if row.decoding}
        for r, row in decoding.items():
            # The verify chunk writes positions [pos, pos + n_draft] (and
            # the draft's k+1 scan steps write the same range of ITS
            # pool); R fused rounds extend the worst case to
            # R*(n_draft+1), clamped at limit — past-limit writes land
            # on sink-clamped columns and their tokens are discarded at
            # commit (same overrun argument as plain multi_step).
            self._ensure_sides(r, min(row.pos + R * (self.n_draft + 1),
                                      row.limit))
            toks[r] = row.last
            positions[r] = row.pos
            rids[r] = row.rid
            steps[r] = row.step
        table = self.t_side.decode_table(active, decoding)
        dtable = self.d_side.decode_table(active, decoding)
        self.pool, self.d_side.pool, g, n_commit = self._spec_round(
            self.params, self.pool, self.draft_params, self.d_side.pool,
            table, dtable, jnp.asarray(toks), jnp.asarray(positions),
            jnp.asarray(rids), jnp.asarray(steps))
        g = np.asarray(g)
        n_commit = np.asarray(n_commit)
        if R == 1:
            g, n_commit = g[None], n_commit[None]   # [R=1, rows, ...]
        # Observability: the acceptance rate is THE speculative-serving
        # health number (a weak draft only costs rate, never correctness).
        self.spec_rounds += R
        for i in range(R):
            live = [r for r in decoding if r in active]
            if not live:
                break
            self.spec_committed += int(sum(int(n_commit[i, r])
                                           for r in live))
            self.spec_row_rounds += len(live)
            yield from self._commit_rows(g[i], n_commit[i], live, active,
                                         free_rows)

    def _commit_rows(self, g, nc, rows, active: Dict[int, _Row],
                     free_rows: List[int]) -> Iterator[Completion]:
        """Commit one speculative round's outputs to ``rows`` — ONE code
        path for the sync (_step_spec) and overlap (_retire_spec) loops,
        so their truncation/finish semantics cannot diverge.  Quota and
        stop truncation: either way the row FINISHES, so the committed-
        stream/cache (and overlap device-carry) consistency question is
        moot."""
        for r in rows:
            row = active[r]
            emit = list(g[r, :int(nc[r])])
            remaining = row.req.max_new_tokens - row.step
            emit = emit[:remaining]
            if row.req.stop_token is not None and \
                    row.req.stop_token in emit:
                emit = emit[:emit.index(row.req.stop_token) + 1]
            row.out.extend(int(t) for t in emit)
            row.step += len(emit)
            row.pos += len(emit)
            row.last = int(emit[-1]) if emit else row.last
            if (row.step >= row.req.max_new_tokens
                    or (row.req.stop_token is not None
                        and row.out and row.out[-1]
                        == row.req.stop_token)):
                done = self._completion(row)
                self._finish_completed(r, active, free_rows)
                yield done

    def _step_spec_overlap(self, active: Dict[int, _Row],
                           free_rows: List[int]) -> Iterator[Completion]:
        """One OVERLAP speculative round: dispatch round t WITHOUT
        syncing round t-1 — continuing rows' token/position/step carry
        on device (commit counts are computed in-graph), the host's
        row.pos/step view lags one retire behind and only backs pages
        (worst case: the un-retired round advanced n_draft+1 and this
        round writes n_draft+1 more).  Endings (stop AND quota — counts
        are device-decided) surface one round late; the overshoot
        round's output is dropped by the rid-checked ticket and its
        writes land in the row's reserved overshoot pages / the sink."""
        k1 = self.n_draft + 1
        dispatch = {r: row for r, row in active.items()
                    if row.decoding and row.step < row.req.max_new_tokens}
        prev = self._inflight
        if dispatch:
            toks = np.zeros((self.rows,), np.int32)
            positions = np.full((self.rows,), self.max_len, np.int32)
            steps = np.zeros((self.rows,), np.int32)
            rids = np.zeros((self.rows,), np.int32)
            use_dev = np.zeros((self.rows,), bool)
            prev_ticket = {} if prev is None else prev[4]
            for r, row in dispatch.items():
                self._ensure_sides(r, min(row.pos + 2 * k1, self.max_len))
                if prev_ticket.get(r) == row.rid:
                    use_dev[r] = True   # continue from device carry
                else:
                    toks[r] = row.last
                    positions[r] = row.pos
                    steps[r] = row.step
                rids[r] = row.rid
            table = self.t_side.decode_table(active, dispatch)
            dtable = self.d_side.decode_table(active, dispatch)
            if prev is None:
                z = jnp.zeros((self.rows,), jnp.int32)
                carry = (jnp.zeros((self.rows, k1), jnp.int32), z, z, z)
            else:
                carry = prev[:4]
            (self.pool, self.d_side.pool, g, nc, pos_d,
             steps_d) = self._spec_round(
                self.params, self.pool, self.draft_params,
                self.d_side.pool, table, dtable, jnp.asarray(toks),
                jnp.asarray(positions), jnp.asarray(rids),
                jnp.asarray(steps), jnp.asarray(use_dev), *carry)
            g.copy_to_host_async()      # transfers overlap the round
            nc.copy_to_host_async()
            self._inflight = (g, nc, pos_d, steps_d,
                              {r: row.rid for r, row in dispatch.items()})
        else:
            self._inflight = None
        if prev is not None:
            yield from self._retire_spec(prev, active, free_rows)

    def _retire_spec(self, inflight, active: Dict[int, _Row],
                     free_rows: List[int]) -> Iterator[Completion]:
        """Sync ONE overlap speculative round (a round behind the
        newest) and do its token-dependent bookkeeping — the same commit
        semantics as _step_spec, rid-gated so a finished row's overshoot
        round is dropped.  Truncation (quota or stop) only ever happens
        on a FINISHING row, so continuing rows advance by exactly the
        device-side commit count and the host view stays consistent
        with the in-graph position/step carry."""
        g, nc, _, _, ticket = inflight
        g = np.asarray(g)       # host sync: one round behind dispatch
        nc = np.asarray(nc)
        live = [r for r, rid in ticket.items()
                if r in active and active[r].rid == rid]
        self.spec_rounds += 1
        self.spec_row_rounds += len(live)
        self.spec_committed += int(sum(int(nc[r]) for r in live))
        yield from self._commit_rows(g, nc, live, active, free_rows)

    # -- end-to-end deadlines ----------------------------------------------

    def _cancel_expired(self, active: Dict[int, _Row],
                        free_rows: List[int]) -> Iterator["Expired"]:
        """Cancel every resident row whose deadline has passed —
        exactly like a finish (pages released, row freed for the next
        admission) except an :class:`Expired` is yielded instead of a
        Completion.  Lag modes (overlap/pipelined) may have one more
        block in flight for the row; its writes land inside the clamped
        reservation or on sink columns and its tokens fail the
        rid-checked retire ticket, the same discard semantics a
        mid-block stop already has."""
        expired = [r for r, row in active.items()
                   if row.req.deadline is not None and row.req.expired]
        for r in expired:
            row = active[r]
            self.deadline_cancels += 1
            rid, req = row.rid, row.req
            self._trace_event(req, "deadline_cancel", rid=rid,
                              where="resident", step=row.step)
            self._finish(r, active, free_rows)
            yield Expired(rid=rid, request=req)

    # -- priority preemption / drain migration ----------------------------

    def _suspendable(self, state: _Row) -> bool:
        """A row whose mid-stream state can be snapshotted right now:
        it is decoding (a still-filling chunked prefill has no complete
        KV to export), its first token has been fetched (an un-settled
        admission burst entry has not), and the mode supports per-row
        export at all."""
        return (self.preemptible and state.decoding and bool(state.out)
                and state.t_first > 0)

    def _suspend_row(self, r: int, active: Dict[int, _Row],
                     free_rows: List[int]) -> dict:
        """Snapshot row ``r`` into a resumable KV artifact (pages past
        the shared prefix + sampler state incl. the emitted tokens) and
        release it — a suspended request IS a KV export, re-admitted
        through ``submit(prefilled=...)`` here or on any matching
        batcher."""
        state = active[r]
        art = self._export_row(r, state)
        self._finish(r, active, free_rows)
        return art

    def _resume_fits(self, req: Request) -> bool:
        """Whether ``req``'s suspended artifact could EVER re-import
        into this pool: the import backs every position with own pages
        (no prefix-cache discount — the pages arrive in the payload),
        so a row admitted only thanks to a deep cache plan on a tight
        pool must not be suspended locally — its resume would exceed
        the pool outright and the parked artifact could never land."""
        side = self.t_side
        reserved = 1 + len(side.shared_pages) \
            + (1 if side.tail_template is not None else 0)
        wt, wd, _ = self._worst_pages(req)
        if wt > side.n_pages - reserved:
            return False
        if self.d_side is not None:
            dside = self.d_side
            dreserved = 1 + len(dside.shared_pages) \
                + (1 if dside.tail_template is not None else 0)
            return wd <= dside.n_pages - dreserved
        return True

    def _maybe_preempt(self, priority: int, active: Dict[int, _Row],
                       free_rows: List[int]) -> bool:
        """Suspend the lowest-priority suspendable row STRICTLY below
        ``priority`` (ties: the newest) and park its artifact for local
        resumption; False when no such victim exists.  Strictness is
        the anti-thrash rule: equal-priority work never preempts, and a
        parked row can only displace classes below its own."""
        if not self.preemptible:
            return False
        victims = [(row.req.priority, -row.rid, r)
                   for r, row in active.items()
                   if self._suspendable(row)
                   and row.req.priority < priority
                   and self._resume_fits(row.req)]
        if not victims:
            return False
        _, _, r = min(victims)
        req = active[r].req
        self._trace_event(req, "preempt", by_priority=priority,
                          priority=req.priority)
        art = self._suspend_row(r, active, free_rows)
        self._parked.append(Prefilled(req, art))
        self.preemptions += 1
        return True

    def _preempt_everything(self, pending: deque, active: Dict[int, _Row],
                            free_rows: List[int],
                            source: Optional[SubmissionQueue]
                            ) -> Iterator[Suspended]:
        """:meth:`preempt_all`'s loop side — drain migration: yield a
        :class:`Suspended` for EVERY in-flight request.  Resident
        suspendable rows carry their KV artifact; everything else
        (still-filling rows, queued arrivals, modes without per-row
        export) requeues with ``artifact=None`` — lossless either way,
        since nothing was delivered and completions are deterministic.
        Parked artifacts and not-yet-admitted imports keep theirs."""
        if source is not None:
            # Queued arrivals must resolve too — a drained replica dies
            # soon after, and a dangling submitter would hang forever.
            while True:
                item = source.poll(False)
                if item is None or item is _CLOSED:
                    break
                pending.append(item)
        # Stale overlap/pipeline device state dies with its rows.
        self._inflight = None
        self._pipe_carry = self._pipe_host = None
        for r in sorted(active):
            state = active[r]
            art = (self._export_row(r, state)
                   if self._suspendable(state) else None)
            req = state.req
            rid = state.rid
            self._trace_event(req, "suspend", rid=rid,
                              exported=art is not None)
            self._finish(r, active, free_rows)
            yield Suspended(rid=rid, request=req, artifact=art)
        while self._parked:
            pre = self._parked.popleft()
            yield Suspended(rid=int(pre.artifact.get("rid", -1)),
                            request=pre.request, artifact=pre.artifact)
        while pending:
            item = pending.popleft()
            if isinstance(item, Prefilled):
                yield Suspended(rid=int(item.artifact.get("rid", -1)),
                                request=item.request,
                                artifact=item.artifact)
            else:
                yield Suspended(rid=-1, request=item, artifact=None)
        self._preempt_event.clear()

    @staticmethod
    def _trace_event(req: Request, name: str, **attrs) -> None:
        """One batcher event on the request's trace (no-op without
        one — local runs cost nothing)."""
        tr = getattr(req, "trace", None)
        if tr is not None:
            tr.event("batcher", name, **attrs)

    def _completion(self, row: _Row) -> Completion:
        now = time.perf_counter()
        tr = getattr(row.req, "trace", None)
        if tr is not None:
            # The two phase spans every waterfall wants: admission ->
            # first token (prefill + queue-for-burst) and first token
            # -> finish (decode), from the row's own perf_counter
            # stamps — hop-local by construction.
            tr.span_between("batcher", "prefill", row.t_admit,
                            max(row.t_first, row.t_admit), rid=row.rid)
            tr.span_between("batcher", "decode",
                            max(row.t_first, row.t_admit), now,
                            rid=row.rid, tokens=len(row.out))
        return Completion(rid=row.rid, request=row.req,
                          tokens=list(row.out),
                          ttft_s=row.t_first - row.t_admit,
                          total_s=now - row.t_admit)

    def _finish(self, row: int, active: Dict[int, _Row],
                free_rows: List[int]) -> None:
        active.pop(row, None)
        self.t_side.release(row)
        if self.d_side is not None:
            self.d_side.release(row)
        free_rows.append(row)
