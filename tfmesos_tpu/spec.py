"""Cluster model: Job groups, Task records, resource Offers.

Mirrors the capability surface of the reference's ``Job`` (scheduler.py:21-31)
and ``Task`` (scheduler.py:34-177) but re-targeted at TPU pod slices: the GPU
resource dimension (``gpus``) becomes ``chips`` (TPU chips per task), and
``to_task_info`` renders the Mesos **v1 HTTP API JSON** shape rather than the
protobuf-shaped addict.Dict the reference builds, because our Mesos backend
speaks the v1 HTTP API directly (no pymesos).
"""

from __future__ import annotations

import base64
import os
import sys
import uuid
from dataclasses import InitVar, dataclass, field
from typing import Any, Dict, List, Optional

from tfmesos_tpu.wire import (TOKEN_ENV as _TOKEN_ENV,
                              TOKEN_FILE_ENV as _TOKEN_FILE_ENV)


@dataclass
class Job:
    """A homogeneous group of tasks (reference: scheduler.py:21-31).

    ``start`` supports launching a partial index range, exactly as the
    reference allows (scheduler.py:29-31).  ``gpus=`` is accepted as a
    drop-in alias for ``chips`` so reference job specs work unchanged.
    """

    name: str
    num: int
    cpus: float = 1.0
    mem: float = 1024.0
    chips: int = 0
    cmd: Optional[str] = None
    start: int = 0
    gpus: InitVar[Optional[int]] = None

    def __post_init__(self, gpus: Optional[int] = None) -> None:
        if gpus is not None:
            if self.chips:
                raise ValueError(f"job {self.name!r}: pass chips or gpus, not both")
            self.chips = gpus
        if self.num <= 0:
            raise ValueError(f"job {self.name!r}: num must be positive, got {self.num}")
        if not 0 <= self.start < self.num:
            raise ValueError(f"job {self.name!r}: start must be in [0, num), "
                             f"got start={self.start} num={self.num}")


def normalize_jobs(jobs: Any) -> List[Job]:
    """Accept a Job, a dict of Job kwargs, or a list of either — the exact
    normalization contract of the reference API (tfmesos/__init__.py:9-16)."""
    if isinstance(jobs, (Job, dict)):
        jobs = [jobs]
    out = []
    for j in jobs:
        if isinstance(j, dict):
            j = Job(**j)
        if not isinstance(j, Job):
            raise TypeError(f"cannot interpret {j!r} as a Job")
        out.append(j)
    return out


@dataclass
class Offer:
    """A resource offer from whichever backend is in use.

    For the Mesos backend this is parsed out of a v1 ``OFFERS`` event; the
    local backend synthesizes one describing the host.
    """

    id: str
    agent_id: str
    hostname: str
    cpus: float = 0.0
    mem: float = 0.0
    chips: int = 0
    #: resource name the chips were advertised under ("tpus", or "gpus" on
    #: GPU agents) — TaskInfo must request them by the same name.
    chips_resource: str = "tpus"
    attributes: Dict[str, str] = field(default_factory=dict)
    raw: Optional[dict] = None


@dataclass
class TaskStatus:
    task_id: str
    state: str  # TASK_RUNNING / TASK_FINISHED / TASK_FAILED / ...
    message: str = ""
    agent_id: str = ""
    uuid: str = ""  # ack handle for Mesos explicit acknowledgements

    TERMINAL = frozenset(
        [
            "TASK_FINISHED",
            "TASK_FAILED",
            "TASK_KILLED",
            "TASK_ERROR",
            "TASK_LOST",
            "TASK_DROPPED",
            "TASK_GONE",
        ]
    )

    @property
    def terminal(self) -> bool:
        return self.state in self.TERMINAL


class Task:
    """One schedulable cluster member (reference: scheduler.py:34-177).

    Keeps the reference's lifecycle fields — a fresh ``uuid4`` id per launch
    attempt, ``offered`` flag, registered ``addr``, live control
    ``connection``, ``initialized`` flag — and its renderer to a backend
    TaskInfo.  (The reference misspells ``initalized``; we do not.)
    """

    def __init__(self, job_name: str, task_index: int, cpus: float = 1.0,
                 mem: float = 1024.0, chips: int = 0, cmd: Optional[str] = None,
                 volumes: Optional[Dict[str, str]] = None):
        self.job_name = job_name
        self.task_index = task_index
        self.cpus = cpus
        self.mem = mem
        self.chips = chips
        self.cmd = cmd
        self.volumes = volumes or {}

        self.id: str = str(uuid.uuid4())
        self.offered: bool = False
        self.offer_id: Optional[str] = None    # offer this attempt was placed on
        self.last_state: Optional[str] = None  # latest backend status state
        self.agent_id: Optional[str] = None
        self.hostname: Optional[str] = None
        self.addr: Optional[str] = None        # task's control addr, set at registration
        self.coord_port: Optional[int] = None  # port reserved for jax.distributed coordinator
        self.connection = None                 # live control socket while handshaking
        self.initialized: bool = False

    def __repr__(self) -> str:  # matches the reference's log-friendly repr intent
        return (f"<Task {self.job_name}:{self.task_index} id={self.id[:8]} "
                f"cpus={self.cpus} mem={self.mem} chips={self.chips} addr={self.addr}>")

    def reset(self) -> None:
        """Revive with a fresh identity (reference: scheduler.py:422-430)."""
        self.id = str(uuid.uuid4())
        self.offered = False
        self.offer_id = None
        self.last_state = None
        self.agent_id = None
        self.hostname = None
        self.addr = None
        self.coord_port = None
        if self.connection is not None:
            try:
                self.connection.close()
            except OSError:
                pass
        self.connection = None
        self.initialized = False

    def fits(self, offer: Offer) -> bool:
        return (offer.cpus >= self.cpus and offer.mem >= self.mem
                and offer.chips >= self.chips)

    def take_from(self, offer: Offer) -> None:
        offer.cpus -= self.cpus
        offer.mem -= self.mem
        offer.chips -= self.chips

    # -- rendering ---------------------------------------------------------

    def to_task_info(self, offer: Offer, master_addr: str, token: str,
                     docker_image: Optional[str] = None,
                     containerizer_type: Optional[str] = None,
                     force_pull_image: bool = False,
                     env: Optional[Dict[str, str]] = None,
                     token_file: Optional[str] = None,
                     secret_token: bool = False) -> dict:
        """Render a Mesos v1 JSON ``TaskInfo`` (reference: scheduler.py:61-177).

        The launched command is our node runtime dialing back to the
        scheduler's rendezvous address — the same bootstrap contract as the
        reference (scheduler.py:163-167):

            python -m tfmesos_tpu.server <task_id> <master_addr>
        """
        env = dict(env or {})
        # The reference overwrites PYTHONPATH with the scheduler's sys.path so
        # tasks resolve the same code (scheduler.py:168-176); keep that.
        env["PYTHONPATH"] = ":".join(sys.path)
        # Token delivery, least-exposed transport first: a mode-0600 file
        # (co-located backends), a Mesos SECRET-typed variable (clusters with
        # a secret resolver; never shown in state endpoints), or — the
        # documented fallback — a plain env var, which anyone able to read
        # Mesos state or the agent's /proc can see.
        secret_vars = []
        if token_file:
            env[_TOKEN_FILE_ENV] = token_file
        elif secret_token:
            secret_vars.append({
                "name": _TOKEN_ENV,
                "type": "SECRET",
                "secret": {"type": "VALUE",
                           "value": {"data": base64.b64encode(
                               token.encode()).decode()}},
            })
        else:
            env[_TOKEN_ENV] = token

        ti: dict = {
            "name": f"{self.job_name}:{self.task_index}",
            "task_id": {"value": self.id},
            "agent_id": {"value": offer.agent_id},
            "resources": [
                _scalar("cpus", self.cpus),
                _scalar("mem", self.mem),
            ],
            "command": {
                "shell": True,
                "value": (f"{sys.executable} -m tfmesos_tpu.server "
                          f"{self.id} {master_addr}"),
                "environment": {
                    "variables": [
                        {"name": k, "value": str(v)} for k, v in sorted(env.items())
                    ] + secret_vars
                },
            },
        }
        if self.chips:
            # Chips are requested under the SAME resource name the offer
            # advertised ("tpus" on TPU-VM agents, "gpus" on GPU agents) —
            # requesting a name the agent never offered would fail at launch.
            ti["resources"].append(
                _scalar(offer.chips_resource, float(self.chips)))

        image = docker_image or os.environ.get("DOCKER_IMAGE")
        if image:
            ti["container"] = _container(image, containerizer_type or "MESOS",
                                         force_pull_image, self.volumes)
        return ti


def _scalar(name: str, value: float) -> dict:
    return {"name": name, "type": "SCALAR", "scalar": {"value": value}}


def _container(image: str, containerizer_type: str, force_pull: bool,
               volumes: Dict[str, str]) -> dict:
    """Container config (reference: scheduler.py:82-146).

    The reference's nvidia-docker v1 plugin dance (scheduler.py:96-119) has no
    TPU analogue — TPU-VM containers only need /dev/vfio plumbed through,
    which the MESOS containerizer handles via the image rootfs — so only the
    rootfs/image and volume mounts survive.  /etc/passwd and /etc/group are
    mounted read-only so uids resolve identically in- and out-of-container
    (reference: scheduler.py:133-139).
    """
    vols = [
        {"container_path": "/etc/passwd", "host_path": "/etc/passwd", "mode": "RO"},
        {"container_path": "/etc/group", "host_path": "/etc/group", "mode": "RO"},
    ]
    for host_path, container_path in sorted(volumes.items()):
        vols.append({"container_path": container_path, "host_path": host_path,
                     "mode": "RW"})
    if containerizer_type == "DOCKER":
        return {
            "type": "DOCKER",
            "volumes": vols,
            "docker": {
                "image": image,
                "network": "HOST",
                "force_pull_image": force_pull,
                "parameters": [{"key": "memory-swap", "value": "-1"}],
            },
        }
    return {
        "type": "MESOS",
        "volumes": vols,
        "mesos": {"image": {"type": "DOCKER",
                            "docker": {"name": image},
                            "cached": not force_pull}},
    }
