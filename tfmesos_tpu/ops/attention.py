"""Attention ops: reference MHA and a Pallas TPU flash-attention kernel.

The reference framework has no kernels of its own (SURVEY §2.6) — its FLOPs
live in TF's compiled runtime.  Ours live here: a blocked, online-softmax
forward kernel and a two-kernel (dq / dk+dv) backward, both tiled for the
MXU (fp32 accumulation, causal blocks skipped entirely, the backward reusing
the forward's stored logsumexp), with a plain-XLA reference implementation
as ground truth and CPU fallback.

Layouts follow the JAX convention ``[batch, seq, heads, head_dim]``.
"""

from __future__ import annotations

import functools
import math
import operator
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tfmesos_tpu.compat import shard_map

NEG_INF = float("-inf")

#: Paged-decode launch accounting (bench_decode_paged_call and the
#: launches-per-block assertions): Python-level call counts, bumped once
#: per ``flash_decode_paged`` invocation.  Under ``jit`` a call site
#: counts once per TRACE (a ``lax.scan`` body traces once however many
#: steps it runs), so measure eager/microbench call sequences — the
#: serving-level launches-per-block number comes from
#: ``ContinuousBatcher.paged_launches_per_block`` instead, which knows
#: the dispatch structure.
PAGED_CALL_STATS = {"calls": 0, "kernel_calls": 0}

#: Per-core VMEM bytes the paged kernel's K + V slabs may claim
#: (double-buffered pair of each, leaving headroom for q, the self
#: operands and the softmax scratch in the ~16 MB core budget).
_PAGED_VMEM_BUDGET = 8 * 2 ** 20


def _paged_head_block(kv: int, ps: int, d: int, itemsize: int) -> int:
    """Heads per paged-kernel grid cell: the largest divisor of ``kv``
    whose [head_block, page, d] K + V slabs, double-buffered, fit
    :data:`_PAGED_VMEM_BUDGET` — every head in one cell when it fits
    (head grid dimension 1, the common case), falling back to smaller
    head blocks for huge page x head_dim products rather than losing
    the kernel eligibility outright."""
    for hb in range(kv, 0, -1):
        if kv % hb == 0 and 4 * hb * ps * d * itemsize <= \
                _PAGED_VMEM_BUDGET:
            return hb
    return 1


def _check_gqa_heads(q, k, v):
    """Every attention path shares one clear failure for bad GQA shapes
    (e.g. 4 q heads over 3 kv heads would otherwise floor to rep=1 and die
    later in an opaque einsum shape error)."""
    if q.shape[2] % k.shape[2] or k.shape[2] != v.shape[2]:
        raise ValueError(
            f"q heads ({q.shape[2]}) must be a multiple of kv heads "
            f"({k.shape[2]}/{v.shape[2]}, which must agree)")


def mha_reference(q, k, v, causal: bool = False, scale: Optional[float] = None,
                  window: Optional[int] = None):
    """Plain-XLA scaled-dot-product attention (ground truth / fallback).

    Grouped-query attention is accepted directly: when ``k``/``v`` carry
    fewer heads than ``q`` (q heads per kv head = H // KV), they are
    broadcast up here — the kernels do the same mapping without
    materializing the repeat.

    ``window`` (requires ``causal``): sliding-window attention — query i
    sees keys [i-window+1, i] only."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    _check_gqa_heads(q, k, v)
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        qpos = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        bad = kpos > qpos
        if window is not None:
            bad = bad | (kpos < qpos - (window - 1))
        scores = jnp.where(bad, NEG_INF, scores)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _pick_block(dim: int, target: int = 512) -> int:
    """Largest Mosaic-legal (8-aligned or full-dim) divisor of ``dim`` that
    is <= ``target``; falls back to the whole dim (always legal)."""
    for c in (1024, 512, 384, 256, 128, 64, 32, 16, 8):
        if c <= min(dim, target) and dim % c == 0:
            return c
    return dim


class _FlashCfg(NamedTuple):
    causal: bool
    scale: float
    block_q: int
    block_k: int
    interpret: bool
    q_per_kv: int = 1  # GQA group size (q heads per kv head); 1 = MHA
    window: Optional[int] = None  # sliding window (causal only); None = full
    # Static GLOBAL offset of the query block's positions relative to the
    # key block's (query i is global position i + q_offset; key j is j).
    # Ring attention sets it to step * shard_len so causal/window masks
    # and block bounds are exact across shards; 0 = the ordinary
    # same-origin call.
    q_offset: int = 0


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, cfg: _FlashCfg,
                  seq_len: int):
    """One (batch, q-block, head) grid cell: stream K/V blocks with online
    softmax.  Accumulation in fp32; output cast back at the end.

    Refs are laid out ``[1, 1, T, D]`` — (seq, head_dim) must be the trailing
    dims so blocks land on the TPU's (8, 128) tiling.

    Operands stay in their input dtype (bf16 runs the MXU at full rate) with
    fp32 accumulation via ``preferred_element_type``; softmax statistics are
    fp32 throughout.
    """
    q = q_ref[0, 0, :, :]  # [bq, d], input dtype
    bq, bk = cfg.block_q, cfg.block_k
    qi = pl.program_id(1)
    nk = seq_len // bk
    lo = 0
    if cfg.causal:
        # Blocks strictly above the diagonal contribute nothing: bound the
        # loop instead of masking them (halves the FLOPs on average).
        nk = jnp.minimum(nk, pl.cdiv((qi + 1) * bq + cfg.q_offset, bk))
        if cfg.window is not None:
            # Sliding window: blocks entirely below every query's window
            # start also contribute nothing — total work is O(T·W).
            lo = jnp.maximum(
                0, (qi * bq + cfg.q_offset - (cfg.window - 1)) // bk)

    def body(j, carry):
        o, m, l = carry
        k_blk = k_ref[0, 0, pl.ds(j * bk, bk), :]  # [bk, d]
        v_blk = v_ref[0, 0, pl.ds(j * bk, bk), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [bq, bk]
        s = s * cfg.scale
        if cfg.causal:
            qpos = (qi * bq + cfg.q_offset
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
            kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            bad = kpos > qpos
            if cfg.window is not None:
                bad = bad | (kpos < qpos - (cfg.window - 1))
            s = jnp.where(bad, NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        if cfg.window is not None:
            # A q row can be ENTIRELY outside the window in this k block
            # (the loop's lo bound fits the block's lowest row, not all of
            # them): m_new stays -inf there and exp(-inf - -inf) is NaN.
            # Zero those entries explicitly — plain causal never hits this
            # (block 0 is valid for every row).
            p = jnp.where(s == NEG_INF, 0.0, jnp.exp(s - m_new))
            corr = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - m_new))
        else:
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    d = q.shape[-1]
    o0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(lo, nk, body, (o0, m0, l0))
    if cfg.window is not None:
        # With an offset window a whole q row (or the whole block: lo >=
        # nk) can see NO key in this shard: emit a clean zero/-inf
        # partial instead of 0/0 NaNs, so the ring's lse merge drops it.
        empty = l == 0.0
        o_ref[0, 0, :, :] = jnp.where(
            empty, 0.0, o / jnp.where(empty, 1.0, l)).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = jnp.where(
            empty, NEG_INF, m + jnp.log(jnp.where(empty, 1.0, l)))
    else:
        o_ref[0, 0, :, :] = (o / l).astype(o_ref.dtype)
        # Per-query logsumexp of the SCALED scores: the backward pass
        # reuses it instead of re-sweeping Q.K^T (causal rows always hit
        # the diagonal, so l > 0 here).
        lse_ref[0, 0, :, :] = m + jnp.log(l)


def _flash_forward(cfg: _FlashCfg, q, k, v):
    b, t, h, d = q.shape
    g = h // k.shape[2]  # q heads per kv head (1 = plain MHA)
    # [B, T, H, D] -> [B, H, T, D]: (seq, head_dim) trailing for TPU tiling.
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    grid = (b, t // cfg.block_q, h)
    q_spec = pl.BlockSpec((1, 1, cfg.block_q, d),
                          lambda bi, qi, hi: (bi, hi, qi, 0),
                          memory_space=pltpu.VMEM)
    # GQA without materializing the repeat: q head hi reads kv head hi//g
    # straight from the narrow K/V arrays via the index map.
    kv_spec = pl.BlockSpec((1, 1, k.shape[1], d),
                           lambda bi, qi, hi: (bi, hi // g, 0, 0),
                           memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec((1, 1, cfg.block_q, 1),
                            lambda bi, qi, hi: (bi, hi, qi, 0),
                            memory_space=pltpu.VMEM)
    kernel = functools.partial(_flash_kernel, cfg=cfg, seq_len=k.shape[1])
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[q_spec, lse_spec],
        out_shape=[jax.ShapeDtypeStruct(qt.shape, q.dtype),
                   jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32)],
        interpret=cfg.interpret,
        compiler_params=None if cfg.interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel")),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * t * k.shape[1] * d,
            bytes_accessed=(q.size + k.size + v.size + q.size) * q.dtype.itemsize,
            transcendentals=b * h * t * k.shape[1],
        ),
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, cfg: _FlashCfg):
    """dq, one (batch, head, q-block, k-block) grid step; k innermost.

    K/V blocks stream through VMEM double-buffered while the dq output block
    (index map constant along k) stays resident as the accumulator — the
    canonical Mosaic reduction pattern.  p = exp(s·scale − lse) is recomputed
    from the stored per-query logsumexp (no second online softmax), then
    ds = p ⊙ (do·vᵀ − Δ), dq += ds·k·scale  (Δ = rowsum(do ⊙ o),
    precomputed outside — one fused elementwise pass in XLA).
    """
    bq, bk = cfg.block_q, cfg.block_k
    qi, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_ref[0, 0, :, :] = jnp.zeros_like(dq_ref[0, 0, :, :])

    def _step():
        q = q_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]       # [bq, 1] fp32
        delta = delta_ref[0, 0, :, :]   # [bq, 1] fp32
        k_blk = k_ref[0, 0, :, :]       # [bk, d]
        v_blk = v_ref[0, 0, :, :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * cfg.scale
        p = jnp.exp(s - lse)            # [bq, bk] fp32
        if cfg.causal:
            qpos = (qi * bq + cfg.q_offset
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
            kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            bad = kpos > qpos
            if cfg.window is not None:
                bad = bad | (kpos < qpos - (cfg.window - 1))
            p = jnp.where(bad, 0.0, p)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k_blk.dtype)
        dq_ref[0, 0, :, :] += cfg.scale * jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if cfg.causal:
        # Blocks strictly above the causal diagonal (or entirely below the
        # sliding window) contribute nothing.
        live = j * bk <= (qi + 1) * bq - 1 + cfg.q_offset
        if cfg.window is not None:
            live = live & ((j + 1) * bk - 1
                           >= qi * bq + cfg.q_offset - (cfg.window - 1))
        pl.when(live)(_step)
    else:
        _step()


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, cfg: _FlashCfg):
    """dk and dv, one (batch, KV head, k-block, q-block x group) grid step;
    the innermost dim runs the group's q heads for each q-block.

    Q/do/lse/Δ blocks stream while the dk/dv output blocks accumulate in
    VMEM:  dv += pᵀ·do,  dk += dsᵀ·q·scale.  With grouped-query attention
    (``cfg.q_per_kv > 1``) this k-block's gradient sums over every query
    head sharing the kv head — the group ride-along on the streamed dim
    does that without a second reduction pass.  Under causality, q-blocks
    strictly before the diagonal see none of this k-block and are skipped.
    """
    bq, bk = cfg.block_q, cfg.block_k
    ki, e = pl.program_id(2), pl.program_id(3)
    i = e // cfg.q_per_kv  # q-block index (e also enumerates the group)

    @pl.when(e == 0)
    def _init():
        dk_ref[0, 0, :, :] = jnp.zeros_like(dk_ref[0, 0, :, :])
        dv_ref[0, 0, :, :] = jnp.zeros_like(dv_ref[0, 0, :, :])

    def _step():
        k_blk = k_ref[0, 0, :, :]  # [bk, d]
        v_blk = v_ref[0, 0, :, :]
        q = q_ref[0, 0, :, :]      # [bq, d]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * cfg.scale
        p = jnp.exp(s - lse)       # [bq, bk] fp32
        if cfg.causal:
            qpos = (i * bq + cfg.q_offset
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
            kpos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            bad = kpos > qpos
            if cfg.window is not None:
                bad = bad | (kpos < qpos - (cfg.window - 1))
            p = jnp.where(bad, 0.0, p)
        dv_ref[0, 0, :, :] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_ref[0, 0, :, :] += cfg.scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if cfg.causal:
        # q-blocks strictly before the diagonal (or beyond the window's
        # reach of this k-block) see none of it.
        live = (i + 1) * bq - 1 + cfg.q_offset >= ki * bk
        if cfg.window is not None:
            live = live & (i * bq + cfg.q_offset
                           <= (ki + 1) * bk - 1 + (cfg.window - 1))
        pl.when(live)(_step)
    else:
        _step()


def _mha_bwd_pallas(cfg: _FlashCfg, q, k, v, o, lse, do, out_dtype=None):
    """Mosaic backward: the standard two-kernel dq / dk+dv split, both
    reusing the forward's stored logsumexp.  ``out_dtype`` overrides the
    gradient dtype (callers that go on accumulating — ring attention —
    take fp32 to avoid a round-trip through bf16 per partial).

    Grids put the reduction dimension innermost with ``arbitrary`` semantics
    so operand blocks pipeline (HBM→VMEM double-buffering) while the output
    block is revisited in place; accumulation is fp32 (outputs cast back to
    the input dtype outside, one fused elementwise pass).
    """
    b, t, h, d = q.shape
    tk = k.shape[1]
    g = h // k.shape[2]  # q heads per kv head (1 = plain MHA)
    # The backward picks its own blocks: grid-step overhead dominates at the
    # forward's numbers (measured on v5e at B4/T2048/H8/D128 bf16: 128-blocks
    # run 1.8x slower than 512), and unlike the forward there is no online-
    # softmax state growing with block_q.
    bq, bk = _pick_block(t), _pick_block(tk)
    cfg = cfg._replace(block_q=bq, block_k=bk, q_per_kv=g)
    # [B, T, H, D] -> [B, H, T, D]: (seq, head_dim) trailing for TPU tiling.
    qt, kt, vt, dot_ = (x.transpose(0, 2, 1, 3) for x in (q, k, v, do))
    # Δ = rowsum(do ⊙ o): one fused elementwise+reduce pass, cheaper as XLA
    # than as a third kernel.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).transpose(0, 2, 1)[..., None]     # [B,H,T,1]

    params = None if cfg.interpret else pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))
    flops_half = 4 * b * h * t * tk * d  # each kernel ~= forward FLOPs

    def outer_spec(block, width):  # indexed by grid dim 2 (output axis)
        return pl.BlockSpec((1, 1, block, width),
                            lambda bi, hi, i, j: (bi, hi, i, 0),
                            memory_space=pltpu.VMEM)

    def inner_spec(block, width):  # indexed by grid dim 3 (streamed axis)
        return pl.BlockSpec((1, 1, block, width),
                            lambda bi, hi, i, j: (bi, hi, j, 0),
                            memory_space=pltpu.VMEM)

    def kv_dq_spec(block, width):  # kv operand in the dq grid (GQA map)
        return pl.BlockSpec((1, 1, block, width),
                            lambda bi, hi, i, j: (bi, hi // g, j, 0),
                            memory_space=pltpu.VMEM)

    # dq grid: q-blocks outer (accumulator), k-blocks streamed; q head hi
    # reads kv head hi // g.
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, cfg=cfg),
        grid=(b, h, t // bq, tk // bk),
        in_specs=[outer_spec(bq, d), kv_dq_spec(bk, d), kv_dq_spec(bk, d),
                  outer_spec(bq, d), outer_spec(bq, 1), outer_spec(bq, 1)],
        out_specs=outer_spec(bq, d),
        out_shape=jax.ShapeDtypeStruct(qt.shape, jnp.float32),
        interpret=cfg.interpret,
        compiler_params=params,
        cost_estimate=pl.CostEstimate(
            flops=flops_half,
            bytes_accessed=(2 * q.size + 2 * k.size) * q.dtype.itemsize,
            transcendentals=b * h * t * tk),
    )(qt, kt, vt, dot_, lse, delta)

    # dk/dv grid: one cell per KV head and k-block (accumulators); the
    # streamed dim enumerates (q-block x group) pairs so a kv head's
    # gradient sums over every q head sharing it.
    def q_dkv_spec(block, width):  # q-side operands in the dkv grid
        return pl.BlockSpec(
            (1, 1, block, width),
            lambda bi, hi, i, e: (bi, hi * g + e % g, e // g, 0),
            memory_space=pltpu.VMEM)

    def kv_dkv_spec(block, width):
        return pl.BlockSpec((1, 1, block, width),
                            lambda bi, hi, i, e: (bi, hi, i, 0),
                            memory_space=pltpu.VMEM)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, cfg=cfg),
        grid=(b, h // g, tk // bk, (t // bq) * g),
        in_specs=[q_dkv_spec(bq, d), kv_dkv_spec(bk, d), kv_dkv_spec(bk, d),
                  q_dkv_spec(bq, d), q_dkv_spec(bq, 1), q_dkv_spec(bq, 1)],
        out_specs=[kv_dkv_spec(bk, d), kv_dkv_spec(bk, d)],
        out_shape=[jax.ShapeDtypeStruct(kt.shape, jnp.float32),
                   jax.ShapeDtypeStruct(vt.shape, jnp.float32)],
        interpret=cfg.interpret,
        compiler_params=params,
        cost_estimate=pl.CostEstimate(
            flops=flops_half,
            bytes_accessed=(2 * q.size + 2 * k.size) * q.dtype.itemsize,
            transcendentals=b * h * t * tk),
    )(qt, kt, vt, dot_, lse, delta)

    back = lambda x, ref: x.transpose(0, 2, 1, 3).astype(
        out_dtype or ref.dtype)
    return back(dq, q), back(dk, k), back(dv, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _FlashCfg, q, k, v):
    return _flash_forward(cfg, q, k, v)[0]


def _flash_fwd(cfg, q, k, v):
    o, lse = _flash_forward(cfg, q, k, v)
    return o, (q, k, v, o, lse)


def _flash_bwd(cfg, res, g):
    q, k, v, o, lse = res
    return _mha_bwd_pallas(cfg, q, k, v, o, lse, g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False, scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    use_pallas: Optional[bool] = None,
                    interpret: bool = False,
                    window: Optional[int] = None):
    """Blocked attention; Pallas kernel on TPU, reference math elsewhere.

    ``use_pallas=None`` auto-selects: the kernel runs when the default
    backend is TPU (or when ``interpret=True`` for tests) and shapes are
    block-aligned; otherwise the XLA reference path runs — same numerics,
    same signature, so model code never branches.

    Grouped-query attention: ``k``/``v`` may carry ``H // g`` heads for any
    integer ``g``; the kernels map q head ``h`` to kv head ``h // g`` via
    their index maps, so the repeat is never materialized.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    _check_gqa_heads(q, k, v)
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    t = q.shape[1]
    # Treat the block arguments as targets: run with the largest Mosaic-legal
    # (8-aligned or full-dim) divisor at or under each — so t=1280 still gets
    # 256-blocks rather than falling off the kernel path.  A dim with no
    # 8-aligned divisor comes back as the full dim (legal, single block); cap
    # that at 1024 so a huge unaligned seq falls back to XLA instead of
    # dragging a whole [t, t] score block through VMEM.
    block_q = _pick_block(t, block_q)
    block_k = _pick_block(k.shape[1], block_k)
    aligned = block_q <= 1024 and block_k <= 1024
    if use_pallas is None:
        on_tpu = jax.default_backend() == "tpu"
        use_pallas = aligned and (on_tpu or interpret)
    elif use_pallas and not aligned:
        # Fail fast on a forced-pallas misuse rather than dragging an
        # unaligned [t, t] score block through VMEM.
        raise ValueError(
            f"flash_attention(use_pallas=True): seq lens {t}/{k.shape[1]} "
            f"have no Mosaic-legal block tiling at or under "
            f"({block_q}, {block_k})")
    if not use_pallas:
        return mha_reference(q, k, v, causal=causal, scale=scale,
                             window=window)
    cfg = _FlashCfg(causal=bool(causal), scale=float(scale),
                    block_q=block_q, block_k=block_k,
                    interpret=bool(interpret),
                    window=None if window is None else int(window))
    return _flash(cfg, q, k, v)


def _decode_reference(q, k_cache, v_cache, pos, scale):
    """Dense masked attention of a query chunk over a KV cache (ground
    truth / non-TPU path for ``flash_decode``).  Grouped einsum: the cache
    streams at kv width, q heads grouped kv-major as [kv, g].  ``q`` is
    [B, H, D] (single token) or [B, t, H, D] (chunk; token tt sees
    positions <= pos + tt); the cache is the kernel-native
    [B, KV, M, D] (seq and head_dim trailing)."""
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    b, t, h, d = q.shape
    kv = k_cache.shape[1]
    g = h // kv
    m = k_cache.shape[2]
    q5 = q.reshape(b, t, kv, g, d)
    s = jnp.einsum("btkgd,bkmd->bkgtm", q5, k_cache).astype(jnp.float32)
    s = s * scale
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
    kpos = jnp.arange(m, dtype=jnp.int32)
    bad = (kpos[None, None] >
           pos[:, None, None] + jnp.arange(t, dtype=jnp.int32)[None, :,
                                                               None])
    s = jnp.where(bad[:, None, None], NEG_INF, s)       # [b,kv,g,t,m]
    p = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    o = jnp.einsum("bkgtm,bkmd->btkgd", p, v_cache)
    o = o.reshape(b, t, h, d)
    return o[:, 0] if squeeze else o


def _decode_block_scores(q, k_blk, scale, ks_row=None):
    """[rows, block] score tile of one K block (int8 blocks convert in
    VMEM; per-position k scales fold post-dot) — shared by the linear
    and paged (kv-folded) decode kernels so their math cannot diverge."""
    s = jax.lax.dot_general(q, k_blk.astype(q.dtype),
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * scale
    if ks_row is not None:
        s = s * ks_row[None, :]
    return s


def _decode_accumulate(s, v_blk, acc, vs_row=None):
    """One online-softmax accumulation of a score tile against its V
    block: returns the updated (m, l, o) triple.  Handles all-masked
    tiles (exp(-inf - -inf) guarded) and the int8 per-position v-scale
    fold — the single definition both decode kernels run."""
    m_prev, l_prev, o_prev = acc
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.where(s == NEG_INF, 0.0, jnp.exp(s - m_new))
    corr = jnp.where(m_prev == NEG_INF, 0.0, jnp.exp(m_prev - m_new))
    l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    if vs_row is not None:
        p = p * vs_row[None, :]
    if v_blk.dtype == jnp.int8:
        v_blk = v_blk.astype(jnp.float32)
    o_new = o_prev * corr + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def _flash_decode_kernel(s_ref, q_ref, k_ref, v_ref, *rest, block_m: int,
                         scale: float, quantized: bool, q_per_kv: int):
    """One (batch, kv-head, m-block) grid step of cache-bounded decode.

    The q block carries this kv head's rows for the WHOLE chunk, t-major:
    row r = chunk token (r // g), group member (r % g) — t = 1 in
    steady-state decode, t > 1 for speculative verify / chunked prefill.
    Chunk token tt sees cache positions <= pos_first + tt.

    ``s_ref`` holds the scalar-prefetched per-row triples (n_live_blocks,
    first chunk position, layer index).  Blocks past the bound are skipped
    AND their index map pins to the last live block, so Mosaic's
    unchanged-index elision never DMAs them — HBM traffic is O(pos), not
    O(max_len).  Online softmax accumulates across the m grid dim in VMEM
    scratch; the normalized output writes once on the final step.

    K/V refs are blocks of the STACKED cache ([L, ..., block_m, d] — the
    layer index rides row 2 of the scalar prefetch into the index maps),
    so decoding never materializes a per-layer slice: the scan over
    layers reads O(pos) from the full buffer directly.

    ``quantized``: K/V refs are int8 with per-position fp32 scale refs
    following them.  The scales fold into the score/probability rows
    (k: s·kscale after the dot; v: (p·vscale)·v_int8), so the cache
    streams from HBM at int8 width — the dequantize never touches HBM.

    Deferred-write decode (an uncommitted current token riding in as a
    self operand) is a PAGED-path feature: only ``decode_step``'s paged
    single-host steps defer their pool commit, so only
    ``_flash_decode_paged_kernel`` carries the self block — the linear
    cache commits before attending and this kernel reads it directly.
    """
    it = list(rest)
    if quantized:
        ks_ref, vs_ref = it[0], it[1]
        it = it[2:]
    o_ref, o_acc, m_acc, l_acc = it
    bi = pl.program_id(0)
    j = pl.program_id(2)
    nb = s_ref[0, bi]      # per-batch-row block bound (ragged serving)
    pos = s_ref[1, bi]     # first chunk position for this row

    @pl.when(j == 0)
    def _init():
        o_acc[...] = jnp.zeros_like(o_acc)
        m_acc[...] = jnp.full_like(m_acc, NEG_INF)
        l_acc[...] = jnp.zeros_like(l_acc)

    @pl.when(j < nb)
    def _step():
        q = q_ref[0, 0, :, :]                       # [t*g, d]
        s = _decode_block_scores(
            q, k_ref[0, 0, 0, :, :], scale,
            ks_ref[0, 0, 0, 0, :] if quantized else None)
        kpos = j * block_m + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        tt = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // q_per_kv
        s = jnp.where(kpos > pos + tt, NEG_INF, s)
        m_acc[...], l_acc[...], o_acc[...] = _decode_accumulate(
            s, v_ref[0, 0, 0, :, :], (m_acc[...], l_acc[...], o_acc[...]),
            vs_ref[0, 0, 0, 0, :] if quantized else None)

    @pl.when(j == pl.num_programs(2) - 1)
    def _finish():
        # Every row has at least one attended slot (block 0 holds
        # position 0), so l > 0.
        o_ref[0, 0, :, :] = (o_acc[...] / l_acc[...]).astype(o_ref.dtype)


def _dequant_lane_major(qt_leaf, dtype):
    """Dequantize a lane-major QTensor cache slice (values [..., M, D],
    scales [..., 1, M]): move the per-position scales back over the seq
    dim and multiply (test/CPU path — the kernel streams int8)."""
    return (qt_leaf.values.astype(dtype)
            * jnp.swapaxes(qt_leaf.scales, -1, -2).astype(dtype))


def _stacked_cache(k_cache, v_cache, layer):
    """Normalize a decode cache to its STACKED form: returns
    (kc, vc, k_scales, v_scales, layer_idx, quantized) with kc/vc
    [L, ..., M|page, D] and lane-major scales [L, ..., 1, M|page] (None
    when not quantized).  A 4-D cache is lifted to L=1 (``layer`` must
    then be None/0)."""
    from tfmesos_tpu.ops.quant import QTensor

    quantized = isinstance(k_cache, QTensor)
    kc = k_cache.values if quantized else k_cache
    vc = v_cache.values if quantized else v_cache
    ks = k_cache.scales if quantized else None
    vs = v_cache.scales if quantized else None
    if kc.ndim == 4:
        # Any STATICALLY-zero index is fine with an L=1 lift (python int,
        # numpy int32, 0-d concrete array — operator.index normalizes
        # them all); only a nonzero or traced index actually needs the
        # stacked form.
        if layer is not None:
            try:
                layer = operator.index(layer)
            except TypeError:
                layer = None    # traced: cannot prove it selects layer 0
            if layer != 0:
                raise ValueError("layer index needs a stacked 5-D cache")
        kc, vc = kc[None], vc[None]
        if quantized:
            ks, vs = ks[None], vs[None]
        layer = 0
    layer = jnp.asarray(0 if layer is None else layer, jnp.int32)
    return kc, vc, ks, vs, layer, quantized


def flash_decode(q, k_cache, v_cache, pos, scale: Optional[float] = None,
                 block_m: int = 1024, use_pallas: Optional[bool] = None,
                 interpret: bool = False, layer=None):
    """Single-token decode attention over a KV cache, bounded at ``pos``.

    ``q``: [B, H, D] (one new token's heads, kv-major groups) or
    [B, t, H, D] (a CHUNK — speculative verify / chunked prefill; chunk
    token tt attends cache positions <= pos + tt, the cache already
    holding the chunk's own K/V);
    ``k_cache``/``v_cache``: the kernel-native layout [B, KV, M, D]
    ((seq, head_dim) trailing — no per-call transpose of cache-sized
    data), or the STACKED [L, B, KV, M, D] buffer with ``layer`` the
    (traced OK) layer index — the ``decode_step`` layer scan passes the
    whole cache and the index rides the scalar prefetch, so no per-layer
    slice is ever materialized.  Plain arrays, or int8 ``QTensor``s with
    LANE-MAJOR scales ([(L,) B, KV, 1, M], as ``init_cache`` builds
    them), in which case HBM streams int8 and the scales fold into the
    score rows; ``pos``: scalar int32, or a [B] vector for RAGGED
    batches (each row at its own position — the mixed-length serving
    case); traced OK either way (it rides the kernel's scalar prefetch,
    bounding each row's block loop independently).  Returns q's shape.

    The XLA einsum reads all M cache slots every step because ``pos`` is
    traced; this kernel's grid maps the out-of-range m-blocks to the last
    live block (never re-fetched), so per-step HBM traffic is
    O(pos·kv·D) — the difference between serving a 32k-slot cache at
    position 2k and paying for 32k.  GQA runs at cache width: the score
    block is [g, block_m] per kv head, no materialized repeat.

    ``block_m`` defaults to 1024 (the Mosaic tile ceiling): the grid
    iterates m/block_m steps even when the bound skips their DMA, so
    bigger blocks cut per-step grid overhead — measured 2.62 -> 2.25
    ms/step on the 16k-buffer decode_longctx config (v5e, round 5);
    ``_pick_block`` still clamps to a legal divisor for small caches.
    """
    kc, vc, ksc, vsc, li, quantized = _stacked_cache(k_cache, v_cache,
                                                     layer)
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    b, t, h, d = q.shape
    kv, m = kc.shape[2], kc.shape[3]
    _check_gqa_heads(q, kc, vc)     # heads at axis 2 of the stacked cache
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    g = h // kv
    block_m = _pick_block(m, block_m)
    aligned = block_m <= 1024
    if use_pallas is None:
        on_tpu = jax.default_backend() == "tpu"
        use_pallas = aligned and (on_tpu or interpret)
    if not use_pallas:
        take = lambda a: jax.lax.dynamic_index_in_dim(a, li, 0,
                                                      keepdims=False)
        k_l, v_l = take(kc), take(vc)
        if quantized:
            from tfmesos_tpu.ops.quant import QTensor
            k_l = _dequant_lane_major(QTensor(k_l, take(ksc)), q.dtype)
            v_l = _dequant_lane_major(QTensor(v_l, take(vsc)), q.dtype)
        out = _decode_reference(q, k_l, v_l, pos, scale)
        return out[:, 0] if squeeze else out

    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
    # Per-row (block bound from the LAST chunk position, first position,
    # layer index) — all three ride the scalar prefetch.
    scalars = jnp.stack([(pos + t - 1) // block_m + 1, pos,
                         jnp.broadcast_to(li, (b,))])           # [3, B]
    if not quantized and q.dtype != kc.dtype:
        # e.g. bf16 queries over a caller-widened fp32 cache: the kernel's
        # dots need one operand dtype (promote, matching the einsum path).
        q = q.astype(jnp.promote_types(q.dtype, kc.dtype))
        kc = kc.astype(q.dtype)
    # Rows t-major per kv head: row = tt*g + group member (the kernel's
    # mask derives the token index as row // g).
    qt = q.reshape(b, t, kv, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b, kv, t * g, d)

    q_spec = pl.BlockSpec((1, 1, t * g, d),
                          lambda bi, hi, j, s: (bi, hi, 0, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec(
        (1, 1, 1, block_m, d),
        lambda bi, hi, j, s: (s[2, 0], bi, hi,
                              jnp.minimum(j, s[0, bi] - 1), 0),
        memory_space=pltpu.VMEM)
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [qt, kc, vc]
    if quantized:
        # Scales stay stacked lane-major [L, B, KV, 1, M]: positions on
        # the lane dim, same pinned index map as their values.
        sc_spec = pl.BlockSpec(
            (1, 1, 1, 1, block_m),
            lambda bi, hi, j, s: (s[2, 0], bi, hi, 0,
                                  jnp.minimum(j, s[0, bi] - 1)),
            memory_space=pltpu.VMEM)
        in_specs += [sc_spec, sc_spec]
        operands += [ksc, vsc]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, kv, m // block_m),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((t * g, d), jnp.float32),
                        pltpu.VMEM((t * g, 1), jnp.float32),
                        pltpu.VMEM((t * g, 1), jnp.float32)])
    out = pl.pallas_call(
        functools.partial(_flash_decode_kernel, block_m=block_m,
                          scale=float(scale), quantized=quantized,
                          q_per_kv=g),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * t * h * m * d,
            bytes_accessed=(kc[0].size * kc.dtype.itemsize * 2
                            + 2 * q.size * q.dtype.itemsize),
            transcendentals=b * t * h * m),
    )(scalars, *operands)
    out = out.reshape(b, kv, t, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b, t, h, d)
    return out[:, 0] if squeeze else out


def _paged_decode_reference(q, k_pool, v_pool, page_table, pos, scale,
                            layer=None, self_kv=None):
    """Gather-the-pages ground truth: materialize each row's logical cache
    view from the pool ([P, KV, page, D], or the stacked
    [L, P, KV, page, D] with ``layer``; int8 QTensors dequantize) and run
    the dense masked reference.  ``self_kv`` (deferred-write decode):
    the uncommitted chunk's [B, t, KV, D] K/V is written into each row's
    view at its own positions [pos, pos + t - 1] — the pool slots there
    are stale (t = 1 in steady-state decode; t > 1 is the FUSED
    multi-row step: a speculative verify chunk or chunked-prefill tail
    attending before its commit)."""
    from tfmesos_tpu.ops.quant import QTensor

    kc, vc, ksc, vsc, li, quantized = _stacked_cache(k_pool, v_pool, layer)
    take = lambda a: jax.lax.dynamic_index_in_dim(a, li, 0, keepdims=False)
    k_pool, v_pool = take(kc), take(vc)
    if quantized:
        # Paged pools carry LANE-MAJOR scales ([P, KV, 1, page]); move
        # them back over the positions to dequantize (test/CPU path —
        # the kernel consumes the lane-major layout directly).
        k_pool = _dequant_lane_major(QTensor(k_pool, take(ksc)), q.dtype)
        v_pool = _dequant_lane_major(QTensor(v_pool, take(vsc)), q.dtype)
    b = q.shape[0]
    kv, ps = k_pool.shape[1], k_pool.shape[2]
    np_ = page_table.shape[1]
    # [B, NP, KV, page, D] -> the contiguous [B, KV, NP*page, D] view.
    gather = lambda pool: pool[page_table].transpose(0, 2, 1, 3, 4).reshape(
        b, kv, np_ * ps, pool.shape[3])
    k_view, v_view = gather(k_pool), gather(v_pool)
    if self_kv is not None:
        posv = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
        put = lambda view, c: jax.vmap(
            lambda v_, c_, p_: jax.lax.dynamic_update_slice(
                v_, c_.astype(v_.dtype), (0, p_, 0)))(
            view, c.transpose(0, 2, 1, 3), posv)
        k_view = put(k_view, self_kv[0])
        v_view = put(v_view, self_kv[1])
    return _decode_reference(q, k_view, v_view, pos, scale)


def _flash_decode_paged_kernel(s_ref, pt_ref, q_ref, k_ref, v_ref, *rest,
                               block_m: int, scale: float, quantized: bool,
                               q_per_kv: int, head_block: int,
                               self_attend: bool = False):
    """One (batch, head-block, logical-page) grid step of paged decode.

    Grid iterations cost ~2.3 µs each even when the per-row bound skips
    their DMA (the scalar-table index map defeats cheap elision —
    measured, v5e round 5), so KV heads are FOLDED into the block in
    slabs of ``head_block`` heads: one iteration fetches a page's
    [head_block, page, d] slab (contiguous in the pool layout) and runs
    the online-softmax body per head against per-head slices of the
    shared scratch.  The head-block dimension is PARALLEL
    (``dimension_semantics`` — head blocks share no accumulator state,
    so Mosaic may split them across megacore) while pages stay
    sequential for the scratch accumulation; when one slab holds every
    head (the ``_paged_head_block`` common case) the head dimension is
    size 1 and the layout degenerates to the fully kv-folded grid.

    Index maps chase this row's physical page id through the
    scalar-prefetched page table, so each row's cache lives in scattered
    pool pages and rows share one physical pool; ``s_ref`` rows are
    (n_live_blocks, position bound, layer index), as in
    ``_flash_decode_kernel``, whose per-head math (including the
    quantized scale folds) this kernel reproduces slice for slice.

    ``self_attend`` (deferred-write decode, a paged-only feature): the
    uncommitted chunk's K/V rides in as a [head_block, t, d] fp operand
    accumulated at the last page step.  The pool bound is then
    EXCLUSIVE and token-independent — ``kpos > bound`` with
    bound = pos - 1, because the pool only holds committed positions
    < pos and the slots at [pos, pos + t - 1] are stale for EVERY chunk
    token — and the intra-chunk causal structure lives in the self
    block instead (chunk token tt attends self slots ss <= tt).  This
    is the FUSED multi-row step: a t-token chunk (speculative verify /
    chunked-prefill tail) retires t decode rows through ONE launch per
    layer, the page table scalar-prefetched once for the whole chunk
    instead of once per step."""
    del pt_ref  # consumed by the index maps
    it = list(rest)
    ks_ref = vs_ref = kself_ref = vself_ref = None
    if quantized:
        ks_ref, vs_ref = it[0], it[1]
        it = it[2:]
    if self_attend:
        kself_ref, vself_ref = it[0], it[1]
        it = it[2:]
    o_ref, o_acc, m_acc, l_acc = it
    bi = pl.program_id(0)
    j = pl.program_id(2)
    nb = s_ref[0, bi]
    bound = s_ref[1, bi]
    tg = q_ref.shape[2]                         # t * g rows per head

    @pl.when(j == 0)
    def _init():
        o_acc[...] = jnp.zeros_like(o_acc)
        m_acc[...] = jnp.full_like(m_acc, NEG_INF)
        l_acc[...] = jnp.zeros_like(l_acc)

    @pl.when(j < nb)
    def _step():
        kpos0 = j * block_m
        for h in range(head_block):
            sl = slice(h * tg, (h + 1) * tg)
            q = q_ref[0, h, :, :]               # [tg, d]
            s = _decode_block_scores(
                q, k_ref[0, 0, h, :, :], scale,
                ks_ref[0, 0, h, 0, :] if quantized else None)
            kpos = kpos0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            if self_attend:
                # Committed positions only, for every chunk token: the
                # chunk's own span is stale in the pool and rides the
                # self block, which carries the causal mask.
                s = jnp.where(kpos > bound, NEG_INF, s)
            else:
                tt = jax.lax.broadcasted_iota(jnp.int32, s.shape,
                                              0) // q_per_kv
                s = jnp.where(kpos > bound + tt, NEG_INF, s)
            m_acc[sl], l_acc[sl], o_acc[sl] = _decode_accumulate(
                s, v_ref[0, 0, h, :, :], (m_acc[sl], l_acc[sl], o_acc[sl]),
                vs_ref[0, 0, h, 0, :] if quantized else None)

    if self_attend:
        @pl.when(j == pl.num_programs(2) - 1)
        def _self():
            for h in range(head_block):
                sl = slice(h * tg, (h + 1) * tg)
                q = q_ref[0, h, :, :]
                s = _decode_block_scores(q, kself_ref[0, h, :, :], scale)
                # Intra-chunk causality: self slot ss holds chunk token
                # ss's K/V, and row tt attends slots <= tt (t = 1 masks
                # nothing — the single-token deferred step unchanged).
                ss = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
                tt = jax.lax.broadcasted_iota(jnp.int32, s.shape,
                                              0) // q_per_kv
                s = jnp.where(ss > tt, NEG_INF, s)
                m_acc[sl], l_acc[sl], o_acc[sl] = _decode_accumulate(
                    s, vself_ref[0, h, :, :],
                    (m_acc[sl], l_acc[sl], o_acc[sl]))

    @pl.when(j == pl.num_programs(2) - 1)
    def _finish():
        for h in range(head_block):
            sl = slice(h * tg, (h + 1) * tg)
            o_ref[0, h, :, :] = (o_acc[sl] / l_acc[sl]).astype(o_ref.dtype)


def flash_decode_paged(q, k_pool, v_pool, page_table, pos,
                       scale: Optional[float] = None,
                       use_pallas: Optional[bool] = None,
                       interpret: bool = False, layer=None, self_kv=None):
    """Decode attention over a PAGED KV cache: each row's logical cache is
    a list of physical pages in a shared pool (``page_table`` [B, NP]
    int32 — logical block j of row b lives at
    ``pool[page_table[b, j]]``), so mixed-length sequences share memory
    without per-row max_len buffers — the PagedAttention layout, realized
    on TPU by routing the page id through the kernel's scalar-prefetched
    BlockSpec index maps (block fetches chase the table; out-of-range
    blocks pin to the last live page and are never re-fetched).

    ``q``: [B, H, D] or [B, t, H, D]; ``k_pool``/``v_pool``:
    [P, KV, page, D] (page and head_dim trailing — the pool's NATIVE
    layout, so no per-call transpose of the shared pool), or the STACKED
    [L, P, KV, page, D] pool with ``layer`` the (traced OK) layer index
    — the layer scan passes the whole pool and the index rides the
    scalar prefetch, so no per-layer slice is materialized.  Plain
    arrays or int8 ``QTensor``s (LANE-MAJOR scales [(L,) P, KV, 1,
    page], as ``init_paged_cache`` builds them; HBM streams int8 and the
    per-position scales fold into the score rows in-kernel);
    ``pos``: scalar or [B] int32 — positions [0..pos(+t-1)] must be
    backed by pages.  Returns q's shape.

    ``self_kv`` (deferred-write decode): the uncommitted chunk's
    ([B, t, KV, D], [B, t, KV, D]) K/V attends as a SELF operand while
    the pool still holds only positions < pos — t = 1 is the
    steady-state deferred step, t > 1 the FUSED multi-row step
    (speculative verify / chunked-prefill tails): t decode rows retire
    through one launch per layer, the page table prefetched once for
    the chunk (int8 pools: pre-quantize-dequantize the chunk so its
    numerics match a committed slot).
    """
    PAGED_CALL_STATS["calls"] += 1
    kp, vp, ksc, vsc, li, quantized = _stacked_cache(k_pool, v_pool, layer)
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    b, t, h, d = q.shape
    kv, ps = kp.shape[2], kp.shape[3]
    _check_gqa_heads(q, kp, vp)     # kv heads at axis 2 of the pool
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    g = h // kv
    # Blocks carry a page's [head_block, page, d] slab per grid cell
    # (head-blocked grid, head_block | kv): eligibility only requires
    # the SINGLE-head slab to fit the VMEM budget — _paged_head_block
    # then folds as many heads per cell as the budget allows (all of
    # them in the common case), so big kv x page x d products shrink
    # the head block instead of losing the kernel.
    aligned = (ps % 8 == 0 and ps <= 1024
               and 4 * ps * d * kp.dtype.itemsize <= _PAGED_VMEM_BUDGET)
    if use_pallas is None:
        on_tpu = jax.default_backend() == "tpu"
        use_pallas = aligned and (on_tpu or interpret)
    elif use_pallas and not aligned:
        raise ValueError(
            f"flash_decode_paged(use_pallas=True): page_size {ps} with "
            f"d={d} is not kernel-eligible (page must be a multiple of "
            f"8, <= 1024, and one head's K/V slabs must fit VMEM)")
    if not use_pallas:
        out = _paged_decode_reference(q, k_pool, v_pool, page_table, pos,
                                      scale, layer=layer, self_kv=self_kv)
        return out[:, 0] if squeeze else out

    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
    if self_kv is None:
        nb = (pos + t - 1) // ps + 1
        bound = pos
    else:
        # Deferred writes: the pool holds positions < pos only — bound
        # the block loop and the mask EXCLUSIVELY; the current token
        # rides the self operands instead of its (stale) cache slot.
        nb = -(-pos // ps)              # ceil(pos / ps); 0 when pos == 0
        bound = pos - 1
    scalars = jnp.stack([nb, bound,
                         jnp.broadcast_to(li, (b,))])           # [3, B]
    page_table = jnp.asarray(page_table, jnp.int32)
    if not quantized and q.dtype != kp.dtype:
        q = q.astype(jnp.promote_types(q.dtype, kp.dtype))
        kp = kp.astype(q.dtype)
    qt = q.reshape(b, t, kv, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b, kv, t * g, d)

    PAGED_CALL_STATS["kernel_calls"] += 1
    # KV heads are FOLDED into the block in head_block slabs (grid
    # (b, kv // head_block, page)): a grid iteration costs ~2.3 us even
    # when skipped, so per-head page loops multiplied pure overhead by
    # KV.  One iteration fetches a page's [head_block, page, d] slab —
    # contiguous in the pool layout, so the DMA stays one dense block —
    # and the head dimension is PARALLEL: blocks share no accumulator,
    # so when VMEM forces head_block < kv the per-slab work spreads
    # across megacore instead of serializing inside one cell.
    head_block = _paged_head_block(kv, ps, d, kp.dtype.itemsize)
    n_hb = kv // head_block
    q_spec = pl.BlockSpec((1, head_block, t * g, d),
                          lambda bi, hi, j, s, pt: (bi, hi, 0, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec(
        (1, 1, head_block, ps, d),
        lambda bi, hi, j, s, pt: (
            s[2, 0], pt[bi, jnp.maximum(jnp.minimum(j, s[0, bi] - 1), 0)],
            hi, 0, 0),
        memory_space=pltpu.VMEM)
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [qt, kp, vp]     # pools already (page, head_dim)-trailing
    if quantized:
        # Scales as [L, P, KV, 1, page]: positions on the lane dim, same
        # page-chasing index map as their values.
        sc_spec = pl.BlockSpec(
            (1, 1, head_block, 1, ps),
            lambda bi, hi, j, s, pt: (
                s[2, 0],
                pt[bi, jnp.maximum(jnp.minimum(j, s[0, bi] - 1), 0)],
                hi, 0, 0),
            memory_space=pltpu.VMEM)
        in_specs += [sc_spec, sc_spec]
        operands += [ksc, vsc]                      # already lane-major
    if self_kv is not None:
        # [B, t, KV, D] model-layout chunks -> [B, KV, t, D] t-slot fp
        # blocks (int8 pools: the caller pre-quantize-dequantizes so
        # numerics match a committed slot exactly).
        kself, vself = (c.transpose(0, 2, 1, 3).astype(q.dtype)
                        for c in self_kv)
        self_spec = pl.BlockSpec((1, head_block, t, d),
                                 lambda bi, hi, j, s, pt: (bi, hi, 0, 0),
                                 memory_space=pltpu.VMEM)
        in_specs += [self_spec, self_spec]
        operands += [kself, vself]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_hb, page_table.shape[1]),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((head_block * t * g, d), jnp.float32),
                        pltpu.VMEM((head_block * t * g, 1), jnp.float32),
                        pltpu.VMEM((head_block * t * g, 1), jnp.float32)])
    # Static cost estimate for the head-blocked grid.  bytes_accessed
    # charges the slabs this call can actually DMA — b rows x live
    # pages x one K + one V [KV, page, d] slab — never the WHOLE pool
    # (the old estimate charged pool bytes: a 1000-page pool serving 4
    # rows x 16 live pages overstated the traffic ~30x and mis-ranked
    # the kernel for the XLA scheduler).  flops/transcendentals use the
    # per-row block bound when ``pos`` is concrete (direct calls,
    # tests, benches); under jit the bound is traced and the TABLE
    # width is the static ceiling — the in-kernel bound still skips the
    # dead iterations either way.
    np_ = page_table.shape[1]
    try:
        est_nb = int(jnp.max(nb))
    except jax.errors.ConcretizationTypeError:
        est_nb = np_
    est_nb = max(1, min(est_nb, np_))
    slab_bytes = kv * ps * d * kp.dtype.itemsize
    out = pl.pallas_call(
        functools.partial(_flash_decode_paged_kernel, block_m=ps,
                          scale=float(scale), quantized=quantized,
                          q_per_kv=g, head_block=head_block,
                          self_attend=self_kv is not None),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * t * h * est_nb * ps * d,
            bytes_accessed=(2 * b * est_nb * slab_bytes
                            + 2 * q.size * q.dtype.itemsize),
            transcendentals=b * t * h * est_nb * ps),
    )(scalars, page_table, *operands)
    out = out.reshape(b, kv, t, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b, t, h, d)
    return out[:, 0] if squeeze else out


def sharded_flash_decode(q, k_cache, v_cache, pos, mesh, layer=None, **kw):
    """``flash_decode`` under GSPMD decode: shard_map over the data axes
    (batch) and tp (kv-major head blocks — the transformer
    ``cache_specs`` layout), each device running the kernel on its local
    [L, b_loc, kv_loc, M, D] cache block.  Requires tp | kv_heads (the
    same alignment condition as ``sharded_flash_attention``).  The output
    stays head-sharded; the caller's output projection contracts it and
    GSPMD inserts the tp psum exactly as on the einsum path.  ``k_cache``
    / ``v_cache`` are the STACKED [L, B, KV, M, D] buffers (lane-major
    int8 ``QTensor``s pair up per leaf; ``layer`` selects the layer
    in-kernel); ``q`` may be [B, H, D] or a chunk [B, t, H, D]."""
    from jax.sharding import PartitionSpec as P

    from tfmesos_tpu.ops.quant import QTensor
    from tfmesos_tpu.parallel.sharding import data_axes

    batch = data_axes(mesh)
    heads = "tp" if mesh.shape.get("tp", 1) > 1 else None
    qspec = (P(batch, heads, None) if q.ndim == 3
             else P(batch, None, heads, None))
    cspec = P(None, batch, heads, None, None)
    if isinstance(k_cache, QTensor):
        cspec = QTensor(cspec, P(None, batch, heads, None, None))
    li = jnp.asarray(0 if layer is None else layer, jnp.int32)
    fn = shard_map(
        lambda q_, k_, v_, p_, l_: flash_decode(q_, k_, v_, p_, layer=l_,
                                                **kw),
        mesh=mesh, in_specs=(qspec, cspec, cspec, P(batch), P()),
        out_specs=qspec, check_vma=False)
    return fn(q, k_cache, v_cache, pos, li)


def sharded_flash_attention(q, k, v, mesh, causal: bool = False,
                            scale: Optional[float] = None, **kw):
    """Flash attention under explicit sharding: shard_map over the mesh's
    batch axes (dp/fsdp) and head axis (tp) so each device runs the Pallas
    kernel on its local [b_loc, T, h_loc, D] block.  Sequence stays
    unsharded here — use ring attention when an ``sp`` axis exists."""
    from jax.sharding import PartitionSpec as P

    from tfmesos_tpu.parallel.sharding import data_axes

    _check_gqa_heads(q, k, v)
    batch = data_axes(mesh)
    heads = "tp" if "tp" in mesh.shape and mesh.shape["tp"] > 1 else None
    if heads is not None and k.shape[2] % mesh.shape["tp"]:
        # GQA/MQA with tp not dividing kv_heads: shard at full head width
        # (tp | kv_heads is also exactly when per-shard h//g grouping stays
        # aligned, so narrower K/V can only ride when it holds).
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    spec = P(batch, None, heads, None)
    if batch is None and heads is None:
        return flash_attention(q, k, v, causal=causal, scale=scale, **kw)
    fn = shard_map(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=causal,
                                           scale=scale, **kw),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def attend(q, k, v, mesh=None, causal: bool = True,
           scale: Optional[float] = None, sp_impl: str = "ring",
           window: Optional[int] = None, **kw):
    """One attention entry point for model code: sequence parallelism when
    the mesh shards the sequence (``sp``) — ring attention by default, or
    Ulysses all-to-all with ``sp_impl="ulysses"`` — sharded flash kernel
    when it shards batch/heads, plain flash/reference otherwise.

    Grouped-query K/V (fewer heads than q) pass straight through to the
    flash/reference paths (head-index mapping, no repeat) and to Ulysses
    (narrow-width K/V all-to-all when sp divides kv_heads); the ring works
    per-head, so GQA inputs are broadcast up for it here."""
    _check_gqa_heads(q, k, v)
    if mesh is not None and "sp" in mesh.shape and mesh.shape["sp"] > 1:
        # Sliding windows compose with both sp paths: Ulysses attends the
        # full sequence after its all-to-all (window passes through to the
        # kernel), and the ring bounds the window exactly across shards
        # on either inner (Pallas via per-step q_offset kernels, einsum
        # via owner-index masks).
        if sp_impl == "ulysses":
            from tfmesos_tpu.parallel.ulysses import ulysses_attention
            return ulysses_attention(q, k, v, mesh, causal=causal,
                                     scale=scale, window=window)
        if k.shape[2] != q.shape[2]:
            rep = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        if sp_impl != "ring":
            raise ValueError(f"sp_impl must be 'ring' or 'ulysses', "
                             f"got {sp_impl!r}")
        from tfmesos_tpu.parallel.ring_attention import ring_attention
        return ring_attention(q, k, v, mesh, causal=causal, scale=scale,
                              window=window)
    if mesh is not None:
        return sharded_flash_attention(q, k, v, mesh, causal=causal,
                                       scale=scale, window=window, **kw)
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           window=window, **kw)
