"""Attention ops: reference MHA and a Pallas TPU flash-attention kernel.

The reference framework has no kernels of its own (SURVEY §2.6) — its FLOPs
live in TF's compiled runtime.  Ours live here: a blocked, online-softmax
attention kernel tiled for the MXU (128-lane blocks, fp32 accumulation,
causal blocks skipped entirely), with a plain-XLA reference implementation
used as ground truth, as the CPU fallback, and to derive the backward pass.

Layouts follow the JAX convention ``[batch, seq, heads, head_dim]``.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")


def mha_reference(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """Plain-XLA scaled-dot-product attention (ground truth / fallback)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        qpos = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        scores = jnp.where(kpos > qpos, NEG_INF, scores)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class _FlashCfg(NamedTuple):
    causal: bool
    scale: float
    block_q: int
    block_k: int
    interpret: bool


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, cfg: _FlashCfg,
                  seq_len: int):
    """One (batch, q-block, head) grid cell: stream K/V blocks with online
    softmax.  Accumulation in fp32; output cast back at the end.

    Refs are laid out ``[1, 1, T, D]`` — (seq, head_dim) must be the trailing
    dims so blocks land on the TPU's (8, 128) tiling.

    Operands stay in their input dtype (bf16 runs the MXU at full rate) with
    fp32 accumulation via ``preferred_element_type``; softmax statistics are
    fp32 throughout.
    """
    q = q_ref[0, 0, :, :]  # [bq, d], input dtype
    bq, bk = cfg.block_q, cfg.block_k
    qi = pl.program_id(1)
    nk = seq_len // bk
    if cfg.causal:
        # Blocks strictly above the diagonal contribute nothing: bound the
        # loop instead of masking them (halves the FLOPs on average).
        nk = jnp.minimum(nk, pl.cdiv((qi + 1) * bq, bk))

    def body(j, carry):
        o, m, l = carry
        k_blk = k_ref[0, 0, pl.ds(j * bk, bk), :]  # [bk, d]
        v_blk = v_ref[0, 0, pl.ds(j * bk, bk), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [bq, bk]
        s = s * cfg.scale
        if cfg.causal:
            qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(kpos > qpos, NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    d = q.shape[-1]
    o0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, nk, body, (o0, m0, l0))
    o_ref[0, 0, :, :] = (o / l).astype(o_ref.dtype)
    # Per-query logsumexp of the SCALED scores: the backward pass reuses it
    # instead of re-sweeping Q.K^T (causal rows always hit the diagonal, so
    # l > 0 here).
    lse_ref[0, 0, :, :] = m + jnp.log(l)


def _flash_forward(cfg: _FlashCfg, q, k, v):
    b, t, h, d = q.shape
    # [B, T, H, D] -> [B, H, T, D]: (seq, head_dim) trailing for TPU tiling.
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    grid = (b, t // cfg.block_q, h)
    q_spec = pl.BlockSpec((1, 1, cfg.block_q, d),
                          lambda bi, qi, hi: (bi, hi, qi, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, 1, k.shape[1], d),
                           lambda bi, qi, hi: (bi, hi, 0, 0),
                           memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec((1, 1, cfg.block_q, 1),
                            lambda bi, qi, hi: (bi, hi, qi, 0),
                            memory_space=pltpu.VMEM)
    kernel = functools.partial(_flash_kernel, cfg=cfg, seq_len=k.shape[1])
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[q_spec, lse_spec],
        out_shape=[jax.ShapeDtypeStruct(qt.shape, q.dtype),
                   jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32)],
        interpret=cfg.interpret,
        compiler_params=None if cfg.interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel")),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * t * k.shape[1] * d,
            bytes_accessed=(q.size + k.size + v.size + q.size) * q.dtype.itemsize,
            transcendentals=b * h * t * k.shape[1],
        ),
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse


def _mha_bwd_blockwise(cfg: _FlashCfg, q, k, v, o, lse, do):
    """Analytical flash-attention backward, blockwise over K/V.

    Never materializes the [T, T] probability matrix: per K-block
    recomputation against the per-query logsumexp (``lse``, emitted by the
    forward kernel), with the standard identities dv = pᵀ·do,
    ds = p ⊙ (do·vᵀ − D), dq += ds·k, dk += dsᵀ·q where D = rowsum(do ⊙ o).
    Memory is O(T·(D + block)) instead of the O(T²) a straight vjp of the
    reference softmax costs.
    """
    in_dtype = q.dtype
    # layout: [B,H,T,D] fp32 throughout
    qf, kf, vf, of, dof = (x.transpose(0, 2, 1, 3).astype(jnp.float32)
                           for x in (q, k, v, o, do))
    qf = qf * cfg.scale
    b, h, t, d = qf.shape
    block_k = min(cfg.block_k, kf.shape[2])
    nk = kf.shape[2] // block_k

    delta = jnp.sum(dof * of, axis=-1, keepdims=True)        # [B,H,T,1]
    kb = kf.reshape(b, h, nk, block_k, d)
    vb = vf.reshape(b, h, nk, block_k, d)
    qpos = jax.lax.broadcasted_iota(jnp.int32, (t, block_k), 0)

    def body(dq, j):
        s = jnp.einsum("bhtd,bhkd->bhtk", qf, kb[:, :, j])
        if cfg.causal:
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (t, block_k), 1)
            s = jnp.where((kpos > qpos)[None, None], NEG_INF, s)
        p = jnp.exp(s - lse)                                  # [B,H,T,bk]
        dv_j = jnp.einsum("bhtk,bhtd->bhkd", p, dof)
        dp = jnp.einsum("bhtd,bhkd->bhtk", dof, vb[:, :, j])
        ds = p * (dp - delta)
        dq = dq + jnp.einsum("bhtk,bhkd->bhtd", ds, kb[:, :, j]) * cfg.scale
        dk_j = jnp.einsum("bhtk,bhtd->bhkd", ds, qf)  # qf pre-scaled
        return dq, (dk_j, dv_j)

    dq0 = jnp.zeros_like(qf)
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(body, dq0, jnp.arange(nk))
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(b, h, nk * block_k, d)
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(b, h, nk * block_k, d)
    back = lambda x: x.transpose(0, 2, 1, 3).astype(in_dtype)
    return back(dq), back(dk), back(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _FlashCfg, q, k, v):
    return _flash_forward(cfg, q, k, v)[0]


def _flash_fwd(cfg, q, k, v):
    o, lse = _flash_forward(cfg, q, k, v)
    return o, (q, k, v, o, lse)


def _flash_bwd(cfg, res, g):
    q, k, v, o, lse = res
    return _mha_bwd_blockwise(cfg, q, k, v, o, lse, g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    use_pallas: Optional[bool] = None,
                    interpret: bool = False):
    """Blocked attention; Pallas kernel on TPU, reference math elsewhere.

    ``use_pallas=None`` auto-selects: the kernel runs when the default
    backend is TPU (or when ``interpret=True`` for tests) and shapes are
    block-aligned; otherwise the XLA reference path runs — same numerics,
    same signature, so model code never branches.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    t = q.shape[1]
    block_q = min(block_q, t)
    block_k = min(block_k, k.shape[1])
    # TPU tiling: a block's sublane dim must be a multiple of 8 OR span the
    # whole array dim (Mosaic's equal-to-dim exception); clamping block to t
    # satisfies the exception, so only the multi-block case needs 8-alignment.
    aligned = (t % block_q == 0 and k.shape[1] % block_k == 0
               and (block_q % 8 == 0 or block_q == t)
               and (block_k % 8 == 0 or block_k == k.shape[1]))
    if use_pallas is None:
        on_tpu = jax.default_backend() == "tpu"
        use_pallas = aligned and (on_tpu or interpret)
    elif use_pallas and not aligned:
        # Fail fast on a forced-pallas misuse: silently running the kernel
        # with non-dividing blocks would truncate keys (and their grads).
        raise ValueError(
            f"flash_attention(use_pallas=True): seq lens {t}/{k.shape[1]} "
            f"not divisible by blocks ({block_q}, {block_k})")
    if not use_pallas:
        return mha_reference(q, k, v, causal=causal, scale=scale)
    cfg = _FlashCfg(causal=bool(causal), scale=float(scale),
                    block_q=block_q, block_k=block_k, interpret=bool(interpret))
    return _flash(cfg, q, k, v)


def sharded_flash_attention(q, k, v, mesh, causal: bool = False,
                            scale: Optional[float] = None, **kw):
    """Flash attention under explicit sharding: shard_map over the mesh's
    batch axes (dp/fsdp) and head axis (tp) so each device runs the Pallas
    kernel on its local [b_loc, T, h_loc, D] block.  Sequence stays
    unsharded here — use ring attention when an ``sp`` axis exists."""
    from jax.sharding import PartitionSpec as P

    from tfmesos_tpu.parallel.sharding import data_axes

    batch = data_axes(mesh)
    heads = "tp" if "tp" in mesh.shape and mesh.shape["tp"] > 1 else None
    spec = P(batch, None, heads, None)
    if batch is None and heads is None:
        return flash_attention(q, k, v, causal=causal, scale=scale, **kw)
    fn = jax.shard_map(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=causal,
                                           scale=scale, **kw),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def attend(q, k, v, mesh=None, causal: bool = True,
           scale: Optional[float] = None, **kw):
    """One attention entry point for model code: ring attention when the
    mesh shards the sequence (``sp``), sharded flash kernel when it shards
    batch/heads, plain flash/reference otherwise."""
    if mesh is not None and "sp" in mesh.shape and mesh.shape["sp"] > 1:
        from tfmesos_tpu.parallel.ring_attention import ring_attention
        return ring_attention(q, k, v, mesh, causal=causal, scale=scale)
    if mesh is not None:
        return sharded_flash_attention(q, k, v, mesh, causal=causal,
                                       scale=scale, **kw)
    return flash_attention(q, k, v, causal=causal, scale=scale, **kw)
