"""Int8 quantization kernels (Pallas), for activation/weight compression.

Per-row absmax scaling: ``x ≈ values * scales[row]`` with int8 values.
The TPU kernel optionally uses stochastic rounding (hardware PRNG) — the
right choice when quantized tensors feed training — while the XLA reference
path rounds to nearest.  HBM-bandwidth win: int8 halves bf16 traffic for
communication-bound tensors (e.g. cross-DCN gradient exchange).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _absmax_scale(x):
    """Shared scale rule (host paths and kernel alike): per-row absmax / 127
    with zero rows pinned to scale 1.0."""
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    return jnp.where(absmax == 0, 1.0, absmax / 127.0)


def quantize_int8_reference(x) -> Tuple[jax.Array, jax.Array]:
    """Round-to-nearest per-row absmax quantization (ground truth)."""
    xf = x.astype(jnp.float32)
    scale = _absmax_scale(xf)
    values = jnp.clip(jnp.round(xf / scale), -127, 127)
    return values.astype(jnp.int8), scale.astype(jnp.float32)


def dequantize_int8(values, scales):
    return values.astype(jnp.float32) * scales


def _quant_kernel(seed_ref, x_ref, values_ref, scales_ref, *, stochastic: bool):
    x = x_ref[:].astype(jnp.float32)
    scale = _absmax_scale(x)
    scaled = x / scale
    if stochastic:
        # Per-block seed so different row blocks draw different dither.
        pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
        bits = pltpu.bitcast(pltpu.prng_random_bits(scaled.shape), jnp.uint32)
        # Uniform dither in [-0.5, 0.5) then round == stochastic rounding.
        # Mosaic has no uint32->f32 cast: drop to 24 bits via int32 first
        # (top byte shifted out, so the sign bit is always clear).
        bits24 = pltpu.bitcast(bits >> 8, jnp.int32)
        dither = bits24.astype(jnp.float32) / jnp.float32(2 ** 24) - 0.5
        scaled = scaled + dither
    values_ref[:] = jnp.clip(jnp.round(scaled), -127, 127).astype(jnp.int8)
    scales_ref[:] = scale


def _row_block(rows: int, cols: int, budget_elems: int = 512 * 1024):
    """Largest 8-aligned divisor of ``rows`` whose fp32 block fits the VMEM
    budget (~2MB input + pipelining headroom), or ``None`` if no legal
    tiling exists (caller falls back to the XLA path).

    Mosaic only accepts sublane dims that are multiples of 8 or equal to the
    full array dim — interpret mode is laxer, so an unaligned block compiles
    in tests but fails on real TPU.  Rows are independent, so any exact
    split is valid and no remainder handling is needed.
    """
    max_block = max(8, budget_elems // max(1, cols))
    if rows <= max_block:
        return rows  # whole dim in one block — always legal
    for candidate in range(max_block - max_block % 8, 7, -8):
        if rows % candidate == 0:
            return candidate
    return None


class QTensor(NamedTuple):
    """A weight stored as int8 values with fp32 scales over the last dim's
    rows (``w ≈ values * scales``).  A NamedTuple, so it is a pytree —
    QTensors travel through jit/scan/checkpoint like any array pair, and
    model code can branch on ``isinstance`` at trace time."""

    values: jax.Array  # int8, same shape as the original weight
    scales: jax.Array  # fp32, original shape with the last dim = 1

    def dequantize(self, dtype=jnp.float32):
        """Materialize the approximated weight.  Under jit the convert+scale
        fuses into the consuming matmul, so int8 (not fp) is what HBM
        streams — the whole point for bandwidth-bound decode."""
        return (self.values.astype(dtype)
                * self.scales.astype(dtype))


def quantize_tensor(w, stochastic: bool = False, seed: int = 0) -> QTensor:
    """Quantize an N-D weight to a :class:`QTensor` (per-row absmax over the
    last dim, rows = all leading dims flattened)."""
    shape = w.shape
    values, scales = quantize_int8(w.reshape(-1, shape[-1]),
                                   stochastic=stochastic, seed=seed)
    return QTensor(values.reshape(shape),
                   scales.reshape(shape[:-1] + (1,)))


def quantize_int8(x, stochastic: bool = False, seed: int = 0,
                  use_pallas: bool = None, interpret: bool = False):
    """Quantize ``[rows, cols]`` to (int8 values, fp32 per-row scales)."""
    if x.ndim != 2:
        raise ValueError(f"expected 2D input, got shape {x.shape}")
    if use_pallas is None:
        use_pallas = interpret or jax.default_backend() == "tpu"
    if stochastic and interpret:
        # The Pallas interpreter doesn't implement the TPU PRNG; the XLA
        # path has identical semantics (uniform dither then round).
        use_pallas = False
    rows, cols = x.shape
    if use_pallas:
        br = _row_block(rows, cols)
        if br is None:
            use_pallas = False  # no 8-aligned exact row split exists
    if not use_pallas:
        xf = x.astype(jnp.float32)
        scale = _absmax_scale(xf)
        scaled = xf / scale
        if stochastic:
            dither = jax.random.uniform(jax.random.PRNGKey(seed),
                                        scaled.shape) - 0.5
            scaled = scaled + dither
        values = jnp.clip(jnp.round(scaled), -127, 127)
        return values.astype(jnp.int8), scale.astype(jnp.float32)

    kernel = functools.partial(_quant_kernel, stochastic=stochastic)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(rows // br,),
            in_specs=[pl.BlockSpec((br, cols), lambda i, *_: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=[pl.BlockSpec((br, cols), lambda i, *_: (i, 0),
                                    memory_space=pltpu.VMEM),
                       pl.BlockSpec((br, 1), lambda i, *_: (i, 0),
                                    memory_space=pltpu.VMEM)],
        ),
        out_shape=[jax.ShapeDtypeStruct((rows, cols), jnp.int8),
                   jax.ShapeDtypeStruct((rows, 1), jnp.float32)],
        interpret=interpret,
    )(jnp.array([seed], jnp.int32), x)
