"""Elementwise/normalization building blocks.

Kept as plain jnp functions — XLA fuses these into surrounding matmuls on
TPU; a Pallas kernel would only pay off for exotic fusions the compiler
misses (none here yet).  fp32 accumulation for the reductions, compute dtype
preserved on the output.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight


def swiglu(x, w_gate, w_up, w_down):
    """SwiGLU MLP: silu(x·Wg) ⊙ (x·Wu) · Wd."""
    g = jax.nn.silu(x @ w_gate)
    return (g * (x @ w_up)) @ w_down


def rope(x, positions, theta: float = 10000.0):
    """Rotary position embedding over the last (head_dim) axis.

    ``x``: [..., T, H, D]; ``positions``: [..., T] int32.
    """
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., T, half]
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def cross_entropy_loss(logits, labels, z_loss: float = 0.0):
    """Mean softmax cross entropy in fp32; optional z-loss regularizer."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    loss = jnp.mean(logz - picked)
    if z_loss:
        loss = loss + z_loss * jnp.mean(logz ** 2)
    return loss
