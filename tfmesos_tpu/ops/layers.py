"""Elementwise/normalization building blocks.

Kept as plain jnp functions — XLA fuses these into surrounding matmuls on
TPU; a Pallas kernel would only pay off for exotic fusions the compiler
misses (none here yet).  fp32 accumulation for the reductions, compute dtype
preserved on the output.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tfmesos_tpu.compat import shard_map


def rms_norm(x, weight, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight


def swiglu(x, w_gate, w_up, w_down):
    """SwiGLU MLP: silu(x·Wg) ⊙ (x·Wu) · Wd."""
    g = jax.nn.silu(x @ w_gate)
    return (g * (x @ w_up)) @ w_down


def rope(x, positions, theta: float = 10000.0):
    """Rotary position embedding over the last (head_dim) axis.

    ``x``: [..., T, H, D]; ``positions``: [..., T] int32.
    """
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., T, half]
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def cross_entropy_loss(logits, labels, z_loss: float = 0.0):
    """Mean softmax cross entropy in fp32; optional z-loss regularizer."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    loss = jnp.mean(logz - picked)
    if z_loss:
        loss = loss + z_loss * jnp.mean(logz ** 2)
    return loss


def _ce_chunk(n: int, target: int) -> int:
    """Largest divisor of ``n`` at or under ``target``; if the best divisor
    is tiny (awkward token counts — e.g. prime n — have none near the
    target), return ``n`` itself: one full-size chunk costs the same memory
    as the unfused path, whereas a scan of tiny matmuls would be
    pathologically slow."""
    target = min(n, max(1, target))
    c = target
    while n % c:
        c -= 1
    return c if c * 8 >= target else n


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_linear_cross_entropy(x, w, labels, z_loss: float = 0.0,
                               chunk: int = 2048):
    """Mean softmax cross entropy of ``logits = x @ w`` WITHOUT materializing
    the full logits tensor.

    ``x``: [..., d] pre-head activations; ``w``: [d, V]; ``labels``: [...]
    int.  Tokens are flattened and processed in chunks of ``chunk`` (largest
    divisor of the token count at or under it): each chunk's logits live
    only inside one scan step, fwd and bwd — so peak memory carries one
    [chunk, V] block instead of [N, V] (at B8/T2048/V8192 fp32 that is
    64MB instead of 512MB), and the HBM never round-trips the full logits
    between the matmul, the softmax and their gradients.

    The price is one extra logits matmul in the backward (recompute from
    the saved per-token logsumexp) — +2·d·V FLOPs/token against the
    ~6·d·V the head already costs fwd+bwd, bought back several times over
    in bandwidth at large V.  Numerics match ``cross_entropy_loss`` (both
    reduce in fp32; only the reduction grouping differs).
    """
    loss, _ = _flce_fwd(x, w, labels, z_loss, chunk)
    return loss


def _flce_flatten(x, labels, chunk):
    d = x.shape[-1]
    xf = x.reshape(-1, d)
    lf = labels.reshape(-1)
    n = xf.shape[0]
    c = _ce_chunk(n, chunk)
    return xf.reshape(n // c, c, d), lf.reshape(n // c, c), n


def _flce_fwd(x, w, labels, z_loss, chunk):
    xs, ls, n = _flce_flatten(x, labels, chunk)
    wc = w.astype(x.dtype)

    def body(acc, inp):
        xc, lc = inp
        logits = (xc @ wc).astype(jnp.float32)          # [c, V]
        logz = jax.nn.logsumexp(logits, axis=-1)        # [c]
        picked = jnp.take_along_axis(
            logits, lc[:, None], axis=-1)[:, 0]
        s = jnp.sum(logz - picked)
        if z_loss:
            s = s + z_loss * jnp.sum(logz ** 2)
        return acc + s, logz

    total, logzs = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xs, ls))
    return total / n, (x, w, labels, logzs)


def _flce_bwd(z_loss, chunk, res, g):
    x, w, labels, logzs = res
    xs, ls, n = _flce_flatten(x, labels, chunk)
    wc = w.astype(x.dtype)
    scale = g / n

    def body(dw_acc, inp):
        xc, lc, logz = inp
        logits = (xc @ wc).astype(jnp.float32)
        p = jnp.exp(logits - logz[:, None])             # softmax, [c, V]
        coeff = 1.0 + (2.0 * z_loss) * logz if z_loss else None
        dlogits = p * coeff[:, None] if z_loss else p
        dlogits = (dlogits - jax.nn.one_hot(lc, logits.shape[-1],
                                            dtype=jnp.float32)) * scale
        dlogits = dlogits.astype(x.dtype)
        dx_c = dlogits @ wc.T                           # [c, d]
        dw_acc = dw_acc + jax.lax.dot_general(
            xc, dlogits, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [d, V] fp32
        return dw_acc, dx_c

    dw, dxs = jax.lax.scan(
        body, jnp.zeros(w.shape, jnp.float32), (xs, ls, logzs))
    dx = dxs.reshape(x.shape).astype(x.dtype)
    return dx, dw.astype(w.dtype), None


fused_linear_cross_entropy.defvjp(_flce_fwd, _flce_bwd)


def _vp_batch_axes(mesh):
    """(data axes, total data-parallel degree) for the vocab-parallel CE."""
    from tfmesos_tpu.parallel.sharding import data_axes

    batch = data_axes(mesh)
    nb = 1
    for a in (batch or ()):
        nb *= mesh.shape[a]
    return batch, nb


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def vocab_parallel_cross_entropy(x, w, labels, mesh, axis: str = "tp",
                                 z_loss: float = 0.0, chunk: int = 2048):
    """``fused_linear_cross_entropy`` for a tensor-parallel (vocab-sharded)
    unembedding: ``w`` [d, V] sharded over ``axis`` on its vocab dim, ``x``
    [B, T, d] and ``labels`` [B, T] sharded over the data axes and
    replicated over ``axis``.

    Each device computes chunked logits against its own [d, V/tp] shard;
    the softmax max / sum-exp / picked-label statistics psum over ``axis``
    (the Megatron vocab-parallel pattern), so no device ever holds more
    than a [chunk, V/tp] block — fwd or bwd.  The returned scalar is the
    global-mean loss, identical math to the unfused path.

    Forward and backward are each ONE explicit ``shard_map`` with all
    cross-device sums written out (tp psums for the softmax statistics and
    dx, data-axis psums for the loss and dw) — the custom VJP sits outside
    the shard_maps, so no gradient ever flows through shard_map's implicit
    replication/transpose rules.
    """
    loss, _ = _vp_fwd(x, w, labels, mesh, axis, z_loss, chunk)
    return loss


def _vp_fwd(x, w, labels, mesh, axis, z_loss, chunk):
    if w.shape[-1] % mesh.shape[axis]:
        raise ValueError(
            f"vocab size {w.shape[-1]} must divide over {axis} "
            f"({mesh.shape[axis]})")
    batch, nb = _vp_batch_axes(mesh)

    def local(xl, wl, ll):
        # Per-shard math shared with the in-body variant (_vpi_fwd
        # returns the LOCAL token mean); equal-sized data shards make
        # the mean-of-means the global mean.
        loss_loc, (_, _, _, logzs) = _vpi_fwd(xl, wl, ll, axis, z_loss,
                                              chunk)
        if batch:
            loss_loc = jax.lax.psum(loss_loc, batch) / nb
        return loss_loc, logzs

    loss, logzs = shard_map(
        local, mesh=mesh,
        in_specs=(P(batch, None, None), P(None, axis), P(batch, None)),
        out_specs=(P(), P(batch, None)), check_vma=False)(x, w, labels)
    return loss, (x, w, labels, logzs)


def _vp_bwd(mesh, axis, z_loss, chunk, res, g):
    x, w, labels, logzs = res
    batch, nb = _vp_batch_axes(mesh)

    def local(xl, wl, ll, logzs_l, gl):
        # Shared per-shard bwd body; dw stays fp32 until after the
        # cross-data-shard psum (accumulate wide, cast once).
        dx, dw = _vpi_grads(axis, z_loss, chunk, (xl, wl, ll, logzs_l),
                            gl / nb)
        if batch:
            dw = jax.lax.psum(dw, batch)                # all tokens' sum
        return dx, dw.astype(wl.dtype)

    dx, dw = shard_map(
        local, mesh=mesh,
        in_specs=(P(batch, None, None), P(None, axis), P(batch, None),
                  P(batch, None), P()),
        out_specs=(P(batch, None, None), P(None, axis)),
        check_vma=False)(x, w, labels, logzs, g)
    return dx, dw, None


vocab_parallel_cross_entropy.defvjp(_vp_fwd, _vp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def vocab_parallel_ce_inbody(x, w, labels, axis: str = "tp",
                             z_loss: float = 0.0, chunk: int = 2048):
    """``vocab_parallel_cross_entropy``'s per-shard body as a standalone
    custom-VJP, for callers ALREADY INSIDE a ``shard_map`` with ``axis``
    manual — the 1F1B pipeline's loss tail.  ``w`` is this device's
    [d, V/tp] vocab shard, ``x``/``labels`` the local microbatch.  All
    tp collectives are written out explicitly in BOTH directions
    (softmax statistics psums forward, the dx psum backward), so the
    in-body ``jax.vjp`` the 1F1B backward runs never transposes a
    collective.  Returns the LOCAL token-mean loss; cross-data-shard
    averaging is the caller's (the pipeline pmean-reduces loss and
    grads over the data axes itself)."""
    loss, _ = _vpi_fwd(x, w, labels, axis, z_loss, chunk)
    return loss


def _vpi_fwd(x, w, labels, axis, z_loss, chunk):
    """Per-shard fwd body — also the inner engine of the shard_map'd
    ``vocab_parallel_cross_entropy`` (one implementation of the math)."""
    xs, ls, n_loc = _flce_flatten(x, labels, chunk)
    wc = w.astype(x.dtype)
    vloc = w.shape[-1]
    voff = jax.lax.axis_index(axis) * vloc

    def body(acc, inp):
        xc, lc = inp
        logits = (xc @ wc).astype(jnp.float32)          # [c, Vloc]
        m = jax.lax.pmax(jnp.max(logits, axis=-1), axis)
        se = jax.lax.psum(
            jnp.sum(jnp.exp(logits - m[:, None]), axis=-1), axis)
        logz = m + jnp.log(se)
        mine = (lc >= voff) & (lc < voff + vloc)
        idx = jnp.clip(lc - voff, 0, vloc - 1)
        picked = jax.lax.psum(
            jnp.where(mine, jnp.take_along_axis(
                logits, idx[:, None], axis=-1)[:, 0], 0.0), axis)
        s = jnp.sum(logz - picked)
        if z_loss:
            s = s + z_loss * jnp.sum(logz ** 2)
        return acc + s, logz

    total, logzs = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                (xs, ls))
    return total / n_loc, (x, w, labels, logzs)


def _vpi_grads(axis, z_loss, chunk, res, g):
    """Per-shard bwd body; returns (dx at x's dtype, dw in fp32) so the
    shard_map'd wrapper can psum dw across data shards BEFORE casting."""
    x, w, labels, logzs = res
    xs, ls, n_loc = _flce_flatten(x, labels, chunk)
    wc = w.astype(x.dtype)
    vloc = w.shape[-1]
    voff = jax.lax.axis_index(axis) * vloc
    scale = g / n_loc

    def body(dw_acc, inp):
        xc, lc, logz = inp
        logits = (xc @ wc).astype(jnp.float32)
        p = jnp.exp(logits - logz[:, None])             # local softmax cols
        if z_loss:
            p = p * (1.0 + (2.0 * z_loss) * logz)[:, None]
        mine = (lc >= voff) & (lc < voff + vloc)
        idx = jnp.clip(lc - voff, 0, vloc - 1)
        onehot = (jax.nn.one_hot(idx, vloc, dtype=jnp.float32)
                  * mine[:, None].astype(jnp.float32))
        dlogits = ((p - onehot) * scale).astype(x.dtype)
        dx_c = jax.lax.psum(dlogits @ wc.T, axis)       # every vocab shard
        dw_acc = dw_acc + jax.lax.dot_general(
            xc, dlogits, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dw_acc, dx_c

    dw, dxs = jax.lax.scan(
        body, jnp.zeros(w.shape, jnp.float32), (xs, ls, logzs))
    return dxs.reshape(x.shape).astype(x.dtype), dw


def _vpi_bwd(axis, z_loss, chunk, res, g):
    dx, dw = _vpi_grads(axis, z_loss, chunk, res, g)
    return dx, dw.astype(res[1].dtype), None


vocab_parallel_ce_inbody.defvjp(_vpi_fwd, _vpi_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def data_parallel_fused_cross_entropy(x, w, labels, mesh, z_loss: float = 0.0,
                                      chunk: int = 2048):
    """``fused_linear_cross_entropy`` for data-parallel meshes: ``x``
    [B, T, d] and ``labels`` [B, T] batch-sharded over the data axes,
    ``w`` [d, V] replicated (or fsdp-sharded — GSPMD gathers it at the
    boundary exactly as the unfused head matmul would).

    Each device runs the chunked scan over ITS OWN tokens only, so no
    chunk ever cuts across the batch sharding (the naive chunked scan
    flattens [B·T] in an order that interleaves devices' shards, forcing
    GSPMD to reshard every step).  Loss and dw psum over the data axes;
    dx stays local.  Same math as the dense form — only the reduction
    grouping differs.
    """
    loss, _ = _dp_fwd(x, w, labels, mesh, z_loss, chunk)
    return loss


def _dp_fwd(x, w, labels, mesh, z_loss, chunk):
    batch, nb = _vp_batch_axes(mesh)

    def local(xl, wl, ll):
        xs, ls, n_loc = _flce_flatten(xl, ll, chunk)
        wc = wl.astype(xl.dtype)

        def body(acc, inp):
            xc, lc = inp
            logits = (xc @ wc).astype(jnp.float32)      # [c, V]
            logz = jax.nn.logsumexp(logits, axis=-1)
            picked = jnp.take_along_axis(logits, lc[:, None], axis=-1)[:, 0]
            s = jnp.sum(logz - picked)
            if z_loss:
                s = s + z_loss * jnp.sum(logz ** 2)
            return acc + s, logz

        total, logzs = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                    (xs, ls))
        if batch:
            total = jax.lax.psum(total, batch)          # global token sum
        return total / (n_loc * nb), logzs

    loss, logzs = shard_map(
        local, mesh=mesh,
        in_specs=(P(batch, None, None), P(None, None), P(batch, None)),
        out_specs=(P(), P(batch, None)), check_vma=False)(x, w, labels)
    return loss, (x, w, labels, logzs)


def _dp_bwd(mesh, z_loss, chunk, res, g):
    x, w, labels, logzs = res
    batch, nb = _vp_batch_axes(mesh)

    def local(xl, wl, ll, logzs_l, gl):
        xs, ls, n_loc = _flce_flatten(xl, ll, chunk)
        wc = wl.astype(xl.dtype)
        scale = gl / (n_loc * nb)

        def body(dw_acc, inp):
            xc, lc, logz = inp
            logits = (xc @ wc).astype(jnp.float32)
            p = jnp.exp(logits - logz[:, None])
            if z_loss:
                p = p * (1.0 + (2.0 * z_loss) * logz)[:, None]
            onehot = jax.nn.one_hot(lc, logits.shape[-1], dtype=jnp.float32)
            dlogits = ((p - onehot) * scale).astype(xl.dtype)
            dx_c = dlogits @ wc.T
            dw_acc = dw_acc + jax.lax.dot_general(
                xc, dlogits, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return dw_acc, dx_c

        dw, dxs = jax.lax.scan(
            body, jnp.zeros(wl.shape, jnp.float32), (xs, ls, logzs_l))
        if batch:
            dw = jax.lax.psum(dw, batch)                # all tokens' sum
        return dxs.reshape(xl.shape).astype(xl.dtype), dw.astype(wl.dtype)

    dx, dw = shard_map(
        local, mesh=mesh,
        in_specs=(P(batch, None, None), P(None, None), P(batch, None),
                  P(batch, None), P()),
        out_specs=(P(batch, None, None), P(None, None)),
        check_vma=False)(x, w, labels, logzs, g)
    return dx, dw, None


data_parallel_fused_cross_entropy.defvjp(_dp_fwd, _dp_bwd)
