"""``tfrun`` — the between-graph CLI (reference: script/tfrun).

Keeps the reference's full flag surface (tfrun:11-33): ``-w`` workers and
``-s`` servers (now mesh-axis sizes, per the north star), per-job resource
flags, volumes, containerizer choice, extra-config JSON, and
``--worker-logs`` log forwarding.  ``-Gw/-Gs`` count TPU chips instead of
GPUs.  New flags: ``--gang`` (all-or-nothing placement for slice atomicity)
and ``--mesh dp=4,tp=2`` (explicit mesh axes handed to tasks).

The log collector reproduces tfrun:83-115: tasks named by ``--worker-logs``
dial back and every line they print arrives on our stdout with a
``[job:idx]`` prefix, while we poll ``cluster.finished()``.
"""

from __future__ import annotations

import argparse
import json
import os
import selectors
import sys
import threading
import time
from typing import Dict, List, Optional

from tfmesos_tpu import cluster, wire
from tfmesos_tpu.spec import Job
from tfmesos_tpu.utils.logging import get_logger

log = get_logger("tfmesos_tpu.tfrun")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tfrun",
        description="Run a distributed command on a TPU cluster scheduled "
                    "via Mesos (or locally).")
    p.add_argument("-w", "--nworker", type=int, required=True,
                   help="number of worker tasks (data-parallel mesh axis)")
    p.add_argument("-s", "--nserver", type=int, required=True,
                   help="number of server tasks (0 for pure FSDP; kept for "
                        "CLI parity — there are no parameter servers on TPU)")
    p.add_argument("-m", "--master", type=str, default=None,
                   help="Mesos master (host:port or zk://...); default env "
                        "MESOS_MASTER, else local backend")
    p.add_argument("-n", "--name", type=str, default=None, help="framework name")
    p.add_argument("-C", "--containerizer_type", choices=["MESOS", "DOCKER"],
                   default=None)
    p.add_argument("-f", "--force_pull_image", action="store_true")
    p.add_argument("-Cw", "--worker_cpus", type=float, default=1.0)
    p.add_argument("-Gw", "--worker_chips", type=int, default=0,
                   help="TPU chips per worker (was GPUs in the reference)")
    p.add_argument("-Mw", "--worker_mem", type=float, default=1024.0)
    p.add_argument("-Cs", "--server_cpus", type=float, default=1.0)
    p.add_argument("-Gs", "--server_chips", type=int, default=0)
    p.add_argument("-Ms", "--server_mem", type=float, default=1024.0)
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("-V", "--volume", action="append", default=[],
                   metavar="SRC:DST", help="host->container mount (repeatable)")
    p.add_argument("-r", "--role", type=str, default="*")
    p.add_argument("-e", "--extra_config", type=str, default=None,
                   metavar="FILE.json",
                   help="JSON file with extra config (initializer/finalizer "
                        "hooks etc.)")
    p.add_argument("--worker-logs", type=str, default="0",
                   help="comma-separated worker indices (or '*') whose output "
                        "to collect; default chief only")
    p.add_argument("--gang", action="store_true",
                   help="all-or-nothing placement (TPU slice atomicity)")
    p.add_argument("--restarts", type=int, default=0,
                   help="auto-restart the whole cluster up to N times on any "
                        "cluster failure, bring-up or post-start (a "
                        "between-graph framework cannot tell a crashed "
                        "command from dead infrastructure — both are "
                        "TASK_FAILED; bring-up already retries placement 3x "
                        "per attempt). Pair with workload checkpoints for "
                        "resume. Default 0 = fail fast like the reference")
    p.add_argument("--restart-policy", choices=["fail_fast", "elastic"],
                   default="fail_fast", dest="restart_policy",
                   help="post-start failure policy: fail_fast aborts the "
                        "whole cluster on any task death (the reference "
                        "behavior); elastic tears down survivors, bumps "
                        "the gang generation, re-forms from fresh offers "
                        "with backoff, and re-broadcasts cluster_def — "
                        "tasks restart their command and should resume "
                        "from their own checkpoints "
                        "(docs/FAULT_TOLERANCE.md)")
    p.add_argument("--max-cluster-restarts", type=int, default=3,
                   dest="max_cluster_restarts",
                   help="elastic restart budget: at most N gang "
                        "re-formations per sliding --restart-window, then "
                        "fatal (crash loops are a problem restarts cannot "
                        "fix)")
    p.add_argument("--restart-window", type=float, default=600.0,
                   dest="restart_window",
                   help="seconds of sliding window the elastic restart "
                        "budget counts over")
    p.add_argument("--mesh", type=str, default=None,
                   help="explicit mesh axes, e.g. dp=4,tp=2; prefix an axis "
                        "with dcn. to span pod slices over the data-center "
                        "network, e.g. dcn.dp=2,dp=2,tp=4")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="command to run on every task (placeholders: "
                        "{ps_hosts} {worker_hosts} {job_name} {task_index} "
                        "{rank} {world_size} {coordinator})")
    return p


def parse_mesh(spec: Optional[str]) -> Optional[Dict[str, int]]:
    if not spec:
        return None
    axes = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        if not size:
            raise ValueError(f"bad mesh axis {part!r}; want name=size")
        axes[name.strip()] = int(size)
    return axes


def parse_volumes(volumes: List[str]) -> Dict[str, str]:
    out = {}
    for v in volumes:
        src, _, dst = v.partition(":")
        if not dst:
            raise ValueError(f"bad volume {v!r}; want src:dst")
        out[src] = dst
    return out


class LogCollector:
    """Accepts task connections and splices their lines to stdout
    (reference: tfrun:83-115 select loop)."""

    def __init__(self) -> None:
        self._listen = wire.bind_ephemeral()
        self.addr = wire.sock_addr(self._listen)
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._listen, selectors.EVENT_READ, "accept")

    def pump(self, timeout: float = 0.1) -> None:
        for key, _ in self._sel.select(timeout=timeout):
            if key.data == "accept":
                conn, _ = self._listen.accept()
                conn.setblocking(False)
                self._sel.register(conn, selectors.EVENT_READ, "conn")
                continue
            try:
                data = key.fileobj.recv(65536)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError:
                data = b""
            if not data:
                self._sel.unregister(key.fileobj)
                key.fileobj.close()
                continue
            sys.stdout.buffer.write(data)
            sys.stdout.buffer.flush()

    def close(self) -> None:
        self.pump(timeout=0)  # drain anything already queued
        for key in list(self._sel.get_map().values()):
            if key.data == "conn":
                key.fileobj.close()
        self._sel.close()
        self._listen.close()


def forward_map(worker_logs: str, nworker: int, collector_addr: str) -> Dict[str, str]:
    """--worker-logs '0' | '1,3' | '*' → forward_addresses (tfrun:89-94)."""
    if worker_logs.strip() == "*":
        return {f"worker:{i}": collector_addr for i in range(nworker)}
    out = {}
    for tok in worker_logs.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if not tok.isdigit():
            raise ValueError(f"bad --worker-logs entry {tok!r}; want indices or '*'")
        out[f"worker:{tok}"] = collector_addr
    return out


def build_serve_parser() -> argparse.ArgumentParser:
    """``tfserve`` — the online-serving entry point: gateway + N batcher
    replicas scheduled as Mode-B tasks (fleet subsystem,
    docs/SERVING.md "Online serving & the fleet gateway")."""
    p = argparse.ArgumentParser(
        prog="tfserve",
        description="Serve a model online: a fleet gateway fronting N "
                    "continuous-batching replicas scheduled via Mesos "
                    "(or locally).")
    p.add_argument("-R", "--replicas", type=int, default=2,
                   help="number of UNIFIED serving replicas (with "
                        "--role, the unified fallback tier; 0 with "
                        "--role runs pure disaggregated)")
    p.add_argument("--role", type=str, default=None, metavar="SPEC",
                   help="disaggregated role split, e.g. "
                        "'prefill:2,decode:2': dedicated prefill "
                        "replicas export KV pages that dedicated "
                        "decode replicas import, so long prefills "
                        "never stall decode ticks; --replicas N still "
                        "adds N unified fallback replicas "
                        "(docs/SERVING.md, docs/MIGRATION.md)")
    p.add_argument("-m", "--master", type=str, default=None,
                   help="Mesos master (host:port or zk://...); default env "
                        "MESOS_MASTER, else local backend")
    p.add_argument("-n", "--name", type=str, default=None,
                   help="framework name")
    p.add_argument("-Cr", "--replica-cpus", type=float, default=1.0,
                   help="CPUs per replica task")
    p.add_argument("-Gr", "--replica-chips", type=int, default=0,
                   help="TPU chips per replica task")
    p.add_argument("-Mr", "--replica-mem", type=float, default=1024.0,
                   help="MB of memory per replica task")
    p.add_argument("-p", "--gateway-port", type=int, default=8780,
                   help="gateway listen port (0 = OS-assigned)")
    p.add_argument("--gateway-host", type=str, default="0.0.0.0")
    p.add_argument("-G", "--gateways", type=int, default=1,
                   help="number of stateless gateway front doors over "
                        "the one registry/router view (the first on "
                        "--gateway-port, the rest OS-assigned): each "
                        "is an event-loop process thread serving "
                        "thousands of connections, clients discover "
                        "the set via 'tfserve gateways' and fail over "
                        "between them (docs/SERVING.md 'Front-door "
                        "scaling')")
    p.add_argument("--gateway-processes", type=int, default=0,
                   dest="gateway_processes",
                   help="run N gateway OS PROCESSES instead of "
                        "in-process gateway threads: they share "
                        "--gateway-port via SO_REUSEPORT where the "
                        "platform has it, else take per-process ports "
                        "behind the 'gateways' discovery op; 0 = "
                        "in-process (docs/SERVING.md 'Multi-process "
                        "gateways')")
    p.add_argument("--http-port", type=int, default=None,
                   dest="http_port",
                   help="serve an OpenAI-style HTTP/1.1 edge (POST "
                        "/v1/completions, stream: true = SSE) next to "
                        "the wire port; default off (docs/SERVING.md "
                        "'HTTP/SSE edge')")
    p.add_argument("--rows", type=int, default=8,
                   help="concurrent decode rows per replica")
    p.add_argument("--max-len", type=int, default=None,
                   help="per-request cache positions (default: model max)")
    p.add_argument("--max-queue", type=int, default=256,
                   help="ingress queue bound (per class); past it "
                        "requests shed with an explicit Overloaded "
                        "rejection")
    p.add_argument("--models", type=str, default=None, metavar="SPEC",
                   help="model catalog, e.g. 'chat:2,code:1,draft:0' "
                        "(model_id:replicas[:seed]) — the fleet serves "
                        "MANY models on one replica budget: replicas "
                        "declare their model, the router routes by it "
                        "(unlabeled requests ride the FIRST entry), "
                        "and the trader reallocates replicas between "
                        "models on relative queue pressure, scaling "
                        "idle models to zero; a :0 entry starts scaled "
                        "to zero and cold-starts through --warm-pool "
                        "(docs/SERVING.md 'Model catalog')")
    p.add_argument("--gang-size", type=int, default=1,
                   dest="gang_size", metavar="N",
                   help="members per UNIFIED replica: each replica is "
                        "an N-task GANG (one model sharded across a "
                        "pod slice) placed all-or-nothing and routed "
                        "as ONE replica via its leader; a member's "
                        "death tears the gang down and re-forms it "
                        "whole; 1 = classic single-process replicas "
                        "(docs/SERVING.md 'Gang replicas')")
    p.add_argument("--warm-pool", type=int, default=0,
                   dest="warm_pool", metavar="N",
                   help="with --models: N pre-warmed UNDEDICATED "
                        "replicas that adopt a model at assignment "
                        "time — a scaled-to-zero model's first request "
                        "costs a weight install, not a process launch "
                        "plus compile")
    p.add_argument("--model-budget", type=int, default=None,
                   dest="model_budget", metavar="N",
                   help="with --models: the fleet-wide replica budget "
                        "the trader reallocates within (default: the "
                        "catalog's boot counts + --warm-pool)")
    p.add_argument("--classes", type=str, default=None, metavar="SPEC",
                   help="admission priority classes, highest first, "
                        "e.g. 'interactive:8,background:1' "
                        "(name:weight[:queue_bound[:model_quota]] — "
                        "model_quota bounds one model's queued slots "
                        "within the class on a --models fleet): each "
                        "class gets "
                        "its own bounded ingress queue served "
                        "weighted-fair, and outranking requests may "
                        "preempt lower-class rows inside the replicas; "
                        "unlabeled requests ride the FIRST class "
                        "(docs/SERVING.md 'Priorities, preemption & "
                        "migration')")
    p.add_argument("--batch-lane", action="store_true", dest="batch_lane",
                   help="add a deadline-less 'batch' priority class "
                        "BELOW every interactive class: batch rows fill "
                        "idle decode slots and leftover tick budget, "
                        "dispatch only when every interactive queue is "
                        "empty, and yield within one tick to an "
                        "interactive arrival via preemption; submit "
                        "with 'tfserve batch' (docs/SERVING.md "
                        "'Offline lane')")
    p.add_argument("--no-migrate", action="store_false", dest="migrate",
                   default=True,
                   help="disable drain migration: scale-downs and "
                        "rollouts wait for in-flight work instead of "
                        "suspending it and resuming on survivors")
    p.add_argument("--no-breakers", action="store_false",
                   dest="breakers", default=True,
                   help="disable the router's per-replica circuit "
                        "breakers (consecutive-failure and latency-"
                        "outlier tripping with half-open probe "
                        "recovery — the gray-failure containment; "
                        "docs/SERVING.md 'Deadlines & failure "
                        "containment')")
    p.add_argument("--rate", type=float, default=None,
                   help="token-bucket admission rate, requests/s "
                        "(default: unlimited)")
    p.add_argument("--burst", type=float, default=None,
                   help="token-bucket burst size (default: max(1, rate))")
    p.add_argument("--workers", type=int, default=8,
                   help="gateway dispatcher threads")
    p.add_argument("--retries", type=int, default=2,
                   help="max failovers to a different replica per request")
    p.add_argument("--prefix-cache", type=int, default=64,
                   metavar="PAGES", dest="prefix_cache",
                   help="per-replica cross-request prefix cache budget "
                        "in KV pool pages per mesh data shard (0 "
                        "disables); warm shared-system-prompt requests "
                        "prefill only their uncached tail, and the "
                        "gateway routes shared prefixes to the replica "
                        "already holding them (prefix-affinity)")
    p.add_argument("--pipeline-depth", type=int, default=0,
                   choices=(0, 1), dest="pipeline_depth",
                   help="1 pipelines each replica's decode loop with a "
                        "device-resident carry (dispatch block N+1 "
                        "before syncing block N's tokens; token "
                        "streams identical to 0, the synchronous "
                        "default — docs/SERVING.md)")
    p.add_argument("--fused-prefill", action="store_true",
                   dest="fused_prefill",
                   help="stall-free decode ticks: fuse a token-budgeted "
                        "slice of prefill chunk tokens into the SAME "
                        "device dispatch as the decode rows (Sarathi-"
                        "style), so admitting a long prompt no longer "
                        "stalls live streams; token streams identical "
                        "to the phase-split default (docs/SERVING.md "
                        "'Stall-free fused scheduling')")
    p.add_argument("--tokens-per-tick", type=int, default=None,
                   dest="tokens_per_tick", metavar="T",
                   help="with --fused-prefill: the per-tick token "
                        "budget shared by decode rows and fused "
                        "prefill chunks (default: rows + one chunk)")
    p.add_argument("--kv-placement", type=str, default="rendezvous",
                   dest="kv_placement",
                   choices=("rendezvous", "loaded"),
                   help="replicated-park peer placement policy on the "
                        "cross-host KV fabric: 'rendezvous' (pure "
                        "HRW, the default) or 'loaded' (occupancy-"
                        "bucketed HRW that steers parks away from "
                        "full peers; tune via 'tfserve simulate "
                        "sessions --sweep kv_placement=...')")
    p.add_argument("--draft", action="store_true",
                   help="replicas serve with a DRAFT companion model "
                        "(speculative decoding): each tick commits "
                        "1..n_draft+1 tokens instead of exactly 1 — "
                        "the single-stream latency lever — and it "
                        "composes with --prefix-cache, --kv-tier-mb, "
                        "disagg roles, and migration; the fleet-wide "
                        "draft acceptance rate is the 'spec' gauge in "
                        "'tfserve metrics' (docs/SERVING.md "
                        "'Speculative decoding & composition')")
    p.add_argument("--n-draft", type=int, default=4, dest="n_draft",
                   metavar="K",
                   help="draft proposals per speculative round "
                        "(with --draft)")
    p.add_argument("--kv-tier-mb", type=float, default=0.0,
                   dest="kv_tier_mb", metavar="MB",
                   help="per-replica host-RAM KV tier budget in MB (0 "
                        "disables, the default — zero behavior "
                        "change): prefix pages evicted from the device "
                        "pool spill into it and promote back on the "
                        "next hit, and 'tfserve submit --session ID' "
                        "requests park their conversation KV between "
                        "turns, resuming with only the new tail "
                        "prefilled (docs/SERVING.md 'KV tiering & "
                        "sessions')")
    p.add_argument("--kv-tier-dir", type=str, default=None,
                   dest="kv_tier_dir", metavar="DIR",
                   help="disk tier directory shared by the host's "
                        "replicas (bounded at 4x the RAM budget; "
                        "HMAC-framed entries, stale-version entries "
                        "read as misses); default with --kv-tier-mb: "
                        "a per-run temp directory, so co-located "
                        "replicas resume each other's parked sessions")
    p.add_argument("--kv-replication", type=int, default=1,
                   dest="kv_replication", metavar="K",
                   help="K-way replicated session parking on the "
                        "cross-host KV fabric (1 disables, the "
                        "default): a park acknowledges only after the "
                        "artifact lands on the parker PLUS K-1 peers, "
                        "so a parked session survives its parking "
                        "host's death and resumes token-identical "
                        "elsewhere (docs/SERVING.md 'Cross-host KV "
                        "fabric')")
    p.add_argument("--kv-replicas", type=int, default=0,
                   dest="kv_replicas", metavar="N",
                   help="dedicated KV-role replicas (storage-only "
                        "fabric peers that never serve tokens): "
                        "replicated parks land there first, so "
                        "artifacts survive every serving replica of a "
                        "model scaling to zero; needs --kv-tier-mb")
    p.add_argument("--warmup", action="store_true",
                   help="replicas compile every jitted serving entry "
                        "point at boot before taking traffic: they "
                        "register as 'warming' (never routed), warm, "
                        "then flip alive — and any elastic/Mode-B "
                        "relaunch re-warms the same way, so a cold "
                        "replica's first request never pays a compile")
    p.add_argument("--autoscale", action="store_true",
                   help="run the fleet autoscaler: a control loop that "
                        "grows/shrinks each tier from live load "
                        "signals (queue-wait p99 for prompt tiers, KV "
                        "headroom for decode) within --min/--max-"
                        "replicas, launching with --warmup semantics "
                        "and shrinking by drain-then-kill "
                        "(docs/SERVING.md 'Autoscaling')")
    p.add_argument("--min-replicas", type=int, default=None,
                   dest="min_replicas",
                   help="autoscale floor per tier (default 1; a "
                        "routable tier never scales to zero)")
    p.add_argument("--max-replicas", type=int, default=None,
                   dest="max_replicas",
                   help="autoscale ceiling per tier (default: twice "
                        "the initial count)")
    p.add_argument("--weights-version", type=str, default="v0",
                   dest="weights_version",
                   help="weights version label the boot replicas "
                        "advertise; 'tfserve rollout --version NEW' "
                        "later replaces the fleet blue-green with zero "
                        "downtime (docs/SERVING.md 'Blue-green "
                        "rollout')")
    p.add_argument("--tiny", action="store_true",
                   help="serve the tiny CI model (dev/demo)")
    p.add_argument("--metrics-interval", type=float, default=10.0,
                   help="seconds between fleet metrics log lines "
                        "(0 disables)")
    p.add_argument("--metrics-port", type=int, default=None,
                   dest="metrics_port",
                   help="serve Prometheus exposition on this loopback "
                        "port (GET /metrics, stdlib HTTP; "
                        "/metrics.json for the raw snapshot); default: "
                        "no endpoint — the snapshot stays reachable "
                        "through the gateway's authenticated metrics "
                        "op ('tfserve metrics')")
    p.add_argument("--trace-sample", type=float, default=0.05,
                   dest="trace_sample",
                   help="fraction of requests whose trace keeps FULL "
                        "span detail (every request keeps a summary; "
                        "failed/shed/deadline-exceeded/slow requests "
                        "keep detail regardless — tail-based "
                        "retention, docs/SERVING.md 'Observability')")
    p.add_argument("--trace-slow-ms", type=float, default=1000.0,
                   dest="trace_slow_ms",
                   help="requests slower than this keep full span "
                        "detail even when unsampled (the tail rule's "
                        "latency threshold; replicas apply it "
                        "hop-locally too)")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def parse_role_spec(spec: Optional[str]) -> dict:
    """``'prefill:2,decode:2'`` → ``{"prefill": 2, "decode": 2}``.
    Both disaggregated tiers must appear (a lone tier cannot serve the
    prefill→decode handoff); counts must be positive ints."""
    if not spec:
        return {}
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        role, _, num = part.partition(":")
        role = role.strip()
        if role not in ("prefill", "decode"):
            raise ValueError(f"bad --role entry {part!r}; want "
                             f"'prefill:N,decode:M'")
        try:
            n = int(num)
        except ValueError:
            raise ValueError(f"bad --role count in {part!r}") from None
        if n < 1:
            raise ValueError(f"--role count must be >= 1 in {part!r}")
        if role in out:
            raise ValueError(f"duplicate --role entry for {role!r}")
        out[role] = n
    if set(out) != {"prefill", "decode"}:
        raise ValueError("--role needs BOTH tiers, e.g. "
                         "'prefill:2,decode:2'")
    return out


def parse_model_spec(spec: Optional[str]):
    """``'chat:2,code:1:7,draft:0'`` → ModelSpec list
    (``model_id:replicas[:seed]``, seed defaulting to the entry's
    index so two entries are two distinct models).  The FIRST entry is
    the default for model-less requests; ``:0`` entries boot scaled to
    zero (cold-started through the warm pool on first demand)."""
    from tfmesos_tpu.fleet.catalog import ModelSpec

    if not spec:
        return None
    out = []
    for i, part in enumerate(p.strip() for p in spec.split(",")
                             if p.strip()):
        bits = part.split(":")
        if len(bits) not in (2, 3) or not bits[0]:
            raise ValueError(f"bad --models entry {part!r}; want "
                             f"model_id:replicas[:seed]")
        try:
            replicas = int(bits[1])
            seed = int(bits[2]) if len(bits) == 3 else i
        except ValueError:
            raise ValueError(
                f"bad --models numbers in {part!r}") from None
        try:
            out.append(ModelSpec(model_id=bits[0], replicas=replicas,
                                 seed=seed))
        except ValueError as e:
            raise ValueError(f"bad --models entry {part!r}: {e}") \
                from None
    if not out:
        raise ValueError("--models is empty")
    if len({s.model_id for s in out}) != len(out):
        raise ValueError("duplicate model_id in --models")
    return out


def parse_class_spec(spec: Optional[str]):
    """``'interactive:8,background:1'`` → PriorityClass list, listed
    highest-priority FIRST: the first class is the default for
    unlabeled requests and gets the highest preemption rank; each entry
    is ``name:weight[:queue_bound]``."""
    from tfmesos_tpu.fleet.admission import PriorityClass

    if not spec:
        return None
    entries = [part.strip() for part in spec.split(",") if part.strip()]
    out = []
    for i, part in enumerate(entries):
        bits = part.split(":")
        if len(bits) not in (2, 3, 4) or not bits[0]:
            raise ValueError(f"bad --classes entry {part!r}; want "
                             f"name:weight[:queue_bound[:model_quota]]")
        try:
            weight = float(bits[1])
            maxq = int(bits[2]) if len(bits) >= 3 else None
            quota = int(bits[3]) if len(bits) == 4 else None
        except ValueError:
            raise ValueError(f"bad --classes numbers in {part!r}") from None
        try:
            out.append(PriorityClass(name=bits[0], weight=weight,
                                     rank=len(entries) - 1 - i,
                                     max_queue=maxq,
                                     model_quota=quota))
        except ValueError as e:
            raise ValueError(f"bad --classes entry {part!r}: {e}") from None
    if len({c.name for c in out}) != len(out):
        raise ValueError("duplicate class name in --classes")
    return out


def build_submit_parser() -> argparse.ArgumentParser:
    """``tfserve submit`` — send one generation request to a RUNNING
    fleet gateway (smoke/debug surface; real clients use
    ``fleet.client.FleetClient``)."""
    p = argparse.ArgumentParser(
        prog="tfserve submit",
        description="Submit one generation request to a running fleet "
                    "gateway and print the completion.")
    p.add_argument("-g", "--gateway", type=str, required=True,
                   metavar="HOST:PORT", help="the running gateway")
    p.add_argument("--prompt", type=str, required=True,
                   help="comma-separated prompt token ids, e.g. '1,2,3'")
    p.add_argument("-n", "--max-new-tokens", type=int, default=16,
                   dest="max_new_tokens")
    p.add_argument("--stop-token", type=int, default=None,
                   dest="stop_token")
    p.add_argument("--priority", type=str, default=None,
                   help="admission class label (e.g. 'background'); "
                        "unlabeled requests ride the fleet's default "
                        "class")
    p.add_argument("--deadline-ms", type=float, default=None,
                   dest="deadline_ms",
                   help="end-to-end deadline in ms from gateway "
                        "receipt: expired work is shed in the "
                        "admission queue, failed fast by the router, "
                        "and cancelled inside the replicas (an "
                        "explicit deadline_exceeded error, never a "
                        "late answer); default: no deadline — the "
                        "fleet's flat request timeout applies "
                        "(docs/MIGRATION.md)")
    p.add_argument("--trace", action="store_true",
                   help="ask the fleet to keep FULL span detail for "
                        "this request's trace; the printed trace_id "
                        "feeds 'tfserve trace -g GW --id ID' (every "
                        "request gets a summary trace regardless)")
    p.add_argument("--session", type=str, default=None,
                   help="multi-turn session id: on a KV-tiered fleet "
                        "(tfserve --kv-tier-mb) the finished request's "
                        "KV parks under this id, and a later submit "
                        "whose --prompt extends the conversation "
                        "(prior prompt + returned tokens + new turn) "
                        "resumes from it, prefilling only the tail "
                        "(docs/SERVING.md 'KV tiering & sessions')")
    p.add_argument("--model", type=str, default=None,
                   help="catalog model this request targets (tfserve "
                        "--models); absent rides the fleet's DEFAULT "
                        "(first-listed) entry — a scaled-to-zero "
                        "model's request cold-starts it through the "
                        "warm pool (docs/SERVING.md 'Model catalog')")
    p.add_argument("--timeout", type=float, default=300.0)
    return p


def submit_main(argv: List[str]) -> int:
    args = build_submit_parser().parse_args(argv)
    from tfmesos_tpu.fleet.admission import Overloaded
    from tfmesos_tpu.fleet.client import FleetClient, RequestFailed

    token = wire.load_token()
    if not token:
        print(f"tfserve submit: no cluster token — set {wire.TOKEN_ENV} "
              f"or {wire.TOKEN_FILE_ENV} (tfserve printed the token "
              f"file at startup)", file=sys.stderr)
        return 2
    try:
        prompt = [int(t) for t in args.prompt.split(",") if t.strip()]
    except ValueError:
        print(f"tfserve submit: bad --prompt {args.prompt!r}; want "
              f"comma-separated ints", file=sys.stderr)
        return 2
    if not prompt:
        print("tfserve submit: --prompt is empty", file=sys.stderr)
        return 2
    client = None
    try:
        client = FleetClient(args.gateway, token, timeout=args.timeout)
        out = client.generate(prompt, args.max_new_tokens,
                              stop_token=args.stop_token,
                              priority=args.priority,
                              deadline_ms=args.deadline_ms,
                              trace=args.trace or None,
                              session=args.session,
                              model=args.model)
    except Overloaded as e:
        print(f"tfserve submit: shed ({e.kind}): {e} — back off and "
              f"retry", file=sys.stderr)
        return 1
    except RequestFailed as e:
        print(f"tfserve submit: {e.kind}: {e}", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"tfserve submit: cannot reach gateway {args.gateway}: "
              f"{e}", file=sys.stderr)
        return 1
    finally:
        if client is not None:
            client.close()
    print(json.dumps({"tokens": out.get("tokens"),
                      "ttft_ms": out.get("ttft_ms"),
                      "total_ms": out.get("total_ms"),
                      "trace_id": out.get("trace_id")}))
    return 0


def build_batch_parser() -> argparse.ArgumentParser:
    """``tfserve batch`` — submit deadline-less offline work on the
    fleet's ``batch`` class (``tfserve --batch-lane``) and collect the
    completions."""
    p = argparse.ArgumentParser(
        prog="tfserve batch",
        description="Submit one or more deadline-less generation "
                    "requests on the fleet's 'batch' priority class "
                    "and print one JSON line per completion as each "
                    "finishes.  Batch work fills idle capacity and "
                    "yields to interactive traffic, so expect high "
                    "and variable latency — that is the contract.")
    p.add_argument("-g", "--gateway", type=str, required=True,
                   metavar="HOST:PORT", help="the running gateway")
    p.add_argument("--prompt", type=str, action="append", default=[],
                   metavar="IDS",
                   help="comma-separated prompt token ids, e.g. "
                        "'1,2,3'; repeatable — each occurrence is one "
                        "batch request")
    p.add_argument("--file", type=str, default=None,
                   help="read additional prompts from this file, one "
                        "comma-separated prompt per line (blank lines "
                        "and '#' comments skipped)")
    p.add_argument("-n", "--max-new-tokens", type=int, default=16,
                   dest="max_new_tokens")
    p.add_argument("--stop-token", type=int, default=None,
                   dest="stop_token")
    p.add_argument("--model", type=str, default=None,
                   help="catalog model the requests target (tfserve "
                        "--models); absent rides the fleet's default "
                        "entry")
    p.add_argument("--concurrency", type=int, default=4,
                   help="in-flight batch submissions (the lane itself "
                        "yields to interactive work regardless of "
                        "this)")
    p.add_argument("--class", type=str, default="batch", dest="klass",
                   metavar="NAME",
                   help="priority class label to submit under "
                        "(default 'batch' — the --batch-lane class)")
    p.add_argument("--timeout", type=float, default=3600.0,
                   help="per-request client timeout in seconds "
                        "(generous: batch work waits out interactive "
                        "bursts by design)")
    return p


def batch_main(argv: List[str]) -> int:
    args = build_batch_parser().parse_args(argv)
    from concurrent.futures import ThreadPoolExecutor

    from tfmesos_tpu.fleet.admission import Overloaded
    from tfmesos_tpu.fleet.client import FleetClient, RequestFailed

    token = wire.load_token()
    if not token:
        print(f"tfserve batch: no cluster token — set {wire.TOKEN_ENV} "
              f"or {wire.TOKEN_FILE_ENV} (tfserve printed the token "
              f"file at startup)", file=sys.stderr)
        return 2
    specs = list(args.prompt)
    if args.file:
        try:
            with open(args.file) as f:
                for line in f:
                    line = line.strip()
                    if line and not line.startswith("#"):
                        specs.append(line)
        except OSError as e:
            print(f"tfserve batch: cannot read --file {args.file!r}: "
                  f"{e}", file=sys.stderr)
            return 2
    prompts = []
    for spec in specs:
        try:
            prompt = [int(t) for t in spec.split(",") if t.strip()]
        except ValueError:
            print(f"tfserve batch: bad prompt {spec!r}; want "
                  f"comma-separated ints", file=sys.stderr)
            return 2
        if not prompt:
            print(f"tfserve batch: empty prompt {spec!r}",
                  file=sys.stderr)
            return 2
        prompts.append(prompt)
    if not prompts:
        print("tfserve batch: no prompts (--prompt and/or --file)",
              file=sys.stderr)
        return 2
    if args.concurrency < 1:
        print("tfserve batch: --concurrency must be >= 1",
              file=sys.stderr)
        return 2

    # One shared client (thread-safe over the multiplexed connection);
    # batch requests carry NO deadline — deadline-less is the class
    # contract, the work waits out interactive bursts instead of
    # being shed.
    plock = threading.Lock()
    failures = [0]

    def one(item):
        idx, prompt = item
        try:
            out = client.generate(prompt, args.max_new_tokens,
                                  stop_token=args.stop_token,
                                  priority=args.klass,
                                  model=args.model)
            row = {"index": idx, "tokens": out.get("tokens"),
                   "total_ms": out.get("total_ms")}
        except (Overloaded, RequestFailed, OSError) as e:
            failures[0] += 1
            row = {"index": idx, "error": str(e),
                   "kind": getattr(e, "kind", "io")}
        with plock:
            print(json.dumps(row), flush=True)

    client = None
    try:
        client = FleetClient(args.gateway, token, timeout=args.timeout)
        with ThreadPoolExecutor(max_workers=args.concurrency) as ex:
            list(ex.map(one, enumerate(prompts)))
    except OSError as e:
        print(f"tfserve batch: cannot reach gateway {args.gateway}: "
              f"{e}", file=sys.stderr)
        return 1
    finally:
        if client is not None:
            client.close()
    return 1 if failures[0] else 0


def build_trace_parser() -> argparse.ArgumentParser:
    """``tfserve trace`` — fetch request traces from a RUNNING fleet
    gateway and print human-readable waterfalls (docs/SERVING.md
    "Observability")."""
    p = argparse.ArgumentParser(
        prog="tfserve trace",
        description="Fetch request traces from a running fleet "
                    "gateway: one waterfall by id, the N slowest, the "
                    "newest failures, or the recent summaries.")
    p.add_argument("-g", "--gateway", type=str, required=True,
                   metavar="HOST:PORT", help="the running gateway")
    group = p.add_mutually_exclusive_group()
    group.add_argument("--id", type=str, default=None, dest="trace_id",
                       help="one trace by id (as printed on every "
                            "completion/error reply)")
    group.add_argument("--slowest", type=int, default=None, metavar="N",
                       help="the N slowest known traces")
    group.add_argument("--failed", action="store_true",
                       help="the newest failed/shed/deadline-exceeded "
                            "traces")
    p.add_argument("--limit", type=int, default=20,
                   help="max records for the summary/failed listings")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="print the raw trace records as one JSON array "
                        "instead of waterfalls — the machine-readable "
                        "export `tfserve simulate --replay` consumes "
                        "(docs/SIMULATOR.md), and offline-analysis "
                        "input generally")
    p.add_argument("--timeout", type=float, default=10.0)
    return p


def trace_main(argv: List[str]) -> int:
    args = build_trace_parser().parse_args(argv)
    from tfmesos_tpu.fleet.client import FleetClient
    from tfmesos_tpu.fleet.tracing import format_waterfall

    token = wire.load_token()
    if not token:
        print(f"tfserve trace: no cluster token — set {wire.TOKEN_ENV} "
              f"or {wire.TOKEN_FILE_ENV} (tfserve printed the token "
              f"file at startup)", file=sys.stderr)
        return 2
    client = None
    try:
        client = FleetClient(args.gateway, token, timeout=args.timeout)
        traces = client.trace(trace_id=args.trace_id,
                              slowest=args.slowest, failed=args.failed,
                              limit=args.limit, timeout=args.timeout)
    except OSError as e:
        print(f"tfserve trace: cannot reach gateway {args.gateway}: "
              f"{e}", file=sys.stderr)
        return 1
    finally:
        if client is not None:
            client.close()
    if args.as_json:
        # Machine-readable export, empty result included (an empty
        # book is a valid export, not an error for a pipeline).
        print(json.dumps(traces), flush=True)
        return 0
    if not traces:
        what = (f"trace {args.trace_id!r}" if args.trace_id
                else "matching traces")
        print(f"tfserve trace: no {what} in the gateway's book (the "
              f"book is bounded — detail is retained for sampled, "
              f"failed, and slow requests)", file=sys.stderr)
        return 1
    if args.trace_id or args.slowest or args.failed:
        for rec in traces:
            print(format_waterfall(rec), flush=True)
            print()
    else:
        for rec in traces:     # summary listing: one line each
            summ = rec.get("summary") or {}
            extra = " ".join(f"{k}={v}" for k, v in sorted(summ.items()))
            print(f"{rec.get('trace_id')}  {rec.get('status'):<20} "
                  f"{rec.get('total_ms', 0):>10.1f}ms  "
                  f"{'detail' if rec.get('detailed') else 'summary':<7} "
                  f"{extra}", flush=True)
    return 0


def build_simulate_parser() -> argparse.ArgumentParser:
    """``tfserve simulate`` — run a named fleet-simulator scenario
    (docs/SIMULATOR.md): the real control plane on a virtual clock
    against simulated replicas, with optional policy-constant
    sweeps."""
    from tfmesos_tpu.fleet.sim import SCENARIOS

    p = argparse.ArgumentParser(
        prog="tfserve simulate",
        description="Run a fleet-simulator scenario: the REAL "
                    "admission/router/containment/autoscaler code on a "
                    "virtual clock against simulated replicas — "
                    "1000-replica fleets and millions of requests in "
                    "seconds of CPU (docs/SIMULATOR.md).")
    p.add_argument("scenario", choices=sorted(SCENARIOS),
                   help="named scenario to run")
    p.add_argument("--replicas", type=int, default=None,
                   help="override the scenario's replica count")
    p.add_argument("--requests", type=int, default=None,
                   help="override the scenario's request count")
    p.add_argument("--seed", type=int, default=None,
                   help="workload/chaos seed (scenarios are "
                        "deterministic per seed)")
    p.add_argument("--set", action="append", default=[], dest="sets",
                   metavar="PATH=VALUE",
                   help="fix one policy constant by path (e.g. "
                        "breaker.latency_factor=8, "
                        "autoscaler.queue_wait_hi_ms=200, "
                        "admission.max_queue=256); repeatable")
    p.add_argument("--sweep", type=str, default=None,
                   metavar="PATH=V1,V2,...",
                   help="run the scenario once per value of one "
                        "policy constant and print a comparison table "
                        "(e.g. breaker.latency_factor=2,4,8)")
    p.add_argument("--replay", type=str, default=None, metavar="FILE",
                   help="replay a recorded `tfserve trace -g GW "
                        "--json` export as the workload; per-hop "
                        "timings seed the replica latency model")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="print raw result dict(s) as JSON")
    return p


_SIM_COLUMNS = (
    ("requests", "requests"), ("completed", "completed"),
    ("lost", "lost"), ("retry_amplification", "amplif"),
    ("queue_wait_p99_ms", "qwait_p99"),
    ("sim_events_per_sec", "events/s"), ("sim_seconds", "sim_s"),
)


def _sim_summary_lines(res: dict) -> List[str]:
    lines = ["  " + "  ".join(f"{label}={res.get(key)}"
                              for key, label in _SIM_COLUMNS)]
    for cls, d in sorted((res.get("classes") or {}).items()):
        lines.append(f"  class {cls:<14s} count={d.get('count'):>8} "
                     f"p50={d.get('p50_ms')}ms p90={d.get('p90_ms')}ms "
                     f"p99={d.get('p99_ms')}ms")
    shed = res.get("shed") or {}
    if any(any(v) for v in shed.values()):
        lines.append("  shed (queue, rate, deadline) per class: "
                     + " ".join(f"{k}={v}" for k, v in sorted(shed.items())))
    traj = res.get("autoscaler_trajectory")
    if traj:
        lines.append(f"  autoscaler: {len(traj)} ticks, last={traj[-1]}")
    for k in ("victim", "victim_isolated", "victim_alive_while_isolated",
              "victim_trip_reason", "healed", "probes_conformant",
              "migration_reruns"):
        if k in res:
            lines.append(f"  {k}={res[k]}")
    return lines


def simulate_main(argv: List[str]) -> int:
    args = build_simulate_parser().parse_args(argv)
    from tfmesos_tpu.fleet.sim import parse_sweep, run_scenario, run_sweep
    from tfmesos_tpu.fleet.workload import (fit_replica_model,
                                            load_trace_export,
                                            replay_from_traces)

    overrides = []
    for spec in args.sets:
        if "=" not in spec:
            print(f"tfserve simulate: --set needs PATH=VALUE, got "
                  f"{spec!r}", file=sys.stderr)
            return 2
        path, _, value = spec.partition("=")
        overrides.append((path.strip(), value))
    kwargs: Dict[str, object] = {}
    if args.replicas is not None:
        kwargs["replicas"] = args.replicas
    if args.requests is not None:
        kwargs["n_requests"] = args.requests
    if args.seed is not None:
        kwargs["seed"] = args.seed
    if args.replay:
        try:
            records = load_trace_export(args.replay)
        except (OSError, ValueError) as e:
            print(f"tfserve simulate: cannot load trace export "
                  f"{args.replay}: {e}", file=sys.stderr)
            return 2
        workload = replay_from_traces(records)
        if not workload:
            print(f"tfserve simulate: {args.replay} holds no replayable "
                  f"trace records", file=sys.stderr)
            return 2
        kwargs["workload"] = workload
        kwargs["n_requests"] = len(workload)
        kwargs["model_fit"] = fit_replica_model(records)
    try:
        if args.sweep:
            path, values = parse_sweep(args.sweep)
            rows = run_sweep(args.scenario, path, values,
                             overrides=overrides, **kwargs)
            if args.as_json:
                print(json.dumps({v: r for v, r in rows}))
                return 0
            print(f"sweep {path} over {args.scenario}:")
            for value, res in rows:
                print(f"{path}={value}")
                for line in _sim_summary_lines(res):
                    print(line)
            return 0
        res = run_scenario(args.scenario, overrides=overrides, **kwargs)
    except ValueError as e:
        print(f"tfserve simulate: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(res))
        return 0
    print(f"scenario {args.scenario} (wall {res.get('wall_s')}s):")
    for line in _sim_summary_lines(res):
        print(line)
    return 0


def build_gateways_parser() -> argparse.ArgumentParser:
    """``tfserve gateways`` — list a fleet's registered front doors
    (client-side discovery for multi-gateway failover)."""
    p = argparse.ArgumentParser(
        prog="tfserve gateways",
        description="List the fleet's registered gateway addresses "
                    "(the `gateways` discovery op ANY gateway serves).")
    p.add_argument("-g", "--gateway", type=str, required=True,
                   metavar="HOST:PORT",
                   help="any running gateway of the fleet")
    p.add_argument("--timeout", type=float, default=10.0)
    return p


def gateways_main(argv: List[str]) -> int:
    args = build_gateways_parser().parse_args(argv)
    from tfmesos_tpu.fleet.client import FleetClient

    token = wire.load_token()
    if not token:
        print(f"tfserve gateways: no cluster token — set "
              f"{wire.TOKEN_ENV} or {wire.TOKEN_FILE_ENV} (tfserve "
              f"printed the token file at startup)", file=sys.stderr)
        return 2
    try:
        client = FleetClient(args.gateway, token, timeout=args.timeout)
        try:
            addrs = client.gateways(timeout=args.timeout)
        finally:
            client.close()
    except OSError as e:
        print(f"tfserve gateways: cannot reach gateway "
              f"{args.gateway}: {e}", file=sys.stderr)
        return 1
    if not addrs:
        print("tfserve gateways: none registered (single-gateway "
              "fleet predating discovery, or the registry restarted)")
        return 0
    for addr in addrs:
        print(addr)
    return 0


def build_metrics_parser() -> argparse.ArgumentParser:
    """``tfserve metrics`` — fetch the gateway snapshot and
    pretty-print it (until now the JSON snapshot was only reachable
    from bench code)."""
    p = argparse.ArgumentParser(
        prog="tfserve metrics",
        description="Fetch a running fleet gateway's metrics snapshot "
                    "and print counters/gauges/histograms as tables.")
    p.add_argument("-g", "--gateway", type=str, required=True,
                   metavar="HOST:PORT", help="the running gateway")
    p.add_argument("--json", action="store_true",
                   help="print the raw JSON snapshot instead of tables")
    p.add_argument("--timeout", type=float, default=10.0)
    return p


def metrics_main(argv: List[str]) -> int:
    args = build_metrics_parser().parse_args(argv)
    from tfmesos_tpu.fleet.client import FleetClient

    token = wire.load_token()
    if not token:
        print(f"tfserve metrics: no cluster token — set "
              f"{wire.TOKEN_ENV} or {wire.TOKEN_FILE_ENV} (tfserve "
              f"printed the token file at startup)", file=sys.stderr)
        return 2
    client = None
    try:
        client = FleetClient(args.gateway, token, timeout=args.timeout)
        snap = client.metrics(timeout=args.timeout)
    except OSError as e:
        print(f"tfserve metrics: cannot reach gateway {args.gateway}: "
              f"{e}", file=sys.stderr)
        return 1
    finally:
        if client is not None:
            client.close()
    if args.json:
        print(json.dumps(snap, indent=2, sort_keys=True))
        return 0
    counters = snap.get("counters") or {}
    gauges = snap.get("gauges") or {}
    hists = snap.get("histograms") or {}
    if counters:
        print("counters:")
        width = max(len(k) for k in counters)
        for name in sorted(counters):
            print(f"  {name:<{width}}  {counters[name]}")
    if gauges:
        print("gauges:")
        width = max(len(k) for k in gauges)
        for name in sorted(gauges):
            print(f"  {name:<{width}}  {gauges[name]}")
    if hists:
        print("histograms:")
        width = max(len(k) for k in hists)
        cols = ("count", "mean", "p50", "p90", "p99", "max")
        head = "".join(f"{c:>10}" for c in cols)
        print(f"  {'':<{width}}{head}")
        for name in sorted(hists):
            h = hists[name]
            row = "".join(f"{h.get(c, ''):>10}" for c in cols)
            print(f"  {name:<{width}}{row}")
    if not (counters or gauges or hists):
        print("tfserve metrics: empty snapshot")
    return 0


def build_swap_adapter_parser() -> argparse.ArgumentParser:
    """``tfserve swap-adapter`` — hot-swap a LoRA-style weight delta
    onto every replica of one catalog model with zero downtime
    (docs/SERVING.md 'Model catalog')."""
    p = argparse.ArgumentParser(
        prog="tfserve swap-adapter",
        description="Fold a weight delta (an .npz of param-path -> "
                    "array entries) into one catalog model's replicas "
                    "between generations: in-flight requests finish on "
                    "the old delta, streams stay token-identical per "
                    "delta version, zero downtime.")
    p.add_argument("-g", "--gateway", type=str, required=True,
                   metavar="HOST:PORT", help="the running gateway")
    p.add_argument("--model", type=str, required=True,
                   help="the catalog model_id to swap")
    p.add_argument("--version", type=str, required=True,
                   dest="adapter_version",
                   help="label of the resulting adapter state (same "
                        "charset as model ids)")
    p.add_argument("--npz", type=str, required=True,
                   help=".npz file whose entries map param paths "
                        "(e.g. 'layers/wq') to delta arrays added "
                        "onto the matching leaves")
    p.add_argument("--timeout", type=float, default=900.0,
                   help="seconds to wait (the swap waits for every "
                        "replica's in-flight generations)")
    return p


def swap_adapter_main(argv: List[str]) -> int:
    args = build_swap_adapter_parser().parse_args(argv)
    from tfmesos_tpu.fleet.client import FleetClient, RequestFailed

    token = wire.load_token()
    if not token:
        print(f"tfserve swap-adapter: no cluster token — set "
              f"{wire.TOKEN_ENV} or {wire.TOKEN_FILE_ENV} (tfserve "
              f"printed the token file at startup)", file=sys.stderr)
        return 2
    try:
        import numpy as np

        with np.load(args.npz) as z:
            delta = {k: z[k] for k in z.files}
    except (OSError, ValueError) as e:
        print(f"tfserve swap-adapter: cannot load {args.npz}: {e}",
              file=sys.stderr)
        return 2
    if not delta:
        print(f"tfserve swap-adapter: {args.npz} holds no arrays",
              file=sys.stderr)
        return 2
    client = None
    try:
        client = FleetClient(args.gateway, token, timeout=args.timeout)
        out = client.swap_adapter(args.model, args.adapter_version,
                                  delta, timeout=args.timeout)
    except RequestFailed as e:
        print(f"tfserve swap-adapter: {e.kind}: {e}", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"tfserve swap-adapter: cannot reach gateway "
              f"{args.gateway}: {e}", file=sys.stderr)
        return 1
    finally:
        if client is not None:
            client.close()
    print(f"tfserve swap-adapter: model {out.get('model_id')} now "
          f"serves adapter {out.get('adapter_version')} on "
          f"{out.get('replicas')} replica(s)", flush=True)
    return 0


def build_rollout_parser() -> argparse.ArgumentParser:
    """``tfserve rollout`` — drive a blue-green weight rollout on a
    RUNNING fleet through the gateway's authenticated control op."""
    p = argparse.ArgumentParser(
        prog="tfserve rollout",
        description="Shift a running fleet to a new weights version "
                    "with zero downtime: launch a new-version replica "
                    "set, warm it, shift routing, drain and reap the "
                    "old tier (docs/SERVING.md 'Blue-green rollout').")
    p.add_argument("-g", "--gateway", type=str, required=True,
                   metavar="HOST:PORT", help="the running gateway")
    p.add_argument("--version", type=str, required=True,
                   dest="weights_version",
                   help="the new weights version label")
    p.add_argument("--timeout", type=float, default=900.0,
                   help="seconds to wait for completion (a rollout "
                        "spans a full tier warmup plus the old tier's "
                        "drain)")
    return p


def rollout_main(argv: List[str]) -> int:
    args = build_rollout_parser().parse_args(argv)
    from tfmesos_tpu.fleet.client import (CallTimeout, FleetClient,
                                          RequestFailed)

    token = wire.load_token()
    if not token:
        print(f"tfserve rollout: no cluster token — set "
              f"{wire.TOKEN_ENV} or {wire.TOKEN_FILE_ENV} (tfserve "
              f"printed the token file at startup)", file=sys.stderr)
        return 2
    client = None
    try:
        # Inside the try: FleetClient dials the gateway in its
        # constructor, so an unreachable host must land in the OSError
        # branch below, not escape as a traceback.
        client = FleetClient(args.gateway, token, timeout=args.timeout)
        out = client.rollout(args.weights_version, timeout=args.timeout)
    except RequestFailed as e:
        print(f"tfserve rollout: {e.kind}: {e}", file=sys.stderr)
        return 1
    except CallTimeout as e:
        # Before the generic OSError branch (CallTimeout IS an OSError
        # subclass): no reply within --timeout means the rollout may
        # STILL BE RUNNING server-side, not that the gateway is down.
        print(f"tfserve rollout: no reply within {args.timeout:.0f}s — "
              f"the rollout may still be in progress; watch the "
              f"gateway's roles gauge (versions) and raise --timeout "
              f"({e})", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"tfserve rollout: cannot reach gateway "
              f"{args.gateway}: {e}", file=sys.stderr)
        return 1
    finally:
        if client is not None:
            client.close()
    print(f"tfserve rollout: fleet now serves weights_version "
          f"{out.get('new_version')} (was {out.get('old_version')}; "
          f"{out.get('replicas')} replica(s) launched, "
          f"{out.get('reaped')} reaped, generation fence "
          f"{out.get('generation')})", flush=True)
    return 0


def _build_fleet(args, models, roles, classes, token):
    """Construct the FleetServer from parsed ``tfserve`` args; its
    constructor ValueErrors (bad flag combinations) surface to the
    caller for the clean exit-2 path."""
    from tfmesos_tpu.fleet.launcher import FleetServer

    return FleetServer(
        replicas=args.replicas, rows=args.rows, tiny=args.tiny,
        prefill_replicas=roles.get("prefill", 0),
        decode_replicas=roles.get("decode", 0),
        models=models, gang_size=args.gang_size,
        warm_pool=args.warm_pool,
        model_budget=args.model_budget,
        weights_version=args.weights_version,
        autoscale=args.autoscale,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        max_len=args.max_len, master=args.master,
        replica_cpus=args.replica_cpus, replica_mem=args.replica_mem,
        replica_chips=args.replica_chips,
        gateway_host=args.gateway_host, gateway_port=args.gateway_port,
        gateways=args.gateways,
        gateway_processes=args.gateway_processes,
        http_port=args.http_port,
        workers=args.workers, max_queue=args.max_queue, rate=args.rate,
        burst=args.burst, max_retries=args.retries,
        priority_classes=classes, migrate_on_drain=args.migrate,
        breakers=args.breakers,
        prefix_cache_pages=args.prefix_cache,
        pipeline_depth=args.pipeline_depth,
        fused_prefill=args.fused_prefill,
        tokens_per_tick=args.tokens_per_tick,
        batch_lane=args.batch_lane,
        draft=args.draft, n_draft=args.n_draft,
        kv_tier_mb=args.kv_tier_mb, kv_tier_dir=args.kv_tier_dir,
        kv_replication=args.kv_replication,
        kv_replicas=args.kv_replicas,
        kv_placement=args.kv_placement,
        warmup=args.warmup,
        report_interval=args.metrics_interval or None,
        metrics_port=args.metrics_port,
        trace_sample=args.trace_sample,
        trace_slow_ms=args.trace_slow_ms,
        quiet=not args.verbose, token=token)


def serve_main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "rollout":
        return rollout_main(argv[1:])
    if argv and argv[0] == "swap-adapter":
        return swap_adapter_main(argv[1:])
    if argv and argv[0] == "submit":
        return submit_main(argv[1:])
    if argv and argv[0] == "batch":
        return batch_main(argv[1:])
    if argv and argv[0] == "trace":
        return trace_main(argv[1:])
    if argv and argv[0] == "metrics":
        return metrics_main(argv[1:])
    if argv and argv[0] == "gateways":
        return gateways_main(argv[1:])
    if argv and argv[0] == "simulate":
        return simulate_main(argv[1:])
    args = build_serve_parser().parse_args(argv)
    try:
        roles = parse_role_spec(args.role)
        classes = parse_class_spec(args.classes)
        models = parse_model_spec(args.models)
    except ValueError as e:
        print(f"tfserve: {e}", file=sys.stderr)
        return 2
    if models and roles:
        print("tfserve: --models runs unified tiers; drop --role",
              file=sys.stderr)
        return 2
    min_replicas = 0 if (roles or models) else 1
    if args.replicas < min_replicas:
        print(f"tfserve: --replicas must be >= {min_replicas}, got "
              f"{args.replicas}", file=sys.stderr)
        return 2
    if args.rows < 1:
        print(f"tfserve: --rows must be >= 1, got {args.rows}",
              file=sys.stderr)
        return 2
    if args.gateways < 1:
        print(f"tfserve: --gateways must be >= 1, got {args.gateways}",
              file=sys.stderr)
        return 2
    if args.gateway_processes < 0:
        print(f"tfserve: --gateway-processes must be >= 0, got "
              f"{args.gateway_processes}", file=sys.stderr)
        return 2

    from tfmesos_tpu.scheduler import ClusterError

    # Clients must present the cluster token: honor an operator-supplied
    # one (the standard TPUMESOS_TOKEN / TPUMESOS_TOKEN_FILE contract);
    # otherwise mint one and leave it in a mode-0600 file the operator
    # can point clients at.
    token = wire.load_token() or None
    try:
        fleet = _build_fleet(args, models, roles, classes, token)
    except ValueError as e:
        # Constructor validation (bad flag combinations: --warm-pool
        # without --models, a budget below the boot footprint, ...) is
        # an ARGUMENT error: one clean line, exit 2, never a traceback.
        print(f"tfserve: {e}", file=sys.stderr)
        return 2
    try:
        fleet.start()
    except (ClusterError, ValueError, RuntimeError) as e:
        print(f"tfserve: fleet bring-up failed: {e}", file=sys.stderr)
        return 1
    token_file = None
    if token is None:
        import tempfile

        fd, token_file = tempfile.mkstemp(prefix="tfserve-token-")
        with os.fdopen(fd, "w") as f:   # mkstemp creates mode 0600
            f.write(fleet.token)
        print(f"tfserve: client token file {token_file} (clients set "
              f"{wire.TOKEN_FILE_ENV}={token_file})", flush=True)
    tiers = f"{args.replicas} unified replica(s)"
    if models:
        tiers = (f"{len(models)} catalog model(s) on a "
                 f"{fleet.replica_budget}-replica budget"
                 + (f" + {args.warm_pool} warm-pool"
                    if args.warm_pool else ""))
    if roles:
        tiers += (f" + {roles['prefill']} prefill / {roles['decode']} "
                  f"decode (disaggregated)")
    if args.autoscale:
        tiers += (f", autoscaling within [{fleet.min_replicas}, "
                  f"{fleet.max_replicas}]")
    if args.gateway_processes:
        doors = (f"{args.gateway_processes} gateway process(es) "
                 f"({', '.join(fleet.addrs)})")
    elif args.gateways == 1:
        doors = fleet.addr
    else:
        doors = f"{args.gateways} gateways ({', '.join(fleet.addrs)})"
    if fleet.http_addr:
        doors += f" + http {fleet.http_addr}"
    print(f"tfserve: gateway on {doors} fronting {tiers}; "
          f"ctrl-c to stop", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("tfserve: shutting down", file=sys.stderr)
    finally:
        fleet.stop()
        if token_file is not None:
            try:
                os.unlink(token_file)
            except OSError:
                pass
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    cmd_parts = list(args.cmd)
    if cmd_parts and cmd_parts[0] == "--":
        cmd_parts = cmd_parts[1:]
    if not cmd_parts:
        print("tfrun: no command given", file=sys.stderr)
        return 2
    cmd = " ".join(cmd_parts)  # joined into one shell string (tfrun:36-37)

    try:
        mesh_axes = parse_mesh(args.mesh)
        volumes = parse_volumes(args.volume)
        forward_map(args.worker_logs, args.nworker, "validate:0")
    except ValueError as e:
        print(f"tfrun: {e}", file=sys.stderr)
        return 2

    extra_config = {}
    if args.extra_config:
        try:
            with open(args.extra_config) as f:
                extra_config = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"tfrun: cannot read extra config "
                  f"{args.extra_config!r}: {e}", file=sys.stderr)
            return 2

    jobs = []
    if args.nserver > 0:
        jobs.append(Job(name="ps", num=args.nserver, cpus=args.server_cpus,
                        mem=args.server_mem, chips=args.server_chips, cmd=cmd))
    jobs.append(Job(name="worker", num=args.nworker, cpus=args.worker_cpus,
                    mem=args.worker_mem, chips=args.worker_chips, cmd=cmd))

    collector = LogCollector()
    forward = forward_map(args.worker_logs, args.nworker, collector.addr)

    from tfmesos_tpu.scheduler import ClusterError

    def attempt(i):
        # Retry messaging is the supervisor's job; no duplicate banner here.
        with cluster(jobs, master=args.master, name=args.name,
                     quiet=not args.verbose,
                     containerizer_type=args.containerizer_type,
                     force_pull_image=args.force_pull_image,
                     volumes=volumes,
                     forward_addresses=forward,
                     extra_config=extra_config, role=args.role,
                     gang_scheduling=args.gang,
                     restart_policy=args.restart_policy,
                     max_cluster_restarts=args.max_cluster_restarts,
                     restart_window=args.restart_window,
                     mesh_axes=mesh_axes) as c:
            while not c.finished():
                collector.pump(timeout=0.1)
            # final drain so lines racing the finish still land
            deadline = time.monotonic() + 1.0
            while time.monotonic() < deadline:
                collector.pump(timeout=0.1)

    try:
        if args.restarts > 0:
            from tfmesos_tpu.train.supervisor import supervise
            supervise(attempt, max_restarts=args.restarts, restart_wait=2.0)
        else:
            attempt(0)
    except ClusterError as e:
        # Fail-fast is policy (reference scheduler.py:394-401); the CLI
        # surfaces it as one line, not a stack trace.
        print(f"tfrun: cluster failed: {e}", file=sys.stderr)
        return 1
    except (ValueError, RuntimeError) as e:
        # Backend/config rejection (bad master URL, subscribe timeout, ...).
        print(f"tfrun: {e}", file=sys.stderr)
        return 2
    finally:
        collector.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
