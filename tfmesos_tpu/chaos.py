"""Deterministic fault injection for the control plane.

The reference framework was only ever tested against a live Mesos cluster
(SURVEY §4) and its failure story was "abort everything"; our elastic
recovery (scheduler ``restart_policy="elastic"``), checkpoint-coordinated
resume (train/supervisor.py) and fleet liveness grading (fleet/registry.py)
all make promises that cannot be trusted without a way to *cause* the
failures on demand, repeatably.  This module is that way: a seeded
:class:`FaultPlan` — an explicit list of :class:`Fault` specs — consulted
from small hooks threaded through the control plane:

* ``scheduler._dispatch``      counts SPMD dispatches (site
  ``"scheduler.dispatch"``);
* ``backends/local.py``        registers every launched task's pid with the
  plan (so ``kill_task`` faults can SIGKILL by ``job:index`` name), counts
  launches (site ``"backend.launch"``), and executes ``drop_agent``;
* ``wire.py``                  consults installed hooks on every framed
  send/recv (sites ``"wire.send"`` / ``"wire.recv"``) so a plan can sever,
  delay, truncate, or drop frames on a live connection;
* ``fleet/registry.py``        consults the plan per heartbeat (site
  ``"registry.heartbeat"``) so beats can be dropped without touching the
  replica.

Everything a plan does is decided by **counters** (the Nth event at a
site, optionally filtered by a target substring) or **fixed timers**, plus
a seeded ``random.Random`` for any jittered choices — the same plan against
the same workload injects the same faults, which is what lets
``tests/test_chaos.py`` assert exact recovery behavior (same final loss as
an uninterrupted run) instead of "it probably survived".
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from tfmesos_tpu.utils.logging import get_logger

__all__ = ["Fault", "FaultPlan"]

log = get_logger("tfmesos_tpu.chaos")

#: Actions a fault can take when its trigger fires.  ``kill_task`` /
#: ``drop_agent`` execute from ANY site (the trigger is just a counter);
#: ``sever`` / ``delay`` / ``truncate`` / ``drop`` are interpreted by the
#: hook site that observed the event (wire or registry).  ``slow_task``
#: is the GRAY-FAILURE generator: from its ``nth`` matching event ON it
#: stays live forever (``count`` is ignored — a slow task stays slow)
#: and injects a ``delay_s`` sleep into every matching event, e.g. every
#: ``wire.send`` toward one replica's addr — the process is alive, its
#: heartbeats are on time, and every dispatch is deterministically slow;
#: exactly the failure a circuit breaker (not a liveness registry) must
#: catch.  ``partition`` is the FABRIC-SPLIT generator: persistent like
#: ``slow_task``, it silently drops every frame between one specific
#: peer PAIR (``target="addrA|addrB"`` — both advertised ``host:port``
#: endpoints, either order) while leaving all other traffic — registry
#: heartbeats included — untouched, so both peers stay registry-alive
#: through the split.  It matches only sockets the sender TAGGED with
#: its own advertised addr (``wire.tag_socket`` — replica-to-replica
#: fabric RPC and direct KV pushes do), because an untagged socket
#: cannot prove which pair it belongs to.
ACTIONS = ("kill_task", "drop_agent", "sever", "delay", "truncate",
           "drop", "slow_task", "partition")


@dataclass
class Fault:
    """One planned fault.

    ``site``   — the counter that triggers it ("scheduler.dispatch",
    "backend.launch", "wire.send", "wire.recv", "registry.heartbeat", or
    "time" for a fixed-delay timer armed at install).
    ``nth``    — fires on the nth matching event (1-based); with
    ``count`` > 1 it stays live for that many consecutive matching events
    (e.g. drop 5 heartbeats in a row).  ``slow_task`` ignores ``count``:
    once armed at its nth event it delays EVERY later matching event
    (``fired`` records only the arming, so a long soak cannot bloat it).
    Each fault keeps its OWN counter
    of matching events, cumulative across every key its target matches.
    ``target`` — optional substring filter against the event's key (a task
    name ``job:index`` for launches, ``host:port`` peers for wire events,
    the replica addr for heartbeats); when set, only matching events
    advance the fault's counter.  A ``partition`` fault's target is the
    peer PAIR ``"addrA|addrB"`` (advertised endpoints, either order):
    only frames between those two tagged endpoints match.
    ``victim`` — for ``kill_task``: the ``job:index`` task to SIGKILL
    (defaults to ``target``).
    ``delay_s`` — sleep length for ``delay`` actions and the timer delay
    for ``site="time"``; ``None`` draws once from the plan's seeded RNG.
    """

    action: str
    site: str
    nth: int = 1
    count: int = 1
    target: Optional[str] = None
    victim: Optional[str] = None
    delay_s: Optional[float] = 0.05

    def __post_init__(self) -> None:
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}; "
                             f"want one of {ACTIONS}")
        if self.nth < 1 or self.count < 1:
            raise ValueError("nth and count are 1-based positives")


class FaultPlan:
    """A seeded, deterministic schedule of faults plus the wiring to
    execute them.  Thread-safe: hooks fire from backend/offer/dispatch
    threads concurrently.

    Pass the plan to the components under test
    (``LocalBackend(chaos=plan)``, ``TPUMesosScheduler(chaos=plan)``,
    ``ReplicaRegistry(chaos=plan)``) and ``install()`` it to arm the
    global wire hooks and any ``site="time"`` timers::

        plan = FaultPlan([Fault("kill_task", "scheduler.dispatch",
                                nth=4, victim="worker:1")], seed=7)
        with plan.installed():
            ...   # run the workload; the 4th dispatch SIGKILLs worker:1

    ``plan.fired`` records every executed fault as ``(site, key, action,
    n)`` tuples, so tests assert exactly what was injected.
    """

    def __init__(self, faults: List[Fault], seed: int = 0):
        self.faults = list(faults)
        self.rng = random.Random(seed)
        self.fired: List[Tuple[str, str, str, int]] = []
        self._lock = threading.RLock()
        self._counts: Dict[Any, int] = {}      # per-site event counters
        self._fault_hits: Dict[int, int] = {}  # per-fault MATCHED counters
        self._pids: Dict[str, int] = {}        # "job:index" -> pid
        self._backend = None                   # bound LocalBackend (or alike)
        self._timers: List[threading.Timer] = []
        self._installed = False
        # Resolve RNG-drawn delays ONCE, in declaration order, so the
        # draw sequence depends only on the seed and the plan.
        for f in self.faults:
            if f.delay_s is None:
                f.delay_s = self.rng.uniform(0.01, 0.1)

    # -- wiring ------------------------------------------------------------

    def bind_backend(self, backend) -> None:
        """Called by a chaos-aware backend at start: gives ``drop_agent``
        faults something to execute against."""
        with self._lock:
            self._backend = backend

    def observe_launch(self, name: str, task_id: str, pid: int) -> None:
        """Called by the backend per successful launch: registers the pid
        under its ``job:index`` name (latest launch wins — revives and
        elastic re-forms re-register) and counts the launch event."""
        with self._lock:
            self._pids[name] = pid
        self.event("backend.launch", key=name)

    def pid(self, name: str) -> Optional[int]:
        with self._lock:
            return self._pids.get(name)

    def install(self) -> "FaultPlan":
        """Arm the process-global wire hooks and any ``time`` faults."""
        from tfmesos_tpu import wire
        with self._lock:
            if self._installed:
                return self
            self._installed = True
            wire.set_chaos(self.on_wire_send, self.on_wire_recv)
            for f in self.faults:
                if f.site != "time":
                    continue
                t = threading.Timer(f.delay_s or 0.0, self._fire_timed, (f,))
                t.daemon = True
                t.start()
                self._timers.append(t)
        return self

    def uninstall(self) -> None:
        from tfmesos_tpu import wire
        with self._lock:
            if not self._installed:
                return
            self._installed = False
            timers, self._timers = self._timers, []
        wire.set_chaos(None, None)
        for t in timers:
            t.cancel()

    def installed(self):
        """Context manager form of install()/uninstall()."""
        import contextlib

        @contextlib.contextmanager
        def _cm():
            self.install()
            try:
                yield self
            finally:
                self.uninstall()
        return _cm()

    # -- trigger machinery -------------------------------------------------

    def event(self, site: str, key: str = "", **ctx) -> List[Fault]:
        """Count one event at ``site`` and execute/return the faults it
        triggers.  ``kill_task`` and ``drop_agent`` execute here (they are
        site-independent actions); connection-local actions (sever /
        delay / truncate / drop) are returned for the observing hook to
        interpret — ``delay`` is also slept here so every site honors it.
        """
        due: List[Fault] = []
        with self._lock:
            self._counts[site] = self._counts.get(site, 0) + 1
            if key:
                ck = (site, key)
                self._counts[ck] = self._counts.get(ck, 0) + 1
            for i, f in enumerate(self.faults):
                if f.site != site:
                    continue
                if f.action == "partition":
                    # Pair semantics: BOTH endpoints of the fault's
                    # ``target`` ("A|B") must appear in the event key
                    # (the tagged sender + the dialed peer), so only
                    # traffic between that specific pair matches.
                    if not _pair_match(f.target, key):
                        continue
                elif f.target and (not key or f.target not in key):
                    continue
                # Per-fault matched-event counter — cumulative across all
                # keys the target matches, so "the 2nd worker launch"
                # means the 2nd launch of ANY worker, not per-task (and
                # fires exactly once, not once per matching key).
                n = self._fault_hits[i] = self._fault_hits.get(i, 0) + 1
                if f.action in ("slow_task", "partition"):
                    # Persistent failures: armed at the nth event,
                    # live forever after.
                    if n >= f.nth:
                        due.append(f)
                        if n == f.nth:
                            self.fired.append((site, key, f.action, n))
                elif f.nth <= n < f.nth + f.count:
                    due.append(f)
                    self.fired.append((site, key, f.action, n))
        for f in due:
            self._execute(f, site=site, key=key)
        return due

    def _fire_timed(self, f: Fault) -> None:
        with self._lock:
            self.fired.append(("time", f.target or "", f.action, 1))
        self._execute(f, site="time", key=f.target or "")

    def _execute(self, f: Fault, site: str, key: str) -> None:
        # Attribution: every firing lands on the ACTIVE request trace
        # (thread-local — the router activates one around its routing
        # loop) plus the chaos flight recorder, so a soak anomaly maps
        # to the exact injected fault instead of "something was slow".
        # Lazy import: chaos must stay importable without the fleet
        # package.
        try:
            from tfmesos_tpu.fleet import tracing as _tracing
            attrs = {"site": site, "key": key, "action": f.action}
            if f.action in ("delay", "slow_task"):
                attrs["delay_s"] = f.delay_s
            if _tracing.current() is not None:
                # cur_event copies into the chaos flight recorder too.
                _tracing.cur_event("chaos", "fault", **attrs)
            else:
                _tracing.flight("chaos").record(
                    dict(attrs, name="fault"))
        except Exception:       # tracing must never break injection
            pass
        if f.action == "kill_task":
            self.kill(f.victim or f.target or key)
        elif f.action == "drop_agent":
            backend = self._backend
            if backend is None:
                log.warning("chaos: drop_agent fault with no bound backend")
                return
            log.warning("chaos: dropping agent (site %s)", site)
            backend.chaos_drop_agent()
        elif f.action in ("delay", "slow_task"):
            # slow_task: the same seeded, deterministic sleep as delay,
            # just applied to every matching event once armed.
            time.sleep(f.delay_s or 0.0)
        # sever/truncate/drop are interpreted by the observing hook.

    def kill(self, name: str) -> bool:
        """SIGKILL the registered task ``job:index`` — the
        preemption/oom stand-in.  Kills the whole PROCESS GROUP when
        the pid leads one (LocalBackend launches tasks with
        start_new_session, and a Mode-B shell=True command's python
        lives UNDER the registered sh pid — killing only the wrapper
        would orphan the real task alive, a death that never
        happened), falling back to the single pid otherwise.  Returns
        False when the task was never observed (or already reaped)."""
        pid = self.pid(name)
        if pid is None:
            log.warning("chaos: kill_task %r: no registered pid", name)
            return False
        log.warning("chaos: SIGKILL task %s (pid %d)", name, pid)
        try:
            os.killpg(pid, signal.SIGKILL)
            return True
        except (ProcessLookupError, PermissionError):
            pass
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            return False
        return True

    # -- hook-site adapters ------------------------------------------------

    def on_wire_send(self, sock, data: bytes) -> bool:
        """wire.send_msg hook: returns True when the frame was consumed
        (dropped — ``drop`` and armed ``partition`` faults); raises
        OSError for sever/truncate."""
        for f in self.event("wire.send", key=_pair_key(sock)):
            if f.action == "sever":
                _close(sock)
                raise OSError("chaos: connection severed (wire.send)")
            if f.action == "truncate":
                try:
                    sock.sendall(data[:max(1, len(data) // 2)])
                finally:
                    _close(sock)
                raise OSError("chaos: frame truncated (wire.send)")
            if f.action in ("drop", "partition"):
                return True
        return False

    def on_wire_recv(self, sock) -> None:
        """wire.recv_msg hook: raises OSError for sever."""
        for f in self.event("wire.recv", key=_pair_key(sock)):
            if f.action == "sever":
                _close(sock)
                raise OSError("chaos: connection severed (wire.recv)")

    def on_heartbeat(self, addr: str) -> bool:
        """Registry hook: True — this heartbeat never arrived.  Counts
        beat-bearing messages only ("hello" is the first beat; "drain"
        is operator intent and never reaches this hook)."""
        return any(f.action == "drop"
                   for f in self.event("registry.heartbeat", key=addr))


def _peer(sock) -> str:
    try:
        name = sock.getpeername()
    except OSError:
        return ""
    if isinstance(name, tuple) and len(name) >= 2:
        return f"{name[0]}:{name[1]}"
    return str(name)       # AF_UNIX sockets name a path (or nothing)


def _pair_key(sock) -> str:
    """The wire event key: ``"<tagged local ident>|<dialed peer>"`` for
    sockets a named endpoint tagged (wire.tag_socket — the fabric's
    replica-to-replica links), the dialed peer alone otherwise.  The
    peer stays a SUBSTRING of the composite key, so plain
    ``target="host:port"`` faults keep matching tagged traffic too."""
    from tfmesos_tpu import wire
    peer = _peer(sock)
    ident = wire.sock_ident(sock)
    return f"{ident}|{peer}" if ident else peer


def _pair_match(target: Optional[str], key: str) -> bool:
    """Whether a ``partition`` fault's ``"A|B"`` pair both appear in
    the event key (either order; each endpoint a substring, matching
    the rest of chaos's target semantics)."""
    if not target or not key:
        return False
    parts = [p for p in target.split("|") if p]
    return len(parts) == 2 and all(p in key for p in parts)


def _close(sock) -> None:
    try:
        sock.close()
    except OSError:
        pass
