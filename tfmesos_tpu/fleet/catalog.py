"""Model catalog + cross-model replica trading: many models, one fleet.

Mesos's whole premise — and the reference repo's — is many workloads
sharing one pool of machines, yet the fleet so far served exactly ONE
model.  This module makes the model a first-class fleet dimension
(docs/SERVING.md "Model catalog"):

* :class:`ModelSpec` / :class:`ModelCatalog` — the catalog: each entry
  names a ``model_id`` (the SAME validated charset as
  ``weights_version`` — it joins a ``shell=True`` Mode-B command line,
  so the charset is a security boundary, and it becomes a Prometheus
  label), its build config (model seed), a priority ``floor`` (the
  replica count trading never shrinks an ACTIVE model below), and its
  scale-to-zero policy.  Requests without a ``model`` label ride the
  DEFAULT (first-listed) entry, so single-model fleets and old clients
  are byte-for-byte unchanged.

* :class:`ModelTrader` — the :class:`~tfmesos_tpu.fleet.autoscaler.
  FleetAutoscaler` generalized from per-tier to per-(model, tier)
  loops under ONE fleet-wide replica budget.  Each model scales on its
  own windowed queue-wait pressure (``queue_wait_ms_model_<id>``
  histograms the gateway feeds per dispatch); when the budget is tight
  the loop TRADES — drain-migrate-kill one replica of the coldest
  model and relaunch (or warm-pool-adopt) it as the hottest.  Idle
  models scale to ZERO (their sessions stay parked in the KV tier and
  resume on the next cold start), and a bounded WARM POOL of
  pre-warmed, undedicated replicas adopts a ``model_id`` at assignment
  time so a cold start costs a weight install, not a process launch
  plus an XLA warmup.  Victim tie-break feeds on the KV tier: among
  equally-cold models, prefer trading away replicas whose sessions are
  already parked on a shared DISK tier (nothing in-flight is lost and
  the parked turns resume anywhere on the host).

* :func:`pack_adapter` / :func:`unpack_adapter` — the LoRA-style
  weight-delta wire format: a small dict of param-path -> array deltas
  shipped to every replica of one model as ONE raw HMAC frame
  (``swap_adapter``), folded by the batcher between generations behind
  its weight-update fence — in-flight requests finish on the old
  delta, streams stay token-identical per delta version, zero
  downtime.

Everything here is stdlib-only and jax-free (numpy only inside the
pack/unpack helpers), like the rest of the control plane.
"""

from __future__ import annotations

import base64
import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tfmesos_tpu.fleet.autoscaler import AutoscalerConfig, FleetAutoscaler
from tfmesos_tpu.fleet.registry import (ALIVE, MODEL_ID_RE, UNIFIED,
                                        validate_model_id)
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["ModelSpec", "ModelCatalog", "TraderConfig", "ModelTrader",
           "MODEL_ID_RE", "validate_model_id", "model_key", "split_key",
           "filter_members", "POOL", "POOL_KEY", "pack_adapter",
           "unpack_adapter", "encode_adapter_fields",
           "decode_adapter_fields"]

#: the warm pool's reserved pseudo-model id.  Starts with ``_`` so it
#: can NEVER collide with a real (validated) model_id, and never
#: appears on the wire as one — pool membership rides its own
#: ``warm_pool`` heartbeat flag.
POOL = "_pool"


def model_key(model_id: str, role: str = UNIFIED) -> str:
    """The per-(model, tier) target key: ``"<model_id>/<role>"``.
    ``/`` is outside the model-id charset, so the split is
    unambiguous."""
    return f"{model_id}/{role}"


POOL_KEY = model_key(POOL)


def split_key(key: str) -> Tuple[Optional[str], str]:
    """``"m/unified"`` -> ``("m", "unified")``; a plain role key (the
    model-less fleet) -> ``(None, role)``."""
    if "/" in key:
        m, _, role = key.rpartition("/")
        return m, role
    return None, key


def filter_members(members, key: str):
    """The subset of registry ``members`` belonging to one
    per-(model, tier) key: warm-pool members for :data:`POOL_KEY`,
    exact ``model_id`` matches for a model key, everything for a plain
    role key (whose role filtering the registry already did)."""
    model, _ = split_key(key)
    if model == POOL:
        return [r for r in members if getattr(r, "warm_pool", False)]
    if model is not None:
        return [r for r in members
                if getattr(r, "model_id", "") == model]
    return list(members)


@dataclasses.dataclass
class ModelSpec:
    """One catalog entry.

    ``seed`` selects the model's weights (the preset builders derive
    parameters from it — two entries with different seeds ARE two
    models); ``replicas`` is the boot count (0 = starts scaled to
    zero, cold-started through the warm pool on first demand);
    ``floor`` is the priority floor — trading never shrinks an ACTIVE
    (traffic-bearing) model below it; ``scale_to_zero`` allows an IDLE
    model to drop to zero replicas (its parked sessions stay in the KV
    tier); ``gang_size`` shards each replica of this model across N
    gang-member tasks (one pod slice presenting as one routable
    replica) — under the shared budget a gang replica costs N SLOTS,
    not one."""

    model_id: str
    replicas: int = 1
    seed: int = 0
    floor: int = 0
    scale_to_zero: bool = True
    gang_size: int = 1

    def __post_init__(self):
        self.model_id = validate_model_id(self.model_id)
        if self.replicas < 0:
            raise ValueError(f"model {self.model_id!r}: replicas must "
                             f"be >= 0, got {self.replicas}")
        if self.floor < 0:
            raise ValueError(f"model {self.model_id!r}: floor must be "
                             f">= 0, got {self.floor}")
        if self.replicas and self.floor > self.replicas:
            raise ValueError(
                f"model {self.model_id!r}: floor ({self.floor}) "
                f"exceeds its boot replicas ({self.replicas})")
        if self.gang_size < 1:
            raise ValueError(
                f"model {self.model_id!r}: gang_size must be >= 1, "
                f"got {self.gang_size}")


class ModelCatalog:
    """The fleet's model table.  Entries keep their listed order; the
    FIRST entry is the DEFAULT — requests without a ``model`` label
    ride it, which is what keeps model-less clients working unchanged
    against a catalog fleet."""

    def __init__(self, specs):
        specs = list(specs)
        if not specs:
            raise ValueError("a model catalog needs at least one entry")
        ids = [s.model_id for s in specs]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate model_id in catalog: {ids}")
        self._specs: Dict[str, ModelSpec] = {s.model_id: s for s in specs}
        self.default_id = specs[0].model_id

    def resolve(self, label: Optional[str]) -> str:
        """The model a request labeled ``label`` targets: the default
        entry for ``None``/empty; :class:`KeyError` for an UNKNOWN
        label — unlike priority classes, a typo'd model cannot be
        served "without special treatment": there are no weights for
        it, and billing it to the default would be silently wrong."""
        if not label:
            return self.default_id
        if label not in self._specs:
            raise KeyError(f"unknown model {label!r} (catalog has: "
                           f"{', '.join(self.ids())})")
        return label

    def get(self, model_id: str) -> ModelSpec:
        return self._specs[model_id]

    def ids(self) -> List[str]:
        return list(self._specs)

    def __len__(self) -> int:
        return len(self._specs)

    def __iter__(self):
        return iter(self._specs.values())


@dataclasses.dataclass
class TraderConfig:
    """Trading knobs on top of :class:`AutoscalerConfig`'s hysteresis
    band (which the per-model loops reuse).  Sweepable by path in the
    fleet simulator (``tfserve simulate multi-model --sweep
    trader.zero_after_ticks=4,8,16`` — docs/SIMULATOR.md), which is
    where these defaults earn their values: the ``multi-model``
    scenario's hotness flip converges in a handful of trades at the
    defaults, while ``trade_cooldown_s=0`` visibly thrashes replicas
    back and forth on the same trace."""

    #: consecutive control ticks with ZERO traffic (no queue-wait
    #: samples, zero utilization) before an idle scale-to-zero model's
    #: target drops to its floor.
    zero_after_ticks: int = 8
    #: minimum seconds between TRADES (budget-tight reallocations) —
    #: the anti-thrash band: a flapping hotness signal must not churn
    #: the same replica between two models every tick.
    trade_cooldown_s: float = 5.0


class ModelTrader(FleetAutoscaler):
    """Per-(model, tier) autoscaling under one fleet replica budget.

    Inherits the whole convergence machinery (one launch per tick,
    pinned drain-migrate-kill scale-down, stuck-victim deadlines,
    dead-replica self-healing) from :class:`FleetAutoscaler` — the
    generalization is in the RETARGETING: targets are keyed
    ``"<model_id>/<role>"`` (plus the warm pool's :data:`POOL_KEY`),
    each model scales on its OWN windowed queue-wait pressure, and
    when ``sum(targets) == fleet.replica_budget`` a hot model can only
    grow by trading a cold model's replica away.  Scale-up prefers
    ADOPTING an alive warm-pool replica (``fleet.adopt_replica`` — a
    weight install on a pre-warmed process) over launching a cold one.

    The ``fleet`` surface extends the autoscaler's with
    ``replica_budget``, ``tier_members(key)``, ``catalog``, and
    optionally ``adopt_replica(addr, model_id)``.
    """

    def __init__(self, fleet, catalog: ModelCatalog,
                 config: Optional[AutoscalerConfig] = None,
                 trader_config: Optional[TraderConfig] = None,
                 signals: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic):
        super().__init__(fleet, config,
                         signals=signals or self._model_signals,
                         clock=clock)
        #: whether self._signals is the built-in windowed reader (its
        #: off-tick peek variant exists) or an injected source.
        self._own_signals = signals is None
        self.catalog = catalog
        self.tcfg = trader_config or TraderConfig()
        self.log = get_logger("tfmesos_tpu.fleet.trader")
        #: consecutive zero-traffic ticks per model key.
        self._idle_ticks: Dict[str, int] = {}
        #: previous cumulative per-model queue-wait samples (windowed
        #: percentiles, the autoscaler discipline).
        self._prev_qw: Dict[str, tuple] = {}
        #: previous cumulative per-model KV-tier (hits, misses) sums —
        #: the windowed tier hit rate rides next to queue wait as a
        #: victim-pick input: a model actively resuming parked
        #: sessions is a costly trade victim even when its queue
        #: looks calm.
        self._prev_kv_model: Dict[str, Tuple[int, int]] = {}
        # The first TICK-driven trade waits out one cooldown from
        # construction: bring-up queue-wait spikes (everything queues
        # while the fleet warms) read as hotness on every model at
        # once, and trading on them would churn replicas before any
        # real signal exists.  demand() (a model with NO replica at
        # all) is deliberately not gated.
        self._last_trade = self._clock()

    # -- signals -----------------------------------------------------------

    def _model_signals(self, advance: bool = True
                       ) -> Dict[str, Dict[str, Any]]:
        """Per-key signal dicts: each model's WINDOWED queue-wait p99
        and sample count (from the ``queue_wait_ms_model_<id>``
        histogram the gateway observes per dispatch) plus utilization
        over its own alive members; the pool key reports its alive
        count only.  ``advance=False`` is the off-tick PEEK (the
        demand hook's victim pick): it must not consume the window —
        storing ``_prev_qw`` here would make the next periodic tick
        diff against an almost-empty interval and miss the very
        pressure the budget-tight situation produced."""
        out: Dict[str, Dict[str, Any]] = {}
        metrics = self.fleet.metrics
        for key in list(self.fleet.targets):
            model, _ = split_key(key)
            members = self._members(key)
            alive = [r for r in members if r.state == ALIVE]
            capacity = sum(r.capacity for r in alive)
            outstanding = sum(r.outstanding for r in alive)
            util = (outstanding / capacity) if capacity > 0 else 0.0
            sig: Dict[str, Any] = {
                "alive": len(alive), "util": util,
                "queue_wait_p99_ms": None, "samples": 0,
            }
            if model is not None and model != POOL:
                cur = metrics.hist_cumulative(
                    f"queue_wait_ms_model_{model}")
                if cur is not None:
                    prev = self._prev_qw.get(key)
                    from tfmesos_tpu.fleet.metrics import Histogram
                    sig["queue_wait_p99_ms"] = Histogram.delta_percentile(
                        prev, cur, 0.99)
                    sig["samples"] = cur[2] - (prev[2] if prev else 0)
                    if advance:
                        self._prev_qw[key] = cur
            # Windowed per-model KV-tier hit rate from the members'
            # heartbeat counter sums.  Deltas clamp at zero — a dying
            # member's counters leave the sum, which must not read as
            # negative tier traffic.
            sig["kv_hit_rate"] = None
            kv_hits = kv_misses = 0
            for r in members:
                kt = getattr(r, "kv_tier", None)
                if isinstance(kt, dict):
                    c = kt.get("counters")
                    if isinstance(c, dict):
                        kv_hits += int(c.get("hits", 0) or 0)
                        kv_misses += int(c.get("misses", 0) or 0)
            prev_kv = self._prev_kv_model.get(key)
            if prev_kv is not None:
                dh = max(0, kv_hits - prev_kv[0])
                dm = max(0, kv_misses - prev_kv[1])
                if dh + dm > 0:
                    sig["kv_hit_rate"] = dh / (dh + dm)
            if advance:
                self._prev_kv_model[key] = (kv_hits, kv_misses)
            out[key] = sig
        return out

    def _peek_signals(self) -> Dict[str, Dict[str, Any]]:
        """Signals for an off-tick decision: window-preserving for the
        built-in source, the injected callable as-is otherwise."""
        if self._own_signals:
            return self._model_signals(advance=False)
        return self._signals()

    # -- the generalized control tick --------------------------------------

    def step(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        with self.fleet.scale_lock:
            signals = self._signals()
            self._retarget_models(signals, now)
            for key in list(self.fleet.targets):
                self._converge(key, now)
            self._reap_drained(now)

    def _retarget_models(self, signals: Dict[str, Dict[str, Any]],
                         now: float) -> None:
        cfg, tcfg = self.config, self.tcfg
        fleet = self.fleet
        budget = getattr(fleet, "replica_budget", None)
        desired = dict(fleet.targets)
        model_keys = [k for k in desired
                      if split_key(k)[0] not in (None, POOL)]
        hot: List[Tuple[float, float, str]] = []
        for key in model_keys:
            model, _ = split_key(key)
            spec = self.catalog.get(model)
            sig = signals.get(key) or {}
            qw = sig.get("queue_wait_p99_ms")
            samples = sig.get("samples") or 0
            util = sig.get("util") or 0.0
            if samples or util > 0:
                self._idle_ticks[key] = 0
            else:
                self._idle_ticks[key] = self._idle_ticks.get(key, 0) + 1
            idle = self._idle_ticks[key] >= tcfg.zero_after_ticks
            if idle and spec.scale_to_zero \
                    and desired[key] > spec.floor:
                # Scale to zero: the model's replicas free their slots
                # for hotter peers; its parked sessions stay in the KV
                # tier and the next request cold-starts through the
                # warm pool (router demand -> adopt).
                self._last_action[key] = (
                    f"to_zero:{desired[key]}->{spec.floor}")
                self._last_down[key] = now
                desired[key] = spec.floor
                self.fleet.metrics.inc("model_scale_to_zero")
                self.log.info("trader: model %s idle %d ticks — scale "
                              "to %d (sessions stay parked)", model,
                              self._idle_ticks[key], spec.floor)
                continue
            up = ((qw is not None and qw > cfg.queue_wait_hi_ms)
                  or util > cfg.util_hi)
            down = ((not samples or qw is None
                     or qw < cfg.queue_wait_lo_ms)
                    and util < cfg.util_lo)
            if up and now - self._last_up.get(key, -1e18) \
                    >= cfg.scale_up_cooldown:
                hot.append((qw or 0.0, util, key))
            elif (down and not up
                  and desired[key] > max(1, spec.floor)
                  and now - self._last_down.get(key, -1e18)
                  >= cfg.scale_down_cooldown):
                desired[key] -= 1
                self._last_down[key] = now
                self._last_action[key] = "down"
                self.fleet.metrics.inc("autoscale_down")
        if hot:
            # One growth decision per tick, hottest model first — the
            # same one-step-per-tick convergence cadence as the base
            # loop, which is what bounds trade thrash.  Budget math is
            # in SLOTS (member tasks), not replicas: a gang replica of
            # size N costs N slots, so growing a gang model may need
            # SEVERAL victims' slots in one trade.
            hot.sort(reverse=True)
            _, _, key = hot[0]
            need = self._slot_cost(key)
            total = self._slots(desired)
            if budget is None or total + need <= budget:
                desired[key] += 1
                self._last_up[key] = now
                self._last_action[key] = "up"
                self.fleet.metrics.inc("autoscale_up")
            elif now - self._last_trade >= tcfg.trade_cooldown_s:
                victims = self._free_slots(desired, key, signals,
                                           need, budget)
                if victims is not None:
                    for victim in victims:
                        desired[victim] -= 1
                        self._last_down[victim] = now
                        self._last_action[victim] = f"trade_to:{key}"
                    desired[key] += 1
                    self._last_trade = now
                    self._last_up[key] = now
                    self._last_action[key] = \
                        f"trade_from:{','.join(victims)}"
                    self.fleet.metrics.inc("model_trades")
                    self.log.info(
                        "trader: budget tight (%d/%s) — trading %d "
                        "replica slot(s) %s -> %s", total, budget,
                        len(victims), victims, key)
                else:
                    self.fleet.metrics.inc("model_trade_blocked")
        for key, n in desired.items():
            if n != fleet.targets.get(key):
                fleet.set_target(key, n)

    def _slot_cost(self, key: str) -> int:
        """Budget slots ONE replica of ``key`` occupies: the model's
        gang size (a pod-slice replica is N member tasks), 1 for the
        warm pool and plain tiers."""
        model, _ = split_key(key)
        if model in (None, POOL):
            return 1
        return int(getattr(self.catalog.get(model),
                           "gang_size", 1) or 1)

    def _slots(self, desired: Dict[str, int]) -> int:
        return sum(n * self._slot_cost(k) for k, n in desired.items())

    def _free_slots(self, desired: Dict[str, int], hot_key: str,
                    signals: Dict[str, Dict[str, Any]], need: int,
                    budget: int) -> Optional[List[str]]:
        """Victim keys (one entry per shrunk replica, keys may repeat)
        whose freed slots make room for one more ``hot_key`` replica
        of ``need`` slots — or None when the fleet cannot free enough.
        All-or-nothing: a gang trade that frees only HALF its slots
        would shrink victims for no growth at all."""
        work = dict(desired)
        victims: List[str] = []
        while self._slots(work) + need > budget:
            victim = self._free_slot(work, hot_key, signals)
            if victim is None:
                return None
            work[victim] -= 1
            victims.append(victim)
        return victims

    def _free_slot(self, desired: Dict[str, int], hot_key: str,
                   signals: Dict[str, Dict[str, Any]]
                   ) -> Optional[str]:
        """The key whose budget slot a hot model claims: the WARM POOL
        first — an undedicated pre-warmed replica exists precisely to
        be handed to whichever model needs one, so its slot moves
        before any traffic-bearing model's replica drains — then the
        coldest model per :meth:`_pick_victim`."""
        if desired.get(POOL_KEY, 0) > 0:
            return POOL_KEY
        return self._pick_victim(desired, hot_key, signals)

    def _pick_victim(self, desired: Dict[str, int], hot_key: str,
                     signals: Dict[str, Dict[str, Any]]
                     ) -> Optional[str]:
        """The COLDEST model key a replica may be traded away from:
        relative windowed queue-wait pressure decides (no-traffic
        models first, then the lowest p99), the KV tier breaks ties —
        prefer victims whose sessions are already PARKED on a shared
        disk tier (the trade then loses nothing resumable).  Never the
        hot model; never below the victim's own live bound (its floor
        when idle, at least one replica while it still has traffic)."""
        tcfg = self.tcfg
        best = None
        for key, n in desired.items():
            model, _ = split_key(key)
            if key == hot_key or model in (None, POOL):
                continue
            spec = self.catalog.get(model)
            idle = self._idle_ticks.get(key, 0) >= tcfg.zero_after_ticks
            bound = spec.floor if (idle and spec.scale_to_zero) \
                else max(1, spec.floor)
            if n <= bound:
                continue
            sig = signals.get(key) or {}
            qw = sig.get("queue_wait_p99_ms")
            samples = sig.get("samples") or 0
            kv_hit = sig.get("kv_hit_rate")
            score = (
                0 if not samples else 1,    # traffic-less models first
                qw if qw is not None else 0.0,
                # Windowed tier hit rate: a model actively RESUMING
                # parked sessions pays real cold re-prefills if its
                # replica drains — prefer victims whose tier sits idle.
                kv_hit if kv_hit is not None else 0.0,
                -self._parked_disk_sessions(key),  # satellite: prefer
                key,                               # parked-on-disk
            )
            if best is None or score < best[0]:
                best = (score, key)
        return best[1] if best is not None else None

    def _parked_disk_sessions(self, key: str) -> int:
        """How many of this model's sessions are parked on a DISK
        (host-shared) KV tier — the PR 13 follow-up signal: those
        conversations resume on any later replica of the host, so
        trading their parker away is the cheapest possible shrink."""
        total = 0
        for r in self._members(key):
            kt = getattr(r, "kv_tier", None)
            if isinstance(kt, dict) and kt.get("disk"):
                sess = kt.get("sessions")
                if isinstance(sess, (list, tuple)):
                    total += len(sess)
        return total

    # -- actuation hooks ---------------------------------------------------

    def _allow_zero(self, key: str) -> bool:
        model, _ = split_key(key)
        if model in (None, POOL):
            return model == POOL
        spec = self.catalog.get(model)
        return spec.scale_to_zero and spec.floor == 0

    def _scale_up(self, key: str) -> str:
        """Adopt an alive warm-pool replica when one exists (a weight
        install on a pre-warmed, pre-compiled process — the cold-start
        TTFT cap), else launch a cold Mode-B task like the base
        loop."""
        model, role = split_key(key)
        adopt = getattr(self.fleet, "adopt_replica", None)
        if model not in (None, POOL) and role == UNIFIED \
                and adopt is not None:
            pool = [r for r in self._members(POOL_KEY)
                    if r.state == ALIVE]
            pool.sort(key=lambda r: r.addr)
            for r in pool:
                try:
                    ok = adopt(r.addr, model)
                except Exception:
                    self.log.exception("warm-pool adoption of %s for "
                                       "%s failed; launching cold",
                                       r.addr, model)
                    break
                if ok:
                    self.fleet.metrics.inc("model_adoptions")
                    self.log.info("trader: warm-pool replica %s "
                                  "adopted model %s", r.addr, model)
                    return f"adopt:{r.addr}"
        return self.fleet.launch_replica(key)

    def demand(self, model_id: str) -> bool:
        """Out-of-band cold-start signal (the router calls this when a
        request names a model with NO routable replica): raise the
        model's target to at least one — trading a cold model's slot
        away if the budget is full — and adopt-or-launch IMMEDIATELY
        instead of waiting for the next tick.  False when the model is
        unknown or nothing could be freed."""
        try:
            spec = self.catalog.get(model_id)
        except KeyError:
            return False
        key = model_key(model_id)
        with self.fleet.scale_lock:
            self._idle_ticks[key] = 0
            if self.fleet.targets.get(key, 0) < 1:
                budget = getattr(self.fleet, "replica_budget", None)
                need = self._slot_cost(key)
                total = self._slots(self.fleet.targets)
                if budget is not None and total + need > budget:
                    victims = self._free_slots(
                        dict(self.fleet.targets), key,
                        self._peek_signals(), need, budget)
                    if victims is None:
                        self.fleet.metrics.inc("model_trade_blocked")
                        return False
                    for victim in victims:
                        self.fleet.set_target(
                            victim, self.fleet.targets[victim] - 1)
                        self._last_down[victim] = self._clock()
                    self.fleet.metrics.inc("model_trades")
                self.fleet.set_target(key, max(1, spec.floor))
                self.fleet.metrics.inc("model_cold_starts")
                self.log.info("trader: cold-start demand for model %s",
                              model_id)
            members = self._members(key)
            if not any(r.state in (ALIVE, "warming") for r in members) \
                    and self.fleet.tier_actual(key) < 1:
                self._scale_up(key)
            return True

    # -- observability -----------------------------------------------------

    def describe(self) -> Dict[str, Dict[str, Any]]:
        out = super().describe()
        for key in out:
            out[key]["idle_ticks"] = self._idle_ticks.get(key, 0)
        return out


# -- adapter (weight-delta) wire format --------------------------------------


def pack_adapter(delta: Dict[str, Any]) -> Tuple[dict, bytes]:
    """Pack a param-path -> numpy-array delta dict into the raw-frame
    shape (``meta``, ``body``): meta carries the manifest (paths,
    shapes, dtypes — JSON, never pickle: PR 4's hardening promise),
    body is the arrays' raw bytes concatenated in path order.  The
    frame's HMAC tag (applied by the wire layer) covers both."""
    import numpy as np

    if not delta:
        raise ValueError("an adapter delta needs at least one entry")
    paths, shapes, dtypes, chunks = [], [], [], []
    for path in sorted(delta):
        arr = np.ascontiguousarray(delta[path])
        paths.append(str(path))
        shapes.append(list(arr.shape))
        dtypes.append(str(arr.dtype))
        chunks.append(arr.tobytes())
    meta = {"adapter": {"paths": paths, "shapes": shapes,
                        "dtypes": dtypes,
                        "sizes": [len(c) for c in chunks]}}
    return meta, b"".join(chunks)


def unpack_adapter(meta: dict, body: bytes) -> Dict[str, Any]:
    """Inverse of :func:`pack_adapter`; raises ``ValueError`` on a
    malformed manifest (sizes that do not tile the body, bad dtypes)."""
    import numpy as np

    man = meta.get("adapter")
    if not isinstance(man, dict):
        raise ValueError("adapter frame carries no manifest")
    paths = man.get("paths")
    shapes = man.get("shapes")
    dtypes = man.get("dtypes")
    sizes = man.get("sizes")
    if not (isinstance(paths, list) and isinstance(shapes, list)
            and isinstance(dtypes, list) and isinstance(sizes, list)
            and len(paths) == len(shapes) == len(dtypes) == len(sizes)
            and paths):
        raise ValueError("malformed adapter manifest")
    if sum(int(s) for s in sizes) != len(body):
        raise ValueError(
            f"adapter body ({len(body)} bytes) does not match its "
            f"manifest ({sum(int(s) for s in sizes)} bytes)")
    out: Dict[str, Any] = {}
    off = 0
    for path, shape, dtype, size in zip(paths, shapes, dtypes, sizes):
        size = int(size)
        try:
            dt = np.dtype(str(dtype))
            if dt.itemsize == 0:    # e.g. "V0": would ZeroDivisionError
                raise ValueError(f"zero-itemsize dtype {dtype!r}")
            arr = np.frombuffer(body, dtype=dt, count=size // dt.itemsize,
                                offset=off).reshape([int(d) for d in shape])
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad adapter entry {path!r}: {e}") from e
        out[str(path)] = arr.copy()     # frombuffer views are read-only
        off += size
    return out


def encode_adapter_fields(delta: Dict[str, Any]) -> Dict[str, Any]:
    """The JSON-safe shape of an adapter delta for the GATEWAY hop
    (the gateway's public port rejects raw frames at the length
    prefix, so the control op carries base64; the launcher re-ships
    the decoded bytes to replicas as one raw HMAC frame)."""
    meta, body = pack_adapter(delta)
    out = dict(meta["adapter"])
    out["body_b64"] = base64.b64encode(body).decode("ascii")
    return out


def decode_adapter_fields(fields: Dict[str, Any]) -> Tuple[dict, bytes]:
    """Gateway-side inverse of :func:`encode_adapter_fields` —
    stdlib-only (no numpy on the gateway): returns the raw-frame
    ``(meta, body)`` WITHOUT materializing arrays; the manifest is
    validated structurally here and numerically by the replica."""
    if not isinstance(fields, dict):
        raise ValueError("adapter delta must be an object")
    b64 = fields.get("body_b64")
    if not isinstance(b64, str) or not b64:
        raise ValueError("adapter delta needs body_b64")
    try:
        body = base64.b64decode(b64.encode("ascii"), validate=True)
    except Exception as e:
        raise ValueError(f"adapter body_b64 does not decode: {e}") from e
    man = {k: fields.get(k) for k in ("paths", "shapes", "dtypes",
                                      "sizes")}
    if not all(isinstance(v, list) and v for v in man.values()):
        raise ValueError("adapter delta needs paths/shapes/dtypes/sizes")
    sizes = man["sizes"]
    if not all(isinstance(s, int) and not isinstance(s, bool) and s > 0
               for s in sizes) or sum(sizes) != len(body):
        raise ValueError("adapter sizes do not tile the body")
    return {"adapter": man}, body
