"""A serving replica: one ``ContinuousBatcher`` behind a TCP server.

This is the process the fleet launcher schedules N of as Mode-B tasks
(``python -m tfmesos_tpu.fleet.replica --registry HOST:PORT ...``): it
builds the model, starts the batcher's incremental serve loop on a
dedicated thread, accepts multiplexed ``generate`` requests over the
authenticated wire protocol, and streams each completion back on the
connection it arrived on as soon as the batcher finishes it — requests
from many gateway workers interleave into ONE continuous batch, which
is the entire point of fronting the batcher with a fleet.

The cluster token arrives through the standard task env contract
(``TPUMESOS_TOKEN_FILE`` / ``TPUMESOS_TOKEN``, resolved by
:func:`tfmesos_tpu.wire.load_token`), so only processes launched by our
scheduler can join the serving path.

Liveness: a heartbeat thread dials the registry and streams
``{op: heartbeat, addr, capacity, outstanding}`` on a persistent
connection; the connection dying IS the registry's earliest death
signal.  On SIGTERM the replica announces a drain, stops accepting, and
exits.  With ``--warmup`` the replica registers with
``status: warming`` — present but never routed — compiles every jitted
serving entry point (``ContinuousBatcher.warmup``), and only then
drops the status to take traffic, so a cold start (boot, elastic
relaunch, Mode-B restart) never pays its compiles on a live request.

:class:`ReplicaServer` itself is model-agnostic — it serves whatever
``handler(msg, reply)`` it is given, which keeps the whole fleet
machinery unit-testable without JAX (see ``tests/test_fleet.py``'s stub
replicas).
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import sys
import threading
from typing import Any, Callable, Dict, List, Optional

from tfmesos_tpu import wire
from tfmesos_tpu.fleet import tracing
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["ReplicaServer", "BatcherServing", "batcher_handler",
           "prefill_handler", "fabric_handler", "tiny_model",
           "flagship_model", "tiny_draft_model", "flagship_draft_model",
           "build_parser", "main"]


def _hop_trace(head) -> Optional["tracing.TraceContext"]:
    """The replica-side hop context for a request carrying a
    ``trace_id``: spans are offsets from THIS moment (receipt) and
    piggyback on the reply — absolute clocks never cross the wire.
    A malformed field costs the trace, never the request."""
    tid = head.get("trace_id")
    if not isinstance(tid, str) or not tid:
        return None
    slow = head.get("trace_slow_ms")
    return tracing.TraceContext(
        trace_id=tid, detailed=bool(head.get("trace_detail")),
        slow_ms=(float(slow) if isinstance(slow, (int, float))
                 and not isinstance(slow, bool) and slow > 0 else None))


def _attach_trace(out: Dict[str, Any], tr, failed: bool = False
                  ) -> Dict[str, Any]:
    """Piggyback the hop's spans on a reply dict per the tail rule:
    detail was requested, the hop failed, or the hop ran slow."""
    if tr is not None and tr.should_export(failed=failed):
        out["trace"] = tr.export()
    return out


class ReplicaServer:
    """Threaded request server + registry heartbeater.

    ``handler(msg, reply)`` serves one ``generate`` message; it may call
    ``reply(dict)`` synchronously or later from another thread (the
    batcher's completion loop).  ``reply`` is single-shot and maintains
    the server's outstanding count.
    """

    def __init__(self, handler: Callable[[Dict[str, Any], Callable], None],
                 token: str = "", capacity: int = 0,
                 host: str = "127.0.0.1", port: int = 0,
                 registry_addr: Optional[str] = None,
                 heartbeat_interval: float = 0.3,
                 advertise_host: Optional[str] = None,
                 extra_info: Optional[Callable[[], Dict[str, Any]]] = None,
                 status: Optional[str] = None):
        self.handler = handler
        self.token = token
        self.capacity = int(capacity)
        self.host = host
        self.port = int(port)
        self.registry_addr = registry_addr
        self.heartbeat_interval = float(heartbeat_interval)
        self.advertise_host = advertise_host
        # Extra fields merged into every heartbeat (must be cheap and
        # never raise) — the batcher's prefix-cache summary rides here
        # so the gateway's prefix-affinity routing knows what this
        # replica has resident.
        self.extra_info = extra_info
        # Lifecycle status advertised on the hello AND every beat
        # ("warming" while the batcher compiles its entry points; None
        # = routable).  It rides the hello so the registry never has a
        # window where a still-compiling replica looks routable, and
        # the replica flips itself live by just dropping the field
        # (set_status(None)) once warmup returns.
        self._status = status
        self.log = get_logger("tfmesos_tpu.fleet.replica")
        self.addr: Optional[str] = None
        self._listen: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns: set = set()
        self._outstanding = 0
        self._olock = threading.Lock()

    @property
    def outstanding(self) -> int:
        with self._olock:
            return self._outstanding

    def set_status(self, status: Optional[str]) -> None:
        """Change the advertised lifecycle status.  The next beat (one
        ``heartbeat_interval`` away at most) carries it; flipping to
        ``None`` is how a warmed replica advertises itself routable."""
        self._status = status

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReplicaServer":
        self._listen = wire.bind_ephemeral(self.host, port=self.port)
        advertise = self.advertise_host or (
            None if self.host in ("0.0.0.0", "::") else self.host)
        self.addr = wire.sock_addr(self._listen, advertise_host=advertise)
        self.log.info("replica serving on %s (capacity %d)", self.addr,
                      self.capacity)
        t = threading.Thread(target=self._accept_loop,
                             name="replica-accept", daemon=True)
        t.start()
        self._threads = [t]
        if self.registry_addr:
            hb = threading.Thread(target=self._heartbeat_loop,
                                  name="replica-heartbeat", daemon=True)
            hb.start()
            self._threads.append(hb)
        return self

    def stop(self) -> None:
        self._stop.set()
        # close() alone does not interrupt a blocked accept(): poke the
        # listener awake so the accept thread exits NOW instead of
        # burning its whole join timeout (this is also what keeps a
        # SIGTERM'd replica's exit prompt — the drain the fleet waits
        # on rides the process death).
        wire.wake_listener(self._listen)
        if self._listen is not None:
            try:
                self._listen.close()
            except OSError:
                pass
        with self._olock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:  # unblock reader threads; peers see EOF
            # shutdown BEFORE close: our own reader thread is blocked
            # in recv on this socket, and close() alone neither wakes
            # it nor sends the peer its FIN until that recv returns —
            # a stopping replica's in-flight callers would ride their
            # full timeouts instead of failing over promptly.
            wire.shutdown_socket(conn)
            try:
                conn.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)

    # -- request serving ---------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listen.accept()
            except OSError:
                return
            with self._olock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="replica-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        # Replica links legitimately carry multi-MB raw KV frames (the
        # disaggregated import path) — the one listener that opts in.
        framer = wire.Framer(self.token, allow_raw=True)
        send_lock = threading.Lock()
        try:
            conn.settimeout(None)
            for msg in wire.iter_msgs(conn, framer):
                self._handle(conn, send_lock, msg)
        except wire.WireError as e:
            self.log.warning("rejecting connection: %s", e)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._olock:
                self._conns.discard(conn)

    def _send(self, conn: socket.socket, lock: threading.Lock,
              msg) -> None:
        try:
            with lock:
                if isinstance(msg, wire.RawFrame):
                    wire.send_raw_msg(conn, msg.meta, msg.body, self.token)
                else:
                    wire.send_msg(conn, msg, self.token)
        except OSError:
            pass    # peer gone; its requests died with it

    def _handle(self, conn: socket.socket, send_lock: threading.Lock,
                msg: Any) -> None:
        # Raw binary frames (the disaggregated KV handoff) carry their
        # op/id in the JSON meta header; the handler receives the
        # whole RawFrame so the body never copies through a re-encode.
        if isinstance(msg, wire.RawFrame):
            head = msg.meta if isinstance(msg.meta, dict) else {}
        elif isinstance(msg, dict):
            head = msg
        else:
            return
        op = head.get("op")
        mid = head.get("id")
        if op == "ping":
            self._send(conn, send_lock, {"op": "pong", "id": mid})
            return
        # "migrate" is the drain-migration control op (the fleet's
        # control plane asks this replica to suspend its in-flight rows
        # so the router can re-place them); "adopt" assigns a warm-pool
        # replica its model, "swap_adapter" ships a weight delta as one
        # raw frame — authenticated like every frame, and
        # handler-interpreted like generate/prefill.  The kv_* ops are
        # the cross-host KV fabric's surface: "kv_put" lands a peer's
        # replicated park, "kv_fetch" serves a peer's resume, and
        # "kv_stage" lands a direct peer-to-peer KV stream ahead of
        # the router's small generate call referencing it.
        # "cancel" is the advisory client-disconnect op: one-way from
        # the router (id 0), it asks the batcher to release the row of
        # an in-flight streamed request whose client is gone.
        if op not in ("generate", "prefill", "migrate", "adopt",
                      "swap_adapter", "kv_put", "kv_fetch", "kv_stage",
                      "cancel"):
            self._send(conn, send_lock,
                       {"op": "error", "id": mid,
                        "kind": "bad_request",
                        "error": f"unknown op {op!r}"})
            return
        with self._olock:
            self._outstanding += 1
        done = threading.Event()    # single-shot guard

        def reply(out) -> None:
            if done.is_set():
                return
            done.set()
            with self._olock:
                self._outstanding -= 1
            self._send(conn, send_lock, out)

        def partial(out) -> None:
            # Streaming side channel: PARTIAL frames (op: tokens) may
            # precede the single final reply — they share the
            # connection's send lock but never consume the single-shot
            # guard or the outstanding count.
            if done.is_set():
                return
            self._send(conn, send_lock, out)

        reply.partial = partial
        # Per-connection identity for the in-flight registry: a cancel
        # names its target by the mux call id, which is only unique PER
        # ROUTER CONNECTION — keying on (conn, id) keeps two routers'
        # colliding ids from cross-cancelling each other's requests.
        reply.conn_key = id(conn)
        try:
            self.handler(msg, reply)
        except Exception as e:      # handler bug: fail THIS request only
            self.log.exception("handler failed: %s", e)
            reply({"op": "error", "id": mid, "kind": "internal",
                   "error": repr(e)})

    # -- heartbeats --------------------------------------------------------

    def _merge_extra(self, beat: Dict[str, Any]) -> None:
        status = self._status
        if status is not None:
            beat["status"] = status
        if self.extra_info is None:
            return
        try:
            beat.update(self.extra_info())
        except Exception:
            # A broken callback costs its fields, never the heartbeat —
            # losing the beat would get a healthy replica marked dead.
            self.log.exception("heartbeat extra_info failed; beat "
                               "sent bare")

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            sock = None
            try:
                sock = wire.connect(self.registry_addr, timeout=5.0)
                hello = {"op": "hello", "addr": self.addr,
                         "capacity": self.capacity}
                self._merge_extra(hello)    # role must land BEFORE any
                wire.send_msg(sock, hello, self.token)  # routing decision
                while not self._stop.wait(self.heartbeat_interval):
                    beat = {"op": "heartbeat", "addr": self.addr,
                            "capacity": self.capacity,
                            "outstanding": self.outstanding}
                    self._merge_extra(beat)
                    wire.send_msg(sock, beat, self.token)
                # Graceful exit: tell the registry we are draining so it
                # stops routing to us before the process dies.
                wire.send_msg(sock, {"op": "drain", "addr": self.addr},
                              self.token)
            except OSError as e:
                self.log.warning("registry %s unreachable: %s; retrying",
                                 self.registry_addr, e)
                self._stop.wait(self.heartbeat_interval)
            finally:
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass


class BatcherServing:
    """Bridge from the request/reply surface to the batcher's
    incremental submission API: ``submit()`` registers a completion
    callback keyed by request identity, a dedicated thread drains
    ``batcher.serve()`` and fires callbacks in finish order."""

    def __init__(self, batcher):
        self.batcher = batcher
        self._callbacks: Dict[int, Callable] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "BatcherServing":
        self._thread = threading.Thread(target=self._loop,
                                        name="batcher-serve", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        try:
            for comp in self.batcher.serve():
                with self._lock:
                    cb = self._callbacks.pop(id(comp.request), None)
                if cb is not None:
                    cb(comp, None)
        except BaseException as e:  # loop died: fail every waiter loudly
            with self._lock:
                cbs = list(self._callbacks.values())
                self._callbacks.clear()
            for cb in cbs:
                cb(None, f"batcher serve loop died: {e!r}")
            raise

    def submit(self, request, on_done: Callable,
               prefilled: Optional[dict] = None) -> None:
        """``on_done(completion, error)``: exactly one of the two is
        set — ``completion`` may also be a
        :class:`~tfmesos_tpu.serving.Suspended` (drain migration gave
        the request back instead of finishing it) or an
        :class:`~tfmesos_tpu.serving.Expired` (the batcher cancelled
        it because its end-to-end deadline passed).  ``prefilled``
        routes the request through the batcher's KV-import admission
        (disaggregated decode, or a migrated resume)."""
        with self._lock:
            self._callbacks[id(request)] = on_done
        if prefilled is not None:
            self.batcher.submit(request, prefilled=prefilled)
        else:
            self.batcher.submit(request)

    def close(self) -> None:
        self.batcher.close()
        if self._thread is not None:
            self._thread.join(timeout=30.0)


def _deadline_ms(head) -> Optional[float]:
    """The remaining end-to-end budget the router forwarded (ms), or
    None — a malformed or non-positive value costs the field, never
    the request (the fleet's standard optional-field discipline)."""
    dl = head.get("deadline_ms")
    if isinstance(dl, (int, float)) and not isinstance(dl, bool) \
            and dl > 0:
        return float(dl)
    return None


def _handle_swap_adapter(batcher, msg, reply: Callable) -> None:
    """Serve one ``swap_adapter`` raw frame (the adapter hot-swap,
    docs/SERVING.md "Model catalog"): unpack the HMAC-verified delta,
    queue the fold behind the batcher's weight-update fence, and reply
    once it has APPLIED — in-flight requests finish on the old delta
    first, so the ack means "every stream from here on runs the new
    version".  Shared by the decode/unified and prefill handlers (a
    prefill batcher has no serve loop, so its fold applies — and
    replies — synchronously)."""
    from tfmesos_tpu.fleet import catalog as catalog_mod

    head = msg.meta if isinstance(msg, wire.RawFrame) else msg
    mid = head.get("id")
    if not isinstance(msg, wire.RawFrame):
        reply({"op": "error", "id": mid, "kind": "bad_request",
               "error": "swap_adapter ships its delta as a raw frame"})
        return
    try:
        from tfmesos_tpu.fleet.registry import validate_model_id
        version = validate_model_id(head.get("adapter_version"))
        delta = catalog_mod.unpack_adapter(head, msg.body)
    except (TypeError, ValueError) as e:
        reply({"op": "error", "id": mid, "kind": "bad_request",
               "error": str(e)})
        return

    def applied() -> None:
        reply({"op": "adapter_swapped", "id": mid,
               "adapter_version": version,
               "swaps": batcher.weight_swaps})

    try:
        batcher.swap_adapter(delta, version, on_applied=applied)
    except ValueError as e:
        reply({"op": "error", "id": mid, "kind": "bad_request",
               "error": str(e)})


def batcher_handler(serving: BatcherServing, generation: int = 0,
                    weights_version: str = "",
                    model_state: Optional[Dict[str, Any]] = None,
                    adopt_fn: Optional[Callable] = None,
                    token: str = "",
                    self_addr: Optional[Callable[[], str]] = None
                    ) -> Callable:
    """The model-backed ``ReplicaServer`` handler (decode/unified
    roles): validate, submit, stream the completion back when the
    batcher finishes it.  A plain ``generate`` dict takes the local
    prefill path; a RAW ``generate`` frame (meta + KV body) takes the
    disaggregated IMPORT path — the payload pages install into the
    pool and the row enters decode directly (mid-stream suspended
    artifacts resume exactly where they stopped).

    A ``migrate`` control message asks the batcher to SUSPEND every
    in-flight request: each pending generate then gets a ``suspended``
    reply instead of a completion — a raw frame carrying the row's
    resumable KV artifact (stamped with this replica's launch
    ``generation`` so the registry fence can reject a zombie's export,
    and its ``weights_version`` so the router resumes onto matching
    weights), or a plain requeue marker when the request held no
    exportable state.  The router re-places either form on a surviving
    replica; the client sees one completion, never the move."""
    import time as _time

    import numpy as np

    from tfmesos_tpu import serving as serving_mod
    from tfmesos_tpu.serving import Expired, Prefilled, Request, Suspended

    batcher = serving.batcher
    log = get_logger("tfmesos_tpu.fleet.replica")
    # Direct-stream staging area (docs/SERVING.md "Cross-host KV
    # fabric"): a peer lands a KV artifact here as one ``kv_stage`` raw
    # frame, the router's later small ``generate`` call references it
    # by ``kv_ref`` — the bytes never transit the control plane.
    # Bounded and TTL'd so an abandoned transfer (router died between
    # broker and generate) cannot pin replica RAM.
    _staged: Dict[str, tuple] = {}
    _stage_lock = threading.Lock()
    _stage_max = 8
    _stage_ttl_s = 120.0
    # Direct-push target for drain migration: the migrate control op
    # may name the survivor the router already picked (``push_to``), in
    # which case each Suspended artifact streams peer-to-peer as a
    # kv_stage frame and only a small ``pushed`` suspended reply rides
    # back through the control plane.
    _push_state: Dict[str, Any] = {"to": None}
    # In-flight requests keyed by (connection identity, call id): the
    # advisory ``cancel`` op (sent by the router when a streaming
    # client disconnects) looks its target up here and stamps the live
    # Request's deadline into the past — the batcher's own per-tick
    # expiry check then cancels the row, frees its pages, and resolves
    # the pending generate as deadline_exceeded.  Keyed per connection
    # because call ids are only unique per router link.
    _inflight: Dict[tuple, Any] = {}
    _inflight_lock = threading.Lock()

    def _push_stage(addr: str, smeta: Dict[str, Any],
                    body: bytes) -> Any:
        from tfmesos_tpu.fleet.kvtier import fabric_rpc

        return fabric_rpc(addr, smeta, body, token=token, timeout=30.0,
                          self_addr=self_addr() if self_addr else "")

    def handler(msg, reply: Callable) -> None:
        raw = isinstance(msg, wire.RawFrame)
        head = msg.meta if raw else msg
        mid = head.get("id")
        if head.get("op") == "cancel":
            target = head.get("target")
            key = (getattr(reply, "conn_key", None), target)
            with _inflight_lock:
                req = _inflight.get(key)
            if req is not None:
                req.deadline = _time.perf_counter()
            # The router sends cancels one-way (id 0) and drops this
            # reply as unmatched; answering anyway keeps the server's
            # outstanding count balanced and gives tests a surface.
            reply({"op": "cancelled", "id": mid,
                   "found": req is not None})
            return
        if head.get("op") == "kv_stage":
            if not raw:
                reply({"op": "error", "id": mid, "kind": "bad_request",
                       "error": "kv_stage ships its artifact as a raw "
                                "frame"})
                return
            xfer = head.get("xfer")
            if not isinstance(xfer, str) or not xfer:
                reply({"op": "error", "id": mid, "kind": "bad_request",
                       "error": "kv_stage needs a string xfer id"})
                return
            now = _time.monotonic()
            with _stage_lock:
                for k in [k for k, (t, _m, _b) in _staged.items()
                          if now - t > _stage_ttl_s]:
                    del _staged[k]
                if len(_staged) >= _stage_max:
                    reply({"op": "error", "id": mid,
                           "kind": "overloaded",
                           "error": f"kv stage full ({_stage_max} "
                                    f"transfers pending)"})
                    return
                _staged[xfer] = (now, dict(head), msg.body)
            reply({"op": "kv_staged", "id": mid, "xfer": xfer,
                   "bytes": len(msg.body)})
            return
        if head.get("op") == "migrate":
            # Ack immediately: the suspensions themselves surface as
            # the in-flight requests' own replies on the next loop
            # tick, and the drain waits on outstanding reaching zero.
            pt = head.get("push_to")
            _push_state["to"] = pt if isinstance(pt, str) and pt \
                else None
            batcher.preempt_all()
            reply({"op": "migrated", "id": mid})
            return
        if head.get("op") == "swap_adapter":
            _handle_swap_adapter(batcher, msg, reply)
            return
        if head.get("op") == "adopt":
            # Warm-pool adoption (docs/SERVING.md "Model catalog"):
            # install one catalog model's weights on this pre-warmed,
            # undedicated replica.  The closure comes from main() —
            # it knows the preset family and updates the heartbeat's
            # model identity once the install applies.
            if adopt_fn is None:
                reply({"op": "error", "id": mid, "kind": "bad_request",
                       "error": "this replica has no model-adoption "
                                "surface (started without a warm-pool "
                                "role)"})
            else:
                adopt_fn(head, reply)
            return
        if head.get("op") == "prefill":
            reply({"op": "error", "id": mid, "kind": "bad_request",
                   "error": "this replica does not serve the prefill "
                            "op (role: decode/unified); route prefill "
                            "to a prefill-role replica"})
            return
        staged_body = None
        if not raw and head.get("kv_ref") is not None:
            # Direct-streamed generate: the KV artifact already landed
            # here as a kv_stage frame; the router's small call names
            # it.  The staged meta merged under the call's own fields
            # reconstructs exactly the raw-frame head the relay path
            # would have delivered.
            kv_ref = head.get("kv_ref")
            with _stage_lock:
                ent = _staged.pop(kv_ref, None) \
                    if isinstance(kv_ref, str) else None
            if ent is None:
                reply({"op": "error", "id": mid, "kind": "bad_request",
                       "error": f"unknown kv_ref {kv_ref!r}: staged "
                                f"transfer expired or never landed"})
                return
            _t0, smeta, staged_body = ent
            merged = {k: v for k, v in smeta.items()
                      if k not in ("op", "id", "xfer", "trace",
                                   "prefill_ms")}
            merged.update(head)
            head = merged
        want_model = head.get("model")
        if isinstance(want_model, str) and want_model \
                and model_state is not None \
                and model_state.get("model_id") != want_model:
            # A pick racing a warm-pool adoption (or a stale routing
            # view): answering with THIS replica's weights would be
            # silently wrong.  Transient (not bad_request) — the
            # router retries another replica of the right model.
            reply({"op": "error", "id": mid, "kind": "wrong_model",
                   "error": f"this replica serves model "
                            f"{model_state.get('model_id') or '(none)'!r}"
                            f", not {want_model!r}"})
            return
        tr = _hop_trace(head)
        if tr is not None:
            tr.event("replica", "recv", op="generate", raw=raw)
        prefilled = None
        try:
            prio = head.get("priority")
            # Session label (docs/SERVING.md "KV tiering & sessions"):
            # with a KV tier attached, the batcher parks this request's
            # finished KV under the id and resumes a later turn from
            # it.  Malformed values cost the field, never the request.
            sid = head.get("session")
            req = Request(
                prompt=np.asarray(head.get("prompt"), np.int32),
                max_new_tokens=int(head.get("max_new_tokens") or 0),
                stop_token=head.get("stop_token"),
                priority=int(prio) if prio is not None else 0,
                deadline_ms=_deadline_ms(head),
                session_id=(str(sid) if isinstance(sid, str) and sid
                            else None))
            req.trace = tr      # the batcher records its events here
            send_partial = getattr(reply, "partial", None)
            if head.get("stream") and send_partial is not None:
                # Per-token incremental replies: the batcher's serve
                # loop flushes each decode block's new tokens through
                # this callback as ``op: tokens`` frames carrying their
                # stream OFFSET — the gateway (and a failover replay)
                # de-duplicates by it, and the final completion still
                # carries the full list, so non-streaming peers see no
                # difference (docs/SERVING.md "Front-door scaling").
                def on_tokens(toks, off, _mid=mid):
                    send_partial({"op": "tokens", "id": _mid,
                                  "off": int(off), "tokens": toks})

                req.on_tokens = on_tokens
            if raw:
                prefilled = serving_mod.unpack_prefilled(head, msg.body)
                batcher.validate(Prefilled(req, prefilled))
            elif staged_body is not None:
                prefilled = serving_mod.unpack_prefilled(head,
                                                         staged_body)
                batcher.validate(Prefilled(req, prefilled))
            else:
                # Reject un-servable requests NOW with an explicit
                # error — run()'s own invalid-request path raises only
                # after the stream drains, which would take the whole
                # replica down.
                batcher.validate(req)
        except (TypeError, ValueError, KeyError) as e:
            reply(_attach_trace(
                {"op": "error", "id": mid, "kind": "bad_request",
                 "error": str(e)}, tr, failed=True))
            return

        ckey = (getattr(reply, "conn_key", None), mid)
        with _inflight_lock:
            _inflight[ckey] = req

        def on_done(comp, err) -> None:
            with _inflight_lock:
                _inflight.pop(ckey, None)
            if comp is None:
                reply(_attach_trace(
                    {"op": "error", "id": mid, "kind": "internal",
                     "error": err or "request dropped"}, tr,
                    failed=True))
                return
            if isinstance(comp, Expired):
                # The batcher cancelled the row (deadline passed):
                # explicit, deterministic, and never retried — the
                # router treats deadline_exceeded as final.
                reply(_attach_trace(
                    {"op": "error", "id": mid,
                     "kind": "deadline_exceeded",
                     "error": "request deadline expired in the "
                              "batcher; row cancelled"}, tr,
                    failed=True))
                return
            if isinstance(comp, Suspended):
                # Model-catalog identity on the export: the router may
                # only resume this mid-stream KV on a replica serving
                # the SAME model and adapter delta.
                model_id = (model_state or {}).get("model_id") or ""
                adapter = getattr(batcher, "adapter_version", "")
                if comp.artifact is None:
                    out = {"op": "suspended", "id": mid, "requeue": True,
                           "gen": generation,
                           "weights_version": weights_version}
                    if model_id:
                        out["model_id"] = model_id
                    reply(_attach_trace(out, tr, failed=True))
                    return
                meta, body = serving_mod.pack_prefilled(comp.artifact)
                meta.update(op="suspended", id=mid, gen=generation,
                            weights_version=weights_version,
                            adapter_version=adapter)
                if model_id:
                    meta["model_id"] = model_id
                pt = _push_state["to"]
                if pt:
                    # Drain migration with a brokered survivor: stream
                    # the artifact peer-to-peer and hand the router only
                    # a small reference.  One bounded attempt — a failed
                    # push falls back to the relay frame below, so the
                    # fast path never costs correctness.
                    xfer = f"mig-{mid}"
                    smeta = dict(meta)
                    smeta.update(op="kv_stage", xfer=xfer)
                    ack = None
                    try:
                        ack = _push_stage(pt, smeta, body)
                    except (OSError, wire.WireError) as e:
                        log.warning("direct KV push of %s to %s failed:"
                                    " %s; relaying through the router",
                                    xfer, pt, e)
                    if isinstance(ack, dict) \
                            and ack.get("op") == "kv_staged":
                        out = {"op": "suspended", "id": mid,
                               "pushed": True, "xfer": xfer,
                               "push_to": pt, "bytes": len(body),
                               "gen": generation,
                               "weights_version": weights_version,
                               "adapter_version": adapter}
                        if model_id:
                            out["model_id"] = model_id
                        reply(_attach_trace(out, tr, failed=True))
                        return
                # A migration hop's spans always piggyback (failed=True
                # here just means "always export"): the router stitches
                # the victim's suspend into the one waterfall.
                _attach_trace(meta, tr, failed=True)
                reply(wire.RawFrame(meta, body))
                return
            reply(_attach_trace(
                {"op": "completion", "id": mid,
                 "tokens": [int(t) for t in comp.tokens],
                 "ttft_ms": round(comp.ttft_s * 1000.0, 3),
                 "total_ms": round(comp.total_s * 1000.0, 3)}, tr))

        serving.submit(req, on_done, prefilled=prefilled)

    return handler


def prefill_handler(batcher, max_queue: int = 8, token: str = "",
                    self_addr: Optional[Callable[[], str]] = None
                    ) -> Callable:
    """The prefill-role ``ReplicaServer`` handler: run the prompt
    through prefill only (``export_kv``) and stream the KV artifact
    back as ONE raw binary frame.  Prefill runs off the connection's
    reader thread so a mux peer can pipeline requests; admitted work
    drains through ONE worker thread off a bounded FIFO queue (exports
    serialize inside the batcher anyway, so extra threads would only
    pile up on its lock in unspecified wakeup order), and a full queue
    answers ``overloaded`` immediately — the router treats that as
    transient and retries another prefill replica or falls back.
    ``generate`` is refused — a prefill-role replica never decodes,
    which is what keeps its tier's admission latency flat."""
    import queue as _queue
    import time as _time

    import numpy as np

    from tfmesos_tpu import serving as serving_mod
    from tfmesos_tpu.serving import Request

    log = get_logger("tfmesos_tpu.fleet.replica")
    work_q: "_queue.Queue" = _queue.Queue(maxsize=max_queue)

    def drain() -> None:
        while True:
            req, mid, reply, t_enq, push = work_q.get()
            tr = getattr(req, "trace", None)
            if tr is not None:
                tr.add("replica", "prefill_queue", tr.rel_ms(t_enq),
                       (_time.perf_counter() - t_enq) * 1000.0)
            if req.expired:
                # The deadline passed while queued: shed without
                # burning a prompt's worth of prefill compute.
                batcher.deadline_cancels += 1
                reply(_attach_trace(
                    {"op": "error", "id": mid,
                     "kind": "deadline_exceeded",
                     "error": "request deadline expired in the "
                              "prefill queue"}, tr, failed=True))
                continue
            try:
                t0 = _time.perf_counter()
                art = batcher.export_kv(req)
                meta, body = serving_mod.pack_prefilled(art)
                prefill_ms = round(
                    (_time.perf_counter() - t0) * 1000.0, 3)
                meta.update(op="prefilled", id=mid,
                            prefill_ms=prefill_ms)
                if tr is not None:
                    tr.add("replica", "prefill_export", tr.rel_ms(t0),
                           prefill_ms)
                    _attach_trace(meta, tr)
                if push is not None:
                    # Direct disagg streaming: the router already
                    # picked the decode replica and brokered its addr;
                    # land the KV there as one kv_stage frame and hand
                    # the router only a small reference.  One bounded
                    # attempt — on any failure the full raw frame
                    # relays through the router exactly as before.
                    daddr, xfer = push
                    smeta = dict(meta)
                    smeta.update(op="kv_stage", xfer=xfer)
                    ack = None
                    try:
                        from tfmesos_tpu.fleet.kvtier import fabric_rpc

                        ack = fabric_rpc(
                            daddr, smeta, body, token=token,
                            timeout=30.0,
                            self_addr=self_addr() if self_addr else "")
                    except (OSError, wire.WireError) as e:
                        log.warning("direct KV push of %s to %s "
                                    "failed: %s; relaying through the "
                                    "router", xfer, daddr, e)
                    if isinstance(ack, dict) \
                            and ack.get("op") == "kv_staged":
                        out = {"op": "prefilled", "id": mid,
                               "pushed": True, "xfer": xfer,
                               "bytes": len(body),
                               "prefill_ms": prefill_ms}
                        if tr is not None:
                            _attach_trace(out, tr)
                        reply(out)
                        continue
                reply(wire.RawFrame(meta, body))
            except Exception as e:
                log.exception("prefill failed: %s", e)
                reply(_attach_trace(
                    {"op": "error", "id": mid, "kind": "internal",
                     "error": repr(e)}, tr, failed=True))

    threading.Thread(target=drain, name="replica-prefill",
                     daemon=True).start()

    def handler(msg, reply: Callable) -> None:
        raw = isinstance(msg, wire.RawFrame)
        head = msg.meta if raw else msg
        mid = head.get("id")
        if not raw and head.get("op") == "migrate":
            # Exports are synchronous — a prefill replica holds no
            # resident rows to suspend; ack so a tier-blind drain can
            # migrate every member the same way.
            reply({"op": "migrated", "id": mid})
            return
        if head.get("op") == "swap_adapter":
            # Prefill replicas compute KV with the weights too: an
            # adapter swap must land tier-wide.  No serve loop here,
            # so the fold applies synchronously under the export lock
            # (exports queue behind it).
            _handle_swap_adapter(batcher, msg, reply)
            return
        if raw or head.get("op") != "prefill":
            reply({"op": "error", "id": mid, "kind": "bad_request",
                   "error": "this replica serves only the prefill op "
                            "(role: prefill); route generate to a "
                            "decode or unified replica"})
            return
        tr = _hop_trace(head)
        if tr is not None:
            tr.event("replica", "recv", op="prefill")
        try:
            prio = head.get("priority")
            req = Request(
                prompt=np.asarray(head.get("prompt"), np.int32),
                max_new_tokens=int(head.get("max_new_tokens") or 0),
                stop_token=head.get("stop_token"),
                priority=int(prio) if prio is not None else 0,
                deadline_ms=_deadline_ms(head))
            req.trace = tr
            batcher.validate(req)
        except (TypeError, ValueError) as e:
            reply(_attach_trace(
                {"op": "error", "id": mid, "kind": "bad_request",
                 "error": str(e)}, tr, failed=True))
            return
        push = None
        pt, xf = head.get("push_to"), head.get("xfer")
        if isinstance(pt, str) and pt and isinstance(xf, str) and xf:
            push = (pt, xf)
        try:
            work_q.put_nowait((req, mid, reply, _time.perf_counter(),
                               push))
        except _queue.Full:
            reply(_attach_trace(
                {"op": "error", "id": mid, "kind": "overloaded",
                 "error": f"prefill queue full ({max_queue} pending)"},
                tr, failed=True))

    return handler


def fabric_handler(fabric, inner: Optional[Callable] = None) -> Callable:
    """Wrap a replica handler with the KV fabric's wire surface
    (docs/SERVING.md "Cross-host KV fabric"): ``kv_put`` lands a peer's
    replicated park, ``kv_fetch`` serves a peer's resume from this
    host's tier.  Everything else delegates to ``inner``; with no
    ``inner`` (a dedicated ``--role kv`` replica) other ops are refused
    — a KV holder never decodes.  Jax-free by construction, so the
    dedicated holder process never imports the model stack."""

    def handler(msg, reply: Callable) -> None:
        raw = isinstance(msg, wire.RawFrame)
        head = msg.meta if raw else msg
        op = head.get("op")
        mid = head.get("id")
        if op == "kv_put":
            if not raw:
                reply({"op": "error", "id": mid, "kind": "bad_request",
                       "error": "kv_put ships its artifact as a raw "
                                "frame"})
                return
            out = fabric.handle_put(msg)
            if isinstance(out, dict):
                out.setdefault("id", mid)
            reply(out)
            return
        if op == "kv_fetch":
            out = fabric.handle_fetch(head)
            if isinstance(out, wire.RawFrame):
                out.meta.setdefault("id", mid)
            elif isinstance(out, dict):
                out.setdefault("id", mid)
            reply(out)
            return
        if inner is not None:
            inner(msg, reply)
            return
        if op == "migrate":
            # A KV holder has no rows to suspend; ack so a tier-blind
            # drain completes the same way everywhere.
            reply({"op": "migrated", "id": mid})
            return
        reply({"op": "error", "id": mid, "kind": "bad_request",
               "error": f"this replica holds KV state only (role: "
                        f"kv); it does not serve {op!r}"})

    return handler


# -- model presets ----------------------------------------------------------


def tiny_model(seed: int = 0):
    """The CI model: deterministic from ``seed``, so a test (or a peer
    replica) can reproduce a replica's exact greedy outputs locally."""
    import jax
    import jax.numpy as jnp

    from tfmesos_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=97, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        max_seq_len=128, dtype=jnp.float32)
    return cfg, transformer.init_params(cfg, jax.random.PRNGKey(seed))


def flagship_model(seed: int = 0, max_len: int = 1024):
    """The flagship serving config (bench.py's 34M d512 transformer)."""
    import jax
    import jax.numpy as jnp

    from tfmesos_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=8192, d_model=512, n_layers=8, n_heads=8, d_ff=1408,
        max_seq_len=max_len, dtype=jnp.bfloat16)
    return cfg, transformer.init_params(cfg, jax.random.PRNGKey(seed))


def tiny_draft_model(seed: int = 5, max_len: int = 128, n_draft: int = 4):
    """The tiny model's DRAFT companion (speculative decoding):
    deterministic from ``seed`` with the tiny vocab, its max_seq_len
    covering the verify overshoot (max_len + n_draft + 1)."""
    import jax
    import jax.numpy as jnp

    from tfmesos_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=97, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        max_seq_len=max_len + n_draft + 1, dtype=jnp.float32)
    return cfg, transformer.init_params(cfg, jax.random.PRNGKey(seed))


def flagship_draft_model(seed: int = 1, max_len: int = 1024,
                         n_draft: int = 4):
    """The flagship's DRAFT companion: a ~16x-smaller transformer on
    the flagship vocab — cheap enough that a speculative round's k
    draft steps cost less than the target tokens they replace."""
    import jax
    import jax.numpy as jnp

    from tfmesos_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=8192, d_model=128, n_layers=2, n_heads=4, d_ff=352,
        max_seq_len=max_len + n_draft + 1, dtype=jnp.bfloat16)
    return cfg, transformer.init_params(cfg, jax.random.PRNGKey(seed))


# -- batcher assembly --------------------------------------------------------


def rid_seed_for_node(node: str) -> int:
    """Per-replica request-id stream base, derived from the fleet node
    id ("job:index").  Sampled draws are pure (rid, step) key folds, so
    two replicas whose rids collide would draw IDENTICAL sampling
    streams — cross-exporter sampled artifacts must never share one
    (the PR 4 caveat, now closed).  A 20-bit CRC of the node id shifted
    10 bits gives distinct nodes disjoint 1024-rid blocks, stays int32-
    safe with ~2^30 of increment headroom, and leaves the node-less
    (direct/test) replica at the historical 0 base."""
    if not node:
        return 0
    import zlib

    return (zlib.crc32(node.encode("utf-8")) & 0xFFFFF) << 10


def build_batcher(args, token: str, generation: int, node: str = "",
                  with_kv_tier: bool = True):
    """Assemble the model + ContinuousBatcher one serving process runs —
    shared by the single-process replica, the gang LEADER (which owns
    the gang's batcher), and gang MEMBERS (which mirror-execute with an
    identical build, minus the KV tier: parking a session N times over
    would corrupt the economy's accounting).  Split out of ``main()``
    so one process == one replica is an entry-point choice, not a
    structural assumption."""
    from tfmesos_tpu.serving import ContinuousBatcher

    build_seed = args.model_seed if args.model_seed is not None \
        else args.seed
    if args.tiny:
        cfg, params = tiny_model(build_seed)
    else:
        cfg, params = flagship_model(build_seed,
                                     max_len=args.max_len or 1024)
    draft_cfg = draft_params = None
    if args.draft:
        max_len = args.max_len or int(cfg.max_seq_len)
        if args.tiny:
            draft_cfg, draft_params = tiny_draft_model(
                max_len=max_len, n_draft=args.n_draft)
        else:
            draft_cfg, draft_params = flagship_draft_model(
                seed=args.seed + 1, max_len=max_len,
                n_draft=args.n_draft)
    kv_tier = None
    if with_kv_tier and (args.kv_tier_mb > 0 or args.kv_tier_dir):
        from tfmesos_tpu.fleet.kvtier import KVTierStore

        # The store is stamped with this replica's rollout identity:
        # a parked artifact from another weights_version (a pre-rollout
        # entry in a shared disk dir) reads as a miss, never stale KV.
        # The MODEL composes into the stamp — two models' replicas may
        # share one host disk tier, and a session parked by model A
        # must read as a version miss to model B, never as its KV.
        wv_stamp = args.weights_version
        if args.model_id:
            wv_stamp = f"{args.weights_version or 'v0'}@{args.model_id}"
        kv_tier = KVTierStore(
            ram_bytes=int(max(0.0, args.kv_tier_mb) * 1e6),
            disk_dir=args.kv_tier_dir, token=token,
            stamp={"weights_version": wv_stamp,
                   "gen": generation})
    return ContinuousBatcher(
        cfg, params, rows=args.rows, max_len=args.max_len,
        page_size=args.page_size, prefill_bucket=args.prefill_bucket,
        multi_step=args.multi_step,
        prefix_cache_pages=args.prefix_cache_pages,
        pipeline_depth=args.pipeline_depth, kv_tier=kv_tier,
        # Fused scheduling serves in chunked mode (the bucket doubles
        # as the chunk width — the batcher couples them anyway).
        prefill_chunk=(args.prefill_bucket if getattr(
            args, "fused_prefill", False) else None),
        fused_prefill=getattr(args, "fused_prefill", False),
        tokens_per_tick=getattr(args, "tokens_per_tick", None),
        draft_cfg=draft_cfg, draft_params=draft_params,
        n_draft=args.n_draft, rid_seed=rid_seed_for_node(node))


def _gang_member_main(args, token: str, spec, generation: int) -> int:
    """A gang MEMBER process (rank >= 1): no serve socket, no registry
    heartbeat — its whole life is the leader's dispatch loop (see
    :mod:`tfmesos_tpu.fleet.gang`).  Mirror-executes each dispatched
    request on an identical batcher build and acks the token digest;
    exits when the leader does (a gang lives and dies whole)."""
    from tfmesos_tpu.fleet import gang as gang_mod

    gid, size, rank = spec
    log = get_logger("tfmesos_tpu.fleet.gang")
    if not args.registry:
        print("gang member needs --registry for leader rendezvous",
              file=sys.stderr)
        return 2
    batcher = build_batcher(args, token, generation,
                            with_kv_tier=False)

    import numpy as np

    from tfmesos_tpu.serving import Request

    def execute(head) -> List[int]:
        req = Request(
            prompt=np.asarray(head.get("prompt"), np.int32),
            max_new_tokens=int(head.get("max_new_tokens") or 0),
            stop_token=head.get("stop_token"))
        comps = list(batcher.run([req]))
        return [int(t) for t in comps[0].tokens] if comps else []

    if args.warmup:
        info = batcher.warmup(decode=True, prefill=True)
        log.info("gang member rank %d warmed in %.1fs", rank,
                 info["seconds"])
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda s, f: stop.set())
    signal.signal(signal.SIGINT, lambda s, f: stop.set())
    member = gang_mod.GangMember(gid, size, rank, generation,
                                 args.registry, token=token,
                                 execute=execute)
    print(f"gang member rank {rank}/{size} serving gang {gid}",
          flush=True)
    reason = member.run(stop)
    log.info("gang member rank %d exiting: %s (%d served)", rank,
             reason, member.served)
    return 0 if reason == "stopped" else 1


def _kv_holder_main(args, token: str, generation: int,
                    node: str = "") -> int:
    """A dedicated ``--role kv`` replica: a bare KV tier behind the
    replica wire surface — no model, no batcher, no JAX import.  Its
    whole job is holding other replicas' parked artifacts (fabric
    pushes land here first, and resumes fetch from here), so a fleet
    can scale its serving replicas to zero without losing one parked
    session (docs/SERVING.md "Cross-host KV fabric")."""
    from tfmesos_tpu.fleet.kvtier import KVFabric, KVTierStore

    log = get_logger("tfmesos_tpu.fleet.replica")
    if args.kv_tier_mb <= 0 and not args.kv_tier_dir:
        print("--role kv needs a tier to hold (--kv-tier-mb and/or "
              "--kv-tier-dir)", file=sys.stderr)
        return 2
    # An EMPTY stamp on purpose: the holder stores many replicas'
    # artifacts verbatim (kv_put installs without re-stamping) and must
    # never fence a read by its OWN identity — fencing belongs to the
    # importer, which judges the original writer's stamp.
    store = KVTierStore(ram_bytes=int(max(0.0, args.kv_tier_mb) * 1e6),
                        disk_dir=args.kv_tier_dir, token=token,
                        stamp={})
    fabric = KVFabric(store, token=token, registry_addr=args.registry,
                      replication=1, placement=args.kv_placement)
    handler = fabric_handler(fabric)

    def extra() -> Dict[str, Any]:
        beat: Dict[str, Any] = {"role": "kv", "gen": generation,
                                "kv_tier": store.summary()}
        if args.weights_version:
            beat["weights_version"] = args.weights_version
        if node:
            beat["node"] = node
        return beat

    server = ReplicaServer(
        handler, token=token, capacity=0, host=args.host,
        port=args.port, registry_addr=args.registry,
        heartbeat_interval=args.heartbeat_interval, extra_info=extra)
    server.start()
    fabric.self_addr = server.addr or ""
    print(f"replica serving on {server.addr} (role kv)", flush=True)
    stop = threading.Event()

    def on_signal(signum, frame) -> None:
        log.info("signal %d: draining", signum)
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    stop.wait()
    server.stop()
    return 0


# -- process entry ----------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tfmesos_tpu.fleet.replica",
        description="One fleet serving replica: a ContinuousBatcher "
                    "behind an authenticated TCP server.")
    p.add_argument("--registry", type=str, default=None,
                   help="registry host:port to heartbeat (none = serve "
                        "unregistered, for direct testing)")
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="listen port (0 = OS-assigned)")
    p.add_argument("--rows", type=int, default=4,
                   help="concurrent decode rows (= advertised capacity)")
    p.add_argument("--max-len", type=int, default=None)
    p.add_argument("--page-size", type=int, default=64)
    p.add_argument("--prefill-bucket", type=int, default=64)
    p.add_argument("--multi-step", type=int, default=1)
    p.add_argument("--fused-prefill", action="store_true",
                   dest="fused_prefill",
                   help="stall-free fused scheduling: serve in chunked-"
                        "prefill mode (chunk width = --prefill-bucket) "
                        "with each tick's chunk slots fused into the "
                        "SAME device dispatch as the decode block, so "
                        "decoding rows never stall behind a long "
                        "prompt's prefill (docs/SERVING.md 'Stall-free "
                        "fused scheduling'); modes the fused program "
                        "cannot cover fall back with a recorded "
                        "bypass reason")
    p.add_argument("--tokens-per-tick", type=int, default=None,
                   dest="tokens_per_tick",
                   help="fused tick token budget (default: rows x "
                        "multi_step + one chunk): decode rows spend "
                        "multi_step each, the leftover coalesces "
                        "still-filling rows' chunks into the dispatch")
    p.add_argument("--prefix-cache-pages", type=int, default=0,
                   help="cross-request prefix cache budget in pool pages "
                        "per mesh data shard (0 disables); cached "
                        "summaries are advertised on registry heartbeats "
                        "for prefix-affinity routing")
    p.add_argument("--kv-tier-mb", type=float, default=0.0,
                   dest="kv_tier_mb",
                   help="host-RAM KV tier budget in MB (0 disables): "
                        "prefix pages evicted from the device pool "
                        "spill here (promoting back on the next hit) "
                        "and session-labeled requests park their KV "
                        "between turns (docs/SERVING.md 'KV tiering & "
                        "sessions')")
    p.add_argument("--kv-tier-dir", type=str, default=None,
                   dest="kv_tier_dir",
                   help="disk tier directory (default: none — RAM "
                        "only); RAM-evicted entries spill into "
                        "HMAC-framed files, and replicas of one host "
                        "sharing the directory can resume each "
                        "other's parked sessions (bounded at 4x the "
                        "RAM budget)")
    p.add_argument("--role", choices=("unified", "prefill", "decode",
                                      "kv"),
                   default="unified",
                   help="serving role: 'unified' (default) serves whole "
                        "requests; 'prefill' only runs prompts through "
                        "prefill and exports their KV pages; 'decode' "
                        "additionally imports exported KV and enters "
                        "rows straight into decode (disaggregated "
                        "serving, docs/SERVING.md); 'kv' serves NO "
                        "model at all — a jax-free dedicated holder "
                        "for the cross-host KV fabric's replicated "
                        "parks (needs a tier via --kv-tier-mb/-dir)")
    p.add_argument("--kv-replication", type=int, default=1,
                   dest="kv_replication",
                   help="K-way replicated session parking (default 1 = "
                        "local only): a park lands on this replica "
                        "PLUS K-1 fabric peers before it counts as "
                        "replicated, so a parked session survives "
                        "SIGKILL of its parking host and resumes from "
                        "a surviving copy (docs/SERVING.md 'Cross-host "
                        "KV fabric'); needs --registry and a KV tier")
    p.add_argument("--kv-placement", choices=("rendezvous", "loaded"),
                   default="rendezvous", dest="kv_placement",
                   help="fabric peer choice for replicated parks: "
                        "'rendezvous' (default) is pure hash-ordered "
                        "(deterministic, ignores load); 'loaded' "
                        "re-scores the rendezvous candidates by their "
                        "heartbeat KV-tier occupancy so parks avoid "
                        "peers whose tiers are nearly full "
                        "(docs/SERVING.md 'Cross-host KV fabric')")
    p.add_argument("--pipeline-depth", type=int, default=0,
                   choices=(0, 1), dest="pipeline_depth",
                   help="1 pipelines the decode loop with a device-"
                        "resident carry: block N+1 dispatches from the "
                        "previous block's on-device outputs and block "
                        "N's tokens sync one block behind — token "
                        "streams identical to 0 (the default, fully "
                        "synchronous; docs/SERVING.md)")
    p.add_argument("--draft", action="store_true",
                   help="serve with a DRAFT model (speculative "
                        "decoding): each tick the draft proposes "
                        "--n-draft tokens and the target verifies them "
                        "in one chunk, so a row commits 1..n+1 tokens "
                        "per dispatch; composes with the prefix cache, "
                        "KV export/import, preemption/migration, and "
                        "the KV tier, and the acceptance rate rides "
                        "heartbeats into the gateway's 'spec' gauge")
    p.add_argument("--n-draft", type=int, default=4, dest="n_draft",
                   help="draft proposals per speculative round "
                        "(with --draft)")
    p.add_argument("--warmup", action="store_true",
                   help="compile every jitted serving entry point at "
                        "boot (ContinuousBatcher.warmup) before taking "
                        "traffic; the replica registers as 'warming' — "
                        "never routed — and flips itself alive when "
                        "warmup returns, so a relaunch re-warms before "
                        "its first request pays a compile")
    p.add_argument("--tiny", action="store_true",
                   help="serve the tiny CI model instead of the flagship")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--heartbeat-interval", type=float, default=0.3)
    p.add_argument("--model-id", type=str, default="",
                   dest="model_id",
                   help="model-catalog identity this replica serves "
                        "(rides every heartbeat — the router's "
                        "per-model tier keys off it); charset-"
                        "validated like --weights-version "
                        "(docs/SERVING.md 'Model catalog')")
    p.add_argument("--model-seed", type=int, default=None,
                   dest="model_seed",
                   help="weight seed of the catalog model (default: "
                        "--seed); two catalog entries with different "
                        "seeds ARE different models")
    p.add_argument("--warm-pool", action="store_true",
                   dest="warm_pool",
                   help="register as an UNDEDICATED warm-pool member: "
                        "pre-warmed and alive but excluded from every "
                        "router pick until the fleet's model trader "
                        "assigns a model via the 'adopt' control op "
                        "(a weight install — no relaunch, no "
                        "recompile)")
    p.add_argument("--weights-version", type=str, default="",
                   dest="weights_version",
                   help="weights version label this replica serves; "
                        "rides the registry hello and every heartbeat "
                        "so the router's version-preference tier and "
                        "the blue-green rollout can tell generations "
                        "of the model apart (docs/SERVING.md)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    token = wire.load_token()
    log = get_logger("tfmesos_tpu.fleet.replica")

    # Control-plane identity, both from the Mode-B task env contract:
    # the launch generation (PR 3's fencing epoch — the registry drops
    # beats of reaped rollout generations) and the scheduler-side task
    # name ("job:index"), which is how the autoscaler maps this
    # replica's registry entry back to a killable task.
    try:
        generation = int(os.environ.get("TPUMESOS_GENERATION", "0") or 0)
    except ValueError:
        generation = 0
    job = os.environ.get("TPUMESOS_JOB_NAME", "")
    idx = os.environ.get("TPUMESOS_TASK_INDEX", "")
    node = f"{job}:{idx}" if job and idx != "" else ""

    if not 1 <= args.kv_replication <= 8:
        print("replica: --kv-replication must be in [1, 8]",
              file=sys.stderr)
        return 2
    if args.role == "kv":
        # Dedicated fabric holder: jax-free, no batcher build at all.
        return _kv_holder_main(args, token, generation, node)

    # Gang identity (docs/SERVING.md "Gang replicas"): when this
    # process was launched as one task of an N-task gang, rank 0 is
    # the LEADER — the one process that owns the fleet identity below —
    # and every other rank is a member whose whole life is the leader's
    # dispatch loop.
    from tfmesos_tpu.fleet import gang as gang_mod

    gang_spec = gang_mod.read_gang_env()
    if gang_spec is not None and gang_spec[2] > 0:
        return _gang_member_main(args, token, gang_spec, generation)

    # Model-catalog identity: --model-id names the catalog entry this
    # replica serves (seeded by --model-seed), --warm-pool starts it
    # UNDEDICATED (default weights, adopted later).  The id is
    # charset-validated here too — every ingress is a boundary, and
    # argv arrived through a shell=True command line.
    if args.model_id:
        from tfmesos_tpu.fleet.registry import validate_model_id

        try:
            args.model_id = validate_model_id(args.model_id)
        except ValueError as e:
            print(f"replica: {e}", file=sys.stderr)
            return 2
    model_state: Dict[str, Any] = {
        "model_id": args.model_id or "",
        "warm_pool": bool(args.warm_pool),
        "pool_capable": bool(args.warm_pool),
    }
    batcher = build_batcher(args, token, generation, node=node)

    # The fabric face of the local KV tier (docs/SERVING.md "Cross-host
    # KV fabric"): replicated parks and locate-driven peer fetch on
    # miss.  The batcher's tier reference is swapped for the wrapper —
    # every park/resume from here on goes through the fabric, and the
    # replica additionally serves kv_put/kv_fetch for its peers.
    fabric = None
    srv_cell: List[Any] = []
    if args.registry and batcher.kv_tier is not None \
            and batcher.kv_tier_bypass_reason is None:
        from tfmesos_tpu.fleet.kvtier import KVFabric

        fabric = KVFabric(batcher.kv_tier, token=token,
                          registry_addr=args.registry,
                          replication=args.kv_replication,
                          placement=args.kv_placement)
        batcher.kv_tier = fabric

    def adopt_fn(head, reply) -> None:
        """The ``adopt`` control op: install one catalog model's
        weights on this (pre-warmed, undedicated) replica.  Same
        preset family and max_len as the boot build, so shapes are
        identical and nothing recompiles — the whole point of the
        warm pool."""
        from tfmesos_tpu.fleet.registry import validate_model_id

        mid = head.get("id")
        try:
            model_id = validate_model_id(head.get("model_id"))
            seed = int(head.get("seed") or 0)
        except (TypeError, ValueError) as e:
            reply({"op": "error", "id": mid, "kind": "bad_request",
                   "error": str(e)})
            return
        # Adoption is a WARM-POOL-ONLY transition: a replica already
        # serving (or mid-install for) a model refuses — the trader's
        # pool view is heartbeat-lagged, so two rapid cold starts
        # could otherwise hand one pool member to BOTH models, and
        # reassigning a dedicated replica would serve wrong_model
        # errors until the identity flip rides a beat.  The refusal
        # makes the trader fall through to the next candidate (or a
        # cold launch).
        if model_state["model_id"] or model_state.get("adopting"):
            reply({"op": "error", "id": mid, "kind": "bad_request",
                   "error": f"already serving model "
                            f"{model_state['model_id'] or '(adopting)'!r}"
                            f"; adoption is a warm-pool-only "
                            f"transition"})
            return
        model_state["adopting"] = True
        if args.tiny:
            _, new_params = tiny_model(seed)
        else:
            _, new_params = flagship_model(seed,
                                           max_len=args.max_len or 1024)

        def applied() -> None:
            model_state["model_id"] = model_id
            model_state["warm_pool"] = False
            model_state["adopting"] = False
            log.info("adopted model %s (seed %d)", model_id, seed)
            reply({"op": "adopted", "id": mid, "model_id": model_id})

        batcher.set_weights(
            new_params,
            version=f"{args.weights_version or 'v0'}@{model_id}",
            on_applied=applied)

    def _self_addr() -> str:
        # Late-bound: the server (and its addr) exist only after the
        # handler is built.  Used to tag direct-push sockets so chaos
        # partition faults can match the peer pair.
        return srv_cell[0].addr or "" if srv_cell else ""

    serving = None
    if args.role == "prefill":
        # Prefill-role replicas never decode: no serve loop runs, the
        # handler drives export_kv directly (exports borrow rows).
        handler = prefill_handler(batcher, token=token,
                                  self_addr=_self_addr)
    else:
        # NOT started yet: warmup must run before the serve loop owns
        # the rows; submissions made while warming just queue.
        serving = BatcherServing(batcher)
        handler = batcher_handler(serving, generation=generation,
                                  weights_version=args.weights_version,
                                  model_state=model_state,
                                  adopt_fn=adopt_fn, token=token,
                                  self_addr=_self_addr)

    stop = threading.Event()
    leader = None
    if gang_spec is not None:
        # Rank 0 leads: it owns the batcher, the serve socket, and the
        # registry heartbeat; the gang coordination server fans each
        # generate to the members and verifies their token digests.  A
        # member loss breaks the gang — stop fires, the process exits,
        # and the fleet tears down and re-forms the gang whole.
        if args.role == "prefill":
            print("gang replicas serve the decode/unified path; "
                  "--role prefill cannot lead a gang", file=sys.stderr)
            return 2
        leader = gang_mod.GangLeader(
            gang_spec[0], gang_spec[1], generation=generation,
            token=token, host=args.host,
            on_break=lambda rank: stop.set())
        leader.start()
        handler = gang_mod.leader_handler(handler, leader)
    if fabric is not None:
        # Outside the gang wrap on purpose: a kv_put/kv_fetch is a
        # host-local tier operation, never gang-dispatched.
        handler = fabric_handler(fabric, handler)

    def extra() -> Dict[str, Any]:
        # Heartbeat advert: the tier this replica belongs to and its
        # live KV headroom (decode-tier routing places imports by it),
        # the rollout identity (weights_version + launch generation +
        # task node), plus the prefix-cache summary when one runs.
        beat: Dict[str, Any] = {"role": args.role,
                                "kv_headroom": batcher.kv_headroom(),
                                "gen": generation}
        if args.weights_version:
            beat["weights_version"] = args.weights_version
        if node:
            beat["node"] = node
        # Model-catalog identity: the served model (set at launch, or
        # by a later adoption), warm-pool membership (always sent once
        # pool-capable, so an adoption's False overwrites the table's
        # True), and the last adapter delta folded in.
        if model_state["model_id"]:
            beat["model_id"] = model_state["model_id"]
        if model_state["pool_capable"]:
            beat["warm_pool"] = bool(model_state["warm_pool"])
        # Sent even when "" — a fold followed by a full weight swap
        # resets it, and the table must follow, not keep the old label.
        beat["adapter_version"] = getattr(batcher, "adapter_version",
                                          "")
        if batcher.prefix_cache_active:
            beat["prefix_cache"] = batcher.prefix_cache_summary()
        if batcher.kv_tier is not None \
                and batcher.kv_tier_bypass_reason is None:
            # Tier summary: parked session ids (the router's session-
            # affinity key), spilled prefix digests (tier-resident
            # affinity), counters and occupancy for the fleet gauge.
            beat["kv_tier"] = batcher.kv_tier.summary()
        if batcher.d_side is not None:
            # Speculative health: the draft acceptance rate (None
            # before the first round) plus the raw sums the registry's
            # spec_summary() re-aggregates fleet-wide.
            beat["spec"] = {
                "acceptance_rate": batcher.acceptance_rate,
                "rounds": batcher.spec_rounds,
                "row_rounds": batcher.spec_row_rounds,
                "committed": batcher.spec_committed,
                "n_draft": batcher.n_draft,
            }
        if leader is not None:
            # Gang identity + member liveness: what role_summary / the
            # gangs gauge report, and what gang_lookup serves booting
            # members (the registry-mediated rendezvous).
            beat["gang"] = leader.gang_info()
        return beat

    server = ReplicaServer(
        handler, token=token, capacity=args.rows,
        host=args.host, port=args.port, registry_addr=args.registry,
        heartbeat_interval=args.heartbeat_interval, extra_info=extra,
        status="warming" if (args.warmup or leader is not None)
        else None)
    srv_cell.append(server)
    # Register (as warming with --warmup) BEFORE compiling: the fleet's
    # bring-up accounting sees the replica exists while the router
    # cannot yet pick it, and a relaunched replica is visibly re-warming
    # instead of silently absent.
    server.start()
    if fabric is not None:
        fabric.self_addr = server.addr or ""
    if args.warmup:
        # Role replicas warm only the surface they serve: a prefill
        # replica never decodes, a decode replica never prefills (it
        # imports exported KV) — compiling the other role's per-width
        # executables would only lengthen the warming window re-paid on
        # every elastic/Mode-B relaunch.
        info = batcher.warmup(decode=(args.role != "prefill"),
                              prefill=(args.role != "decode"))
        log.info("warmup compiled %s in %.1fs", info["compiled"],
                 info["seconds"])
        print(f"replica warmed in {info['seconds']:.1f}s "
              f"({len(info['compiled'])} entry points)", flush=True)
    if serving is not None:
        serving.start()
    if leader is not None:
        # Never routed while forming: the leader stays 'warming' until
        # every member has joined.  A gang that cannot form exits
        # nonzero — the scheduler reports the death and the fleet
        # re-forms the gang whole rather than serving degraded.
        if not leader.wait_formed(timeout=300.0) or leader.broken:
            log.error("gang %s never formed (%d/%d live); exiting",
                      leader.gang_id, leader.live, leader.size)
            server.stop()
            leader.stop()
            return 1
        print(f"gang {leader.gang_id} formed "
              f"({leader.size} members, generation {generation})",
              flush=True)
    server.set_status(None)     # routable: the next beat drops 'warming'
    print(f"replica serving on {server.addr} (role {args.role})",
          flush=True)

    def on_signal(signum, frame) -> None:
        log.info("signal %d: draining", signum)
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    stop.wait()
    broken = leader is not None and leader.broken
    server.stop()
    if leader is not None:
        leader.stop()
    if serving is not None:
        serving.close()
    # A gang break exits nonzero: the death must read as a failure to
    # the scheduler's dynamic accounting, not a graceful finish.
    return 1 if broken else 0


if __name__ == "__main__":
    sys.exit(main())
