"""Workloads for the fleet simulator: synthesized and trace-replayed.

Two sources feed :mod:`tfmesos_tpu.fleet.sim` (docs/SIMULATOR.md):

* :class:`SyntheticWorkload` — a seeded generator of request arrivals:
  a Poisson (or fixed-interval) arrival process, lognormal prompt and
  decode-length distributions, a weighted priority-class mix (tenant
  skew is just an uneven mix), and optional per-request deadlines.
  Same seed, same stream — byte-for-byte, which is what makes every
  simulator scenario a deterministic regression gate.

* :func:`replay_from_traces` — a recorded ``tfserve trace -g GW
  --json`` export replayed as a workload: each retained trace record
  becomes one request, re-arriving at its recorded wall-clock offset
  with its recorded class and token counts, and
  :func:`fit_replica_model` distills the records' per-hop timings
  (TTFT, decode tail) into the latency-model parameters the simulated
  replicas run on.  The replay is an arrival/shape replay, not a
  byte-level one — see docs/SIMULATOR.md "Fidelity contract" for what
  is and is not preserved.

Everything here is stdlib-only and jax-free, like the rest of the
control plane.
"""

from __future__ import annotations

import json
import random
from typing import Any, Dict, Iterable, Iterator, List, NamedTuple, Optional

__all__ = ["Request", "SyntheticWorkload", "replay_from_traces",
           "fit_replica_model", "load_trace_export"]


class Request(NamedTuple):
    """One simulated arrival.  ``at`` is the absolute virtual-clock
    arrival time in seconds (ignored by closed-loop drivers);
    ``cls`` is the priority-class label (None = the default class).
    ``session`` labels a multi-turn conversation — the sim's KV-tier
    model resumes a later turn from the parked coverage, like the real
    fleet's ``tfserve submit --session`` (docs/SERVING.md)."""

    at: float
    cls: Optional[str]
    prompt_len: int
    new_tokens: int
    deadline_ms: Optional[float] = None
    session: Optional[str] = None
    #: model-catalog label (docs/SERVING.md "Model catalog"): the
    #: sim's gateway analog stamps it onto the forward like the real
    #: one, so the router's per-model tier and the trader's per-model
    #: pressure signals run in simulation too.  None = the default.
    model: Optional[str] = None


def _clamped_lognormal(rng: random.Random, median: float, sigma: float,
                       lo: int, hi: int) -> int:
    if median <= 0:
        return lo
    v = rng.lognormvariate(0.0, sigma) * median if sigma > 0 else median
    return max(lo, min(hi, int(round(v))))


class SyntheticWorkload:
    """Seeded arrival stream (iterable of :class:`Request`).

    ``rate`` is mean arrivals/second of virtual time: Poisson
    (exponential gaps) by default, fixed-interval with
    ``deterministic=True``.  ``class_mix`` maps class label ->
    relative weight of TRAFFIC (distinct from the class's WFQ service
    weight — a background tenant may emit 10x the traffic of the
    interactive one precisely to test that WFQ holds); ``None`` labels
    ride the fleet's default class.
    """

    def __init__(self, n_requests: int, rate: float, seed: int = 0,
                 class_mix: Optional[Dict[Optional[str], float]] = None,
                 prompt_len: int = 64, prompt_sigma: float = 0.5,
                 new_tokens: int = 16, new_tokens_sigma: float = 0.5,
                 max_prompt_len: int = 2048, max_new_tokens: int = 512,
                 deadline_ms: Optional[float] = None,
                 deterministic: bool = False, start_at: float = 0.0,
                 model: Optional[str] = None):
        if n_requests < 1:
            raise ValueError(f"n_requests must be >= 1, got {n_requests}")
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        self.n_requests = int(n_requests)
        self.rate = float(rate)
        self.seed = int(seed)
        mix = class_mix or {None: 1.0}
        total = float(sum(mix.values()))
        if total <= 0:
            raise ValueError(f"class_mix weights must sum > 0: {mix}")
        self._labels = list(mix)
        self._weights = [mix[k] / total for k in self._labels]
        self.prompt_len = int(prompt_len)
        self.prompt_sigma = float(prompt_sigma)
        self.new_tokens = int(new_tokens)
        self.new_tokens_sigma = float(new_tokens_sigma)
        self.max_prompt_len = int(max_prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_ms = deadline_ms
        self.deterministic = bool(deterministic)
        self.start_at = float(start_at)
        self.model = model

    def __iter__(self) -> Iterator[Request]:
        rng = random.Random(self.seed)
        t = self.start_at
        gap = 1.0 / self.rate
        for _ in range(self.n_requests):
            t += gap if self.deterministic else rng.expovariate(self.rate)
            cls = rng.choices(self._labels, weights=self._weights)[0]
            yield Request(
                at=t, cls=cls,
                prompt_len=_clamped_lognormal(
                    rng, self.prompt_len, self.prompt_sigma, 1,
                    self.max_prompt_len),
                new_tokens=_clamped_lognormal(
                    rng, self.new_tokens, self.new_tokens_sigma, 1,
                    self.max_new_tokens),
                deadline_ms=self.deadline_ms, model=self.model)


# -- trace replay ------------------------------------------------------------


def load_trace_export(path: str) -> List[dict]:
    """Parse a ``tfserve trace -g GW --json`` export file: either one
    JSON array or JSON-lines, each element a trace record dict."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read().strip()
    if not text:
        return []
    if text[0] == "[":
        records = json.loads(text)
    else:
        records = [json.loads(line) for line in text.splitlines() if line]
    return [r for r in records if isinstance(r, dict)]


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def replay_from_traces(records: Iterable[dict],
                       speedup: float = 1.0,
                       deadline_ms: Optional[float] = None
                       ) -> List[Request]:
    """Turn trace records into a replayable arrival list: each record
    re-arrives at its recorded wall-clock offset (``ts``, compressed
    by ``speedup``) with its recorded class and token counts.  Records
    are replayed in timestamp order; the first arrival lands at t=0.
    Prompt length comes from the retained ``gateway.recv`` span when
    the record kept detail, else a small default — the export's
    summary records carry class/latency/tokens but not the prompt."""
    rows = []
    for rec in records:
        ts = _num(rec.get("ts"))
        if ts is None:
            continue
        summary = rec.get("summary") or {}
        cls = summary.get("cls")
        tokens = _num(summary.get("tokens"))
        prompt_len = None
        for span in rec.get("spans") or ():
            if isinstance(span, dict) and span.get("name") == "recv":
                prompt_len = _num(span.get("prompt_len"))
                break
        rows.append((ts, cls if isinstance(cls, str) else None,
                     int(prompt_len) if prompt_len else 16,
                     int(tokens) if tokens and tokens > 0 else 8))
    rows.sort(key=lambda r: r[0])
    if not rows:
        return []
    t0 = rows[0][0]
    scale = 1.0 / max(1e-9, float(speedup))
    return [Request(at=(ts - t0) * scale, cls=cls, prompt_len=pl,
                    new_tokens=nt, deadline_ms=deadline_ms)
            for ts, cls, pl, nt in rows]


def fit_replica_model(records: Iterable[dict]) -> Dict[str, Any]:
    """Distill recorded traces into latency-model parameters for the
    simulated replicas: median TTFT (the prefill estimate) and median
    per-token decode time, from completed records carrying ``ttft_ms``
    + ``total_ms`` + a token count.  Returns a possibly-empty dict of
    ``{"prefill_base_ms", "decode_ms_per_token"}`` — callers lay the
    fitted values over :class:`tfmesos_tpu.fleet.sim.ReplicaModel`
    defaults and keep whatever the traces could not determine."""
    ttfts: List[float] = []
    per_tok: List[float] = []
    for rec in records:
        if not isinstance(rec, dict) or rec.get("status") != "completed":
            continue
        summary = rec.get("summary") or {}
        ttft = _num(summary.get("ttft_ms"))
        total = _num(rec.get("total_ms"))
        tokens = _num(summary.get("tokens"))
        if ttft is not None and ttft >= 0:
            ttfts.append(ttft)
        if total is not None and ttft is not None and tokens \
                and tokens > 0 and total > ttft:
            per_tok.append((total - ttft) / tokens)
    out: Dict[str, Any] = {}
    if ttfts:
        ttfts.sort()
        out["prefill_base_ms"] = round(ttfts[len(ttfts) // 2], 3)
    if per_tok:
        per_tok.sort()
        out["decode_ms_per_token"] = round(per_tok[len(per_tok) // 2], 3)
    return out
