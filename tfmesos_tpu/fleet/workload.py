"""Workloads for the fleet simulator: synthesized and trace-replayed.

Two sources feed :mod:`tfmesos_tpu.fleet.sim` (docs/SIMULATOR.md):

* :class:`SyntheticWorkload` — a seeded generator of request arrivals:
  a Poisson (or fixed-interval) arrival process, lognormal prompt and
  decode-length distributions, a weighted priority-class mix (tenant
  skew is just an uneven mix), and optional per-request deadlines.
  Same seed, same stream — byte-for-byte, which is what makes every
  simulator scenario a deterministic regression gate.

* :func:`replay_from_traces` — a recorded ``tfserve trace -g GW
  --json`` export replayed as a workload: each retained trace record
  becomes one request, re-arriving at its recorded wall-clock offset
  with its recorded class and token counts, and
  :func:`fit_replica_model` distills the records' per-hop timings
  (TTFT, decode tail) into the latency-model parameters the simulated
  replicas run on.  The replay is an arrival/shape replay, not a
  byte-level one — see docs/SIMULATOR.md "Fidelity contract" for what
  is and is not preserved.

Everything here is stdlib-only and jax-free, like the rest of the
control plane.
"""

from __future__ import annotations

import bisect
import json
import math
import random
from typing import Any, Dict, Iterable, Iterator, List, NamedTuple, Optional

__all__ = ["Request", "SyntheticWorkload", "DiurnalWorkload",
           "replay_from_traces", "fit_replica_model", "fit_diurnal",
           "load_trace_export"]


class Request(NamedTuple):
    """One simulated arrival.  ``at`` is the absolute virtual-clock
    arrival time in seconds (ignored by closed-loop drivers);
    ``cls`` is the priority-class label (None = the default class).
    ``session`` labels a multi-turn conversation — the sim's KV-tier
    model resumes a later turn from the parked coverage, like the real
    fleet's ``tfserve submit --session`` (docs/SERVING.md)."""

    at: float
    cls: Optional[str]
    prompt_len: int
    new_tokens: int
    deadline_ms: Optional[float] = None
    session: Optional[str] = None
    #: model-catalog label (docs/SERVING.md "Model catalog"): the
    #: sim's gateway analog stamps it onto the forward like the real
    #: one, so the router's per-model tier and the trader's per-model
    #: pressure signals run in simulation too.  None = the default.
    model: Optional[str] = None


def _clamped_lognormal(rng: random.Random, median: float, sigma: float,
                       lo: int, hi: int) -> int:
    if median <= 0:
        return lo
    v = rng.lognormvariate(0.0, sigma) * median if sigma > 0 else median
    return max(lo, min(hi, int(round(v))))


class SyntheticWorkload:
    """Seeded arrival stream (iterable of :class:`Request`).

    ``rate`` is mean arrivals/second of virtual time: Poisson
    (exponential gaps) by default, fixed-interval with
    ``deterministic=True``.  ``class_mix`` maps class label ->
    relative weight of TRAFFIC (distinct from the class's WFQ service
    weight — a background tenant may emit 10x the traffic of the
    interactive one precisely to test that WFQ holds); ``None`` labels
    ride the fleet's default class.
    """

    def __init__(self, n_requests: int, rate: float, seed: int = 0,
                 class_mix: Optional[Dict[Optional[str], float]] = None,
                 prompt_len: int = 64, prompt_sigma: float = 0.5,
                 new_tokens: int = 16, new_tokens_sigma: float = 0.5,
                 max_prompt_len: int = 2048, max_new_tokens: int = 512,
                 deadline_ms: Optional[float] = None,
                 deadline_exempt: Optional[Iterable[str]] = None,
                 deterministic: bool = False, start_at: float = 0.0,
                 model: Optional[str] = None):
        if n_requests < 1:
            raise ValueError(f"n_requests must be >= 1, got {n_requests}")
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        self.n_requests = int(n_requests)
        self.rate = float(rate)
        self.seed = int(seed)
        mix = class_mix or {None: 1.0}
        total = float(sum(mix.values()))
        if total <= 0:
            raise ValueError(f"class_mix weights must sum > 0: {mix}")
        self._labels = list(mix)
        self._weights = [mix[k] / total for k in self._labels]
        self.prompt_len = int(prompt_len)
        self.prompt_sigma = float(prompt_sigma)
        self.new_tokens = int(new_tokens)
        self.new_tokens_sigma = float(new_tokens_sigma)
        self.max_prompt_len = int(max_prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_ms = deadline_ms
        # Classes whose arrivals carry NO deadline even when
        # ``deadline_ms`` is set — the batch-class semantics (the
        # offline lane is deadline-less by convention; its work waits
        # out interactive bursts instead of being shed).
        self.deadline_exempt = frozenset(deadline_exempt or ())
        self.deterministic = bool(deterministic)
        self.start_at = float(start_at)
        self.model = model

    def __iter__(self) -> Iterator[Request]:
        rng = random.Random(self.seed)
        t = self.start_at
        gap = 1.0 / self.rate
        for _ in range(self.n_requests):
            t += gap if self.deterministic else rng.expovariate(self.rate)
            cls = rng.choices(self._labels, weights=self._weights)[0]
            yield Request(
                at=t, cls=cls,
                prompt_len=_clamped_lognormal(
                    rng, self.prompt_len, self.prompt_sigma, 1,
                    self.max_prompt_len),
                new_tokens=_clamped_lognormal(
                    rng, self.new_tokens, self.new_tokens_sigma, 1,
                    self.max_new_tokens),
                deadline_ms=(None if cls in self.deadline_exempt
                             else self.deadline_ms),
                model=self.model)


class DiurnalWorkload:
    """Seeded DIURNAL arrival stream (iterable of :class:`Request`):
    a non-homogeneous Poisson process whose instantaneous rate rides a
    sinusoidal day/night envelope plus optional seeded burst spikes —
    the traffic shape a planet-scale front door actually sees, where a
    steady-``rate`` stream would flatter every saturation number.

    The rate at virtual time ``t`` is::

        rate(t) = base_rate * envelope(t) * burst(t)
        envelope(t) = 1 + (peak_ratio - 1) *
                      (0.5 + 0.5 * sin(2*pi*t/period_s + phase))

    so traffic swings [base_rate, base_rate*peak_ratio] once per
    ``period_s``.  ``bursts`` seeded spikes each multiply the rate by
    ``burst_ratio`` for ``burst_duration_s`` (flash crowds riding on
    top of the diurnal swell).  Arrivals are drawn by Lewis-Shedler
    thinning, so the stream is exact and byte-for-byte deterministic
    per seed.

    ``class_mix`` is the tenant mix (label -> traffic weight);
    ``class_phases`` optionally phase-shifts each tenant's share of
    the envelope (an interactive tenant peaking at local noon while a
    batch tenant fills the trough), normalized per arrival.  Fit the
    envelope constants from a real ``tfserve trace --json`` export
    with :func:`fit_diurnal`.
    """

    def __init__(self, n_requests: int, base_rate: float, seed: int = 0,
                 period_s: float = 86400.0, peak_ratio: float = 4.0,
                 phase: float = 0.0,
                 bursts: int = 0, burst_ratio: float = 4.0,
                 burst_duration_s: float = 60.0,
                 class_mix: Optional[Dict[Optional[str], float]] = None,
                 class_phases: Optional[Dict[Optional[str], float]] = None,
                 prompt_len: int = 64, prompt_sigma: float = 0.5,
                 new_tokens: int = 16, new_tokens_sigma: float = 0.5,
                 max_prompt_len: int = 2048, max_new_tokens: int = 512,
                 deadline_ms: Optional[float] = None,
                 deadline_exempt: Optional[Iterable[str]] = None,
                 start_at: float = 0.0,
                 model: Optional[str] = None):
        if n_requests < 1:
            raise ValueError(f"n_requests must be >= 1, got {n_requests}")
        if base_rate <= 0:
            raise ValueError(f"base_rate must be > 0, got {base_rate}")
        if period_s <= 0:
            raise ValueError(f"period_s must be > 0, got {period_s}")
        if peak_ratio < 1.0:
            raise ValueError(
                f"peak_ratio must be >= 1 (the envelope never dips "
                f"below base_rate), got {peak_ratio}")
        if bursts < 0:
            raise ValueError(f"bursts must be >= 0, got {bursts}")
        if bursts and (burst_ratio < 1.0 or burst_duration_s <= 0):
            raise ValueError(
                f"bursts need burst_ratio >= 1 and burst_duration_s "
                f"> 0, got {burst_ratio}/{burst_duration_s}")
        self.n_requests = int(n_requests)
        self.base_rate = float(base_rate)
        self.seed = int(seed)
        self.period_s = float(period_s)
        self.peak_ratio = float(peak_ratio)
        self.phase = float(phase)
        self.bursts = int(bursts)
        self.burst_ratio = float(burst_ratio)
        self.burst_duration_s = float(burst_duration_s)
        mix = class_mix or {None: 1.0}
        total = float(sum(mix.values()))
        if total <= 0:
            raise ValueError(f"class_mix weights must sum > 0: {mix}")
        self._labels = list(mix)
        self._weights = [mix[k] / total for k in self._labels]
        self._phases = dict(class_phases or {})
        # Hot-path class pick without phases: one rng.random + bisect
        # over precomputed cumulative weights (rng.choices rebuilds
        # its cumulative table per call — measurable at 1M arrivals).
        self._cum: List[float] = []
        acc = 0.0
        for w in self._weights:
            acc += w
            self._cum.append(acc)
        self.prompt_len = int(prompt_len)
        self.prompt_sigma = float(prompt_sigma)
        self.new_tokens = int(new_tokens)
        self.new_tokens_sigma = float(new_tokens_sigma)
        self.max_prompt_len = int(max_prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_ms = deadline_ms
        # Same batch-class exemption as SyntheticWorkload: listed
        # classes arrive deadline-less (the trough-filling offline
        # tenant in a phase-shifted mix).
        self.deadline_exempt = frozenset(deadline_exempt or ())
        self.start_at = float(start_at)
        self.model = model

    def envelope(self, t: float) -> float:
        """The diurnal multiplier at virtual time ``t`` (>= 1.0)."""
        s = math.sin(2.0 * math.pi * t / self.period_s + self.phase)
        return 1.0 + (self.peak_ratio - 1.0) * (0.5 + 0.5 * s)

    def _burst_windows(self, rng: random.Random,
                       horizon: float) -> List[tuple]:
        return sorted(
            (b, b + self.burst_duration_s)
            for b in (rng.uniform(0.0, horizon)
                      for _ in range(self.bursts)))

    def rate_at(self, t: float, windows: List[tuple]) -> float:
        r = self.base_rate * self.envelope(t)
        for lo, hi in windows:
            if lo <= t < hi:
                r *= self.burst_ratio
                break
        return r

    def _pick_class(self, rng: random.Random, t: float):
        if not self._phases or len(self._labels) == 1:
            if len(self._labels) == 1:
                return self._labels[0]
            i = bisect.bisect_right(self._cum, rng.random() * self._cum[-1])
            return self._labels[min(i, len(self._labels) - 1)]
        # Tenant phase shifts: each class's share rides its own
        # sinusoid (same period), renormalized at this instant.
        w = []
        for label, base_w in zip(self._labels, self._weights):
            ph = self._phases.get(label)
            if ph is None:
                w.append(base_w)
            else:
                s = math.sin(2.0 * math.pi * t / self.period_s
                             + self.phase + float(ph))
                w.append(base_w * (0.5 + 0.5 * s) + 1e-9)
        return rng.choices(self._labels, weights=w)[0]

    def __iter__(self) -> Iterator[Request]:
        rng = random.Random(self.seed)
        # Burst placement needs a horizon before arrivals exist: the
        # expected span of n_requests at the MEAN envelope rate.
        mean_rate = self.base_rate * (1.0 + (self.peak_ratio - 1.0) / 2)
        horizon = self.n_requests / mean_rate
        windows = self._burst_windows(rng, horizon) if self.bursts \
            else []
        # Lewis-Shedler thinning with a PIECEWISE-CONSTANT majorant:
        # outside burst windows the ceiling is base*peak, inside it is
        # base*peak*burst_ratio — a global ceiling would reject ~2/3
        # of candidates for the whole stream to cover windows spanning
        # a fraction of it.  Exactness holds by the exponential's
        # memorylessness: a step that would cross a majorant boundary
        # ADVANCES to the boundary and redraws at the new ceiling
        # (the standard non-homogeneous thinning refinement).  The
        # hot loop is inlined — this generator feeds million-request
        # sim runs where every per-arrival microsecond is wall time.
        bounds: List[float] = []
        for lo, hi in windows:         # merge overlaps into [lo, hi)
            if bounds and lo <= bounds[-1]:
                bounds[-1] = max(bounds[-1], hi)
            else:
                bounds.extend((lo, hi))
        plain_max = self.base_rate * self.peak_ratio
        burst_max = plain_max * self.burst_ratio
        amp = self.base_rate * (self.peak_ratio - 1.0) * 0.5
        mid = self.base_rate + amp
        omega = 2.0 * math.pi / self.period_s
        ph = self.phase
        burst_ratio = self.burst_ratio
        n, start_at = self.n_requests, self.start_at
        u, ev, sin, bis = (rng.random, rng.expovariate, math.sin,
                           bisect.bisect_right)
        p_med, p_sig = self.prompt_len, self.prompt_sigma
        o_med, o_sig = self.new_tokens, self.new_tokens_sigma
        rel = 0.0
        emitted = 0
        while emitted < n:
            i = bis(bounds, rel)
            in_burst = i & 1           # odd index = inside a window
            ceiling = burst_max if in_burst else plain_max
            step = ev(ceiling)
            if i < len(bounds) and rel + step >= bounds[i]:
                rel = bounds[i]        # crossed into the next segment:
                continue               # redraw at its ceiling (exact)
            rel += step
            rate = mid + amp * sin(omega * rel + ph)
            if in_burst:
                rate *= burst_ratio
            if u() * ceiling > rate:
                continue
            emitted += 1
            cls = self._pick_class(rng, rel)
            yield Request(
                at=start_at + rel, cls=cls,
                prompt_len=_clamped_lognormal(
                    rng, p_med, p_sig, 1, self.max_prompt_len),
                new_tokens=_clamped_lognormal(
                    rng, o_med, o_sig, 1, self.max_new_tokens),
                deadline_ms=(None if cls in self.deadline_exempt
                             else self.deadline_ms),
                model=self.model)


# -- trace replay ------------------------------------------------------------


def load_trace_export(path: str) -> List[dict]:
    """Parse a ``tfserve trace -g GW --json`` export file: either one
    JSON array or JSON-lines, each element a trace record dict."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read().strip()
    if not text:
        return []
    if text[0] == "[":
        records = json.loads(text)
    else:
        records = [json.loads(line) for line in text.splitlines() if line]
    return [r for r in records if isinstance(r, dict)]


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def replay_from_traces(records: Iterable[dict],
                       speedup: float = 1.0,
                       deadline_ms: Optional[float] = None
                       ) -> List[Request]:
    """Turn trace records into a replayable arrival list: each record
    re-arrives at its recorded wall-clock offset (``ts``, compressed
    by ``speedup``) with its recorded class and token counts.  Records
    are replayed in timestamp order; the first arrival lands at t=0.
    Prompt length comes from the retained ``gateway.recv`` span when
    the record kept detail, else a small default — the export's
    summary records carry class/latency/tokens but not the prompt."""
    rows = []
    for rec in records:
        ts = _num(rec.get("ts"))
        if ts is None:
            continue
        summary = rec.get("summary") or {}
        cls = summary.get("cls")
        tokens = _num(summary.get("tokens"))
        prompt_len = None
        for span in rec.get("spans") or ():
            if isinstance(span, dict) and span.get("name") == "recv":
                prompt_len = _num(span.get("prompt_len"))
                break
        rows.append((ts, cls if isinstance(cls, str) else None,
                     int(prompt_len) if prompt_len else 16,
                     int(tokens) if tokens and tokens > 0 else 8))
    rows.sort(key=lambda r: r[0])
    if not rows:
        return []
    t0 = rows[0][0]
    scale = 1.0 / max(1e-9, float(speedup))
    return [Request(at=(ts - t0) * scale, cls=cls, prompt_len=pl,
                    new_tokens=nt, deadline_ms=deadline_ms)
            for ts, cls, pl, nt in rows]


def fit_replica_model(records: Iterable[dict]) -> Dict[str, Any]:
    """Distill recorded traces into latency-model parameters for the
    simulated replicas: median TTFT (the prefill estimate) and median
    per-token decode time, from completed records carrying ``ttft_ms``
    + ``total_ms`` + a token count.  Returns a possibly-empty dict of
    ``{"prefill_base_ms", "decode_ms_per_token"}`` — callers lay the
    fitted values over :class:`tfmesos_tpu.fleet.sim.ReplicaModel`
    defaults and keep whatever the traces could not determine."""
    ttfts: List[float] = []
    per_tok: List[float] = []
    for rec in records:
        if not isinstance(rec, dict) or rec.get("status") != "completed":
            continue
        summary = rec.get("summary") or {}
        ttft = _num(summary.get("ttft_ms"))
        total = _num(rec.get("total_ms"))
        tokens = _num(summary.get("tokens"))
        if ttft is not None and ttft >= 0:
            ttfts.append(ttft)
        if total is not None and ttft is not None and tokens \
                and tokens > 0 and total > ttft:
            per_tok.append((total - ttft) / tokens)
    out: Dict[str, Any] = {}
    if ttfts:
        ttfts.sort()
        out["prefill_base_ms"] = round(ttfts[len(ttfts) // 2], 3)
    if per_tok:
        per_tok.sort()
        out["decode_ms_per_token"] = round(per_tok[len(per_tok) // 2], 3)
    return out


def fit_diurnal(records: Iterable[dict],
                period_s: Optional[float] = None,
                bins: int = 48) -> Dict[str, Any]:
    """Fit :class:`DiurnalWorkload` envelope constants from a
    ``tfserve trace --json`` export: arrival timestamps are bucketed
    into ``bins`` equal windows over the recorded span, the trough
    (10th-percentile bin rate) becomes ``base_rate``, the crest
    (90th) sets ``peak_ratio``, and the busiest bin's center sets
    ``phase`` so the fitted sinusoid peaks where the trace did.
    ``period_s`` defaults to the recorded span (assume the export
    caught one full cycle).  Returns a possibly-empty dict of
    ``{"base_rate", "peak_ratio", "period_s", "phase"}`` — lay it
    over :class:`DiurnalWorkload` defaults like
    :func:`fit_replica_model` does for :class:`ReplicaModel`."""
    ts = sorted(t for t in (_num(r.get("ts")) for r in records
                            if isinstance(r, dict)) if t is not None)
    if len(ts) < 2 or ts[-1] <= ts[0]:
        return {}
    span = ts[-1] - ts[0]
    period = float(period_s) if period_s else span
    if period <= 0:
        return {}
    bins = max(2, int(bins))
    width = span / bins
    counts = [0] * bins
    for t in ts:
        counts[min(bins - 1, int((t - ts[0]) / width))] += 1
    rates = sorted(c / width for c in counts)
    base = rates[int(0.10 * (bins - 1))]
    peak = rates[int(0.90 * (bins - 1))]
    if base <= 0:
        # A trace with dead-silent troughs: anchor the base on the
        # quietest NON-EMPTY bin so peak_ratio stays finite.
        nonzero = [r for r in rates if r > 0]
        if not nonzero:
            return {}
        base = nonzero[0]
    busiest = counts.index(max(counts))
    center = (busiest + 0.5) * width
    # envelope() peaks where sin(2*pi*t/period + phase) == 1.
    phase = math.pi / 2 - 2.0 * math.pi * center / period
    return {"base_rate": round(base, 6),
            "peak_ratio": round(max(1.0, peak / base), 4),
            "period_s": round(period, 3),
            "phase": round(phase, 6)}
