"""Tiered KV store: bounded host-RAM → disk spill for serving state.

The device-resident serving caches are the top of a memory hierarchy —
PR 2's prefix cache keeps hot pages in the KV pool, and the suspend/
resume unit (PR 7) made any request's mid-stream KV a portable
artifact.  This module is the next two levels down, jax-free and
stdlib-only, so the whole control plane can reason about it without an
accelerator:

* a bounded **host-RAM tier** (LRU) holding opaque ``(meta, body)``
  blobs keyed by ``(kind, key)`` — prefix pages evicted from the device
  pool park here and promote back on the next hit;
* an optional bounded **disk tier** under ``disk_dir``: RAM-evicted
  entries SPILL to files instead of vanishing, and — because the files
  are plain HMAC-framed blobs — replicas sharing one host can share the
  directory, which is what lets a parked *session* resume on any
  same-``weights_version`` replica of the host.

Two kinds ride the same store:

* ``"prefix"`` — one spilled prefix-cache page per entry, keyed by its
  chain digest (:mod:`tfmesos_tpu.prefixhash`): content-addressed, so a
  promoted page is bit-identical to the one evicted.
* ``"session"`` — a whole conversation's KV artifact
  (:func:`tfmesos_tpu.serving.pack_prefilled` shape) keyed by the
  client's ``session_id``, parked between turns and resumed as a
  leading-KV import + tail prefill (docs/SERVING.md "KV tiering &
  sessions").

Integrity and fencing:

* disk entries are framed exactly like the wire's raw frames — a
  32-byte HMAC tag (keyed by the cluster token) over
  ``meta_len + meta_json + body``, verified BEFORE the meta decodes; a
  tag mismatch (bit rot, a crash mid-write, tampering) is treated as a
  MISS and the file removed, never an exception on the serving path;
* entries carry the writer's ``stamp`` (``weights_version`` +
  generation); a reader stamped with a DIFFERENT weights_version
  misses (``version_miss`` counter) — stale-weights KV can never feed
  a decode after a rollout, the same fence drain migration enforces.

Capacity is a hard bound, not advisory: an entry that can never fit
(larger than both budgets) raises :class:`KVTierFull` — the batcher
turns a session park into an explicit rejected-park counter and the
request completes normally; nothing ever blocks waiting for space.

Counter semantics (``stats()``/``summary()``; surfaced fleet-wide as
the gateway's ``kv_tier`` gauge): ``hits``/``misses`` count every
lookup; ``spills`` device-evicted prefix pages parked into the tier;
``demotions`` RAM→disk moves; ``evictions`` entries dropped
entirely; ``park`` successful session parks and ``park_rejected``
explicit capacity rejections; ``corrupt`` disk tag mismatches;
``version_miss`` stamp fences.  ``resume`` (validated session resumes)
and ``promotions`` (tier pages re-installed into device pool pages)
are counted by the batcher, which is the only layer that can tell a
usable artifact from a stale one.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import struct
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from tfmesos_tpu import wire
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["KVTierFull", "KVTierStore", "KVFabric", "fabric_rpc",
           "rendezvous_order", "pack_gang_shards", "unpack_gang_shards"]

_TAG_LEN = 32
_LEN = struct.Struct(">I")

#: entry kinds; anything else is rejected loudly at put().
KINDS = ("prefix", "session")


class KVTierFull(RuntimeError):
    """The entry can NEVER fit the tier's budgets (explicit rejection,
    never a hang — the caller completes without parking)."""


def _tag(token: str, payload: bytes) -> bytes:
    return hmac.new(token.encode("utf-8"), payload,
                    hashlib.sha256).digest()


def pack_gang_shards(shards: List[Tuple[Dict[str, Any], bytes]]
                     ) -> Tuple[Dict[str, Any], bytes]:
    """Fold one gang replica's per-member KV exports into ONE tier
    artifact: the gang's sharded state parks and re-imports WHOLE —
    never one member's shard alone, which would resume as silently
    wrong KV on a gang of a different shape.  The combined meta
    carries the gang size, each shard's own meta, and the byte splits;
    the body is the shard bodies concatenated in rank order."""
    if not shards:
        raise ValueError("pack_gang_shards needs at least one shard")
    metas: List[Dict[str, Any]] = []
    lens: List[int] = []
    parts: List[bytes] = []
    for meta, body in shards:
        metas.append(dict(meta))
        lens.append(len(body))
        parts.append(bytes(body))
    out_meta: Dict[str, Any] = {"gang_size": len(shards),
                                "shard_meta": metas,
                                "shard_lens": lens}
    # The outer stamp mirrors shard 0's: one gang, one weights_version
    # (the batcher's export stamps every shard identically).
    for k in ("weights_version", "model_id", "adapter_version"):
        if k in metas[0]:
            out_meta[k] = metas[0][k]
    return out_meta, b"".join(parts)


def unpack_gang_shards(meta: Dict[str, Any], body: bytes
                       ) -> List[Tuple[Dict[str, Any], bytes]]:
    """Split a :func:`pack_gang_shards` artifact back into rank-order
    ``(meta, body)`` shards.  Raises ``ValueError`` on any shape
    mismatch — a torn or truncated gang artifact must read as
    corruption, never as a smaller gang."""
    try:
        size = int(meta["gang_size"])
        metas = list(meta["shard_meta"])
        lens = [int(n) for n in meta["shard_lens"]]
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"not a gang artifact: {e}")
    if size < 1 or len(metas) != size or len(lens) != size \
            or any(n < 0 for n in lens):
        raise ValueError(
            f"gang artifact shape mismatch: size={size}, "
            f"{len(metas)} metas, {len(lens)} lens")
    if sum(lens) != len(body):
        raise ValueError(
            f"gang artifact truncated: {sum(lens)} bytes declared, "
            f"{len(body)} present")
    shards: List[Tuple[Dict[str, Any], bytes]] = []
    off = 0
    for rank in range(size):
        shards.append((dict(metas[rank]), body[off:off + lens[rank]]))
        off += lens[rank]
    return shards


class KVTierStore:
    """Bounded two-level (host-RAM → disk) blob store.

    ``ram_bytes`` bounds the in-memory tier (by body + serialized-meta
    bytes — session metas embed the conversation history, so meta is
    not always small).  ``disk_dir`` (optional) enables the disk tier,
    bounded by
    ``disk_bytes`` (default 4x RAM); the directory may be SHARED by
    replicas of one host — files are HMAC-framed with the cluster
    ``token`` and stamped with the writer's ``weights_version``, so a
    foreign or stale entry reads as a miss, never as wrong KV.

    Thread-safe: the batcher's serve loop writes, the replica heartbeat
    thread reads ``summary()``.
    """

    def __init__(self, ram_bytes: int, disk_dir: Optional[str] = None,
                 disk_bytes: Optional[int] = None, token: str = "",
                 stamp: Optional[Dict[str, Any]] = None):
        if ram_bytes < 0:
            raise ValueError(f"ram_bytes must be >= 0, got {ram_bytes}")
        self.ram_bytes = int(ram_bytes)
        self.disk_dir = disk_dir
        self.disk_bytes = (int(disk_bytes) if disk_bytes is not None
                           else 4 * self.ram_bytes)
        if disk_dir is not None and self.disk_bytes <= 0:
            raise ValueError(f"disk_bytes must be > 0 with a disk tier, "
                             f"got {self.disk_bytes}")
        if self.ram_bytes == 0 and disk_dir is None:
            raise ValueError("a KV tier needs ram_bytes > 0 or a "
                             "disk_dir (both bounds zero stores nothing)")
        self.token = token
        #: writer identity merged into every entry's meta; a reader
        #: whose stamp names a DIFFERENT weights_version misses.
        self.stamp = dict(stamp or {})
        # The base weights_version :meth:`restamp` composes adapter
        # labels onto (the stamp dict itself is REPLACED atomically —
        # readers under the lock see old or new whole, never a mix).
        self._base_wv = self.stamp.get("weights_version")
        #: the prefix-page chunk geometry this store's "prefix" entries
        #: were cut with ({page, first, seed}) — set by the owning
        #: batcher; rides summary() so the router can match prompts
        #: against spilled (tier-resident) digests too.
        self.prefix_geometry: Optional[Dict[str, Any]] = None
        self.log = get_logger("tfmesos_tpu.fleet.kvtier")
        if disk_dir is not None:
            os.makedirs(disk_dir, exist_ok=True)
        self._lock = threading.Lock()
        # (kind, key) -> (meta, body, cost); LRU order, most recent
        # last.  ``cost`` = body + serialized-meta bytes: session metas
        # embed the full conversation history, so budgeting the body
        # alone would let the advertised hard bound drift.
        self._ram: "OrderedDict[Tuple[str, str], tuple]" = OrderedDict()
        self._ram_used = 0
        # Incremental disk-occupancy estimate (own writes/deletes);
        # reconciled against a real scandir only when a write thinks
        # it is over budget — a shared dir's foreign entries surface
        # there, and the common-case put stays O(1).
        self._disk_used = 0
        if disk_dir is not None:
            self._disk_used = sum(s for _, _, s in self._disk_usage())
        # Disk entries THIS process wrote (filename -> (kind, key,
        # size)) — summary() lists own spilled keys without a scandir
        # per heartbeat; cross-process entries are still readable (get
        # stats the filesystem), they just don't ride our summary.
        self._disk_keys: "OrderedDict[str, Tuple[str, str, int]]" = \
            OrderedDict()
        self._stats = {"hits": 0, "misses": 0, "spills": 0,
                       "demotions": 0, "evictions": 0, "park": 0,
                       "park_rejected": 0, "resume": 0, "promotions": 0,
                       "corrupt": 0, "version_miss": 0}

    # -- counters ----------------------------------------------------------

    def count(self, name: str, n: int = 1) -> None:
        """Bump one counter (the batcher records ``resume`` and
        ``promotions`` here — only it can tell a usable hit)."""
        with self._lock:
            self._stats[name] = self._stats.get(name, 0) + n

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self._stats)
            out["ram_bytes_used"] = self._ram_used
            out["ram_entries"] = len(self._ram)
            out["disk_bytes_used"] = sum(
                s for _, _, s in self._disk_keys.values())
            out["disk_entries"] = len(self._disk_keys)
        return out

    # -- disk framing ------------------------------------------------------

    def _path(self, kind: str, key: str) -> str:
        name = hashlib.sha256(
            f"{kind}\x00{key}".encode("utf-8")).hexdigest()
        return os.path.join(self.disk_dir, f"{name}.kvt")

    def _disk_write(self, kind: str, key: str, meta: dict,
                    body: bytes) -> bool:
        """Write one HMAC-framed entry atomically (tmp + rename — a
        crash mid-write leaves either the old entry or a tag-failing
        partial, never a silently wrong one).  False on any OS error:
        spilling is best-effort, the eviction itself must stand."""
        path = self._path(kind, key)
        mb = json.dumps(meta).encode("utf-8")
        payload = _LEN.pack(len(mb)) + mb + body
        try:
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(_tag(self.token, payload))
                f.write(payload)
            os.replace(tmp, path)
        except OSError as e:
            self.log.warning("kv tier disk write failed for %s/%s: %s",
                             kind, key, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        name = os.path.basename(path)
        old = self._disk_keys.pop(name, None)
        if old is not None:
            self._disk_used -= old[2]
        total = _TAG_LEN + len(payload)
        self._disk_keys[name] = (kind, key, total)
        self._disk_used += total
        return True

    def _disk_read(self, kind: str, key: str
                   ) -> Optional[Tuple[dict, bytes]]:
        """Read + verify one disk entry; a missing file is a miss, a
        tag mismatch (corruption, crash mid-write, tampering) is a
        COUNTED miss and the poisoned file is removed."""
        path = self._path(kind, key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        ok = len(blob) > _TAG_LEN + _LEN.size and hmac.compare_digest(
            blob[:_TAG_LEN], _tag(self.token, blob[_TAG_LEN:]))
        meta: Any = None
        if ok:
            (mlen,) = _LEN.unpack_from(blob, _TAG_LEN)
            off = _TAG_LEN + _LEN.size
            if off + mlen <= len(blob):
                try:
                    meta = json.loads(blob[off:off + mlen])
                except ValueError:
                    meta = None
        if not ok or not isinstance(meta, dict):
            self._stats["corrupt"] += 1
            self.log.warning("kv tier disk entry for %s/%s failed its "
                             "integrity tag; treating as a miss", kind,
                             key)
            try:
                os.unlink(path)
            except OSError:
                pass
            old = self._disk_keys.pop(os.path.basename(path), None)
            if old is not None:
                self._disk_used -= old[2]
            return None
        return meta, blob[_TAG_LEN + _LEN.size + mlen:]

    def _disk_usage(self) -> List[Tuple[float, str, int]]:
        """(mtime, path, size) of every entry in the shared dir."""
        out = []
        try:
            with os.scandir(self.disk_dir) as it:
                for e in it:
                    if not e.name.endswith(".kvt"):
                        continue
                    try:
                        st = e.stat()
                    except OSError:
                        continue
                    out.append((st.st_mtime, e.path, st.st_size))
        except OSError:
            pass
        return out

    def _disk_make_room(self, need: int) -> bool:
        """Evict oldest disk entries until ``need`` more bytes fit the
        disk budget; False when ``need`` alone exceeds it.  O(1) while
        the incremental estimate says there is room; the full scandir
        (which also reconciles the estimate against foreign entries in
        a shared dir) runs only under pressure."""
        if need > self.disk_bytes:
            return False
        if self._disk_used + need <= self.disk_bytes:
            return True
        entries = sorted(self._disk_usage())
        used = sum(s for _, _, s in entries)
        while entries and used + need > self.disk_bytes:
            _, path, size = entries.pop(0)
            try:
                os.unlink(path)
            except OSError:
                pass
            self._disk_keys.pop(os.path.basename(path), None)
            self._stats["evictions"] += 1
            used -= size
        self._disk_used = used
        return used + need <= self.disk_bytes

    # -- the RAM tier ------------------------------------------------------

    def _ram_evict_one(self) -> None:
        """Drop the LRU RAM entry, spilling it to the disk tier when
        one is configured (the memory-hierarchy move: RAM pressure
        demotes, it never destroys — unless there is nowhere down)."""
        (kind, key), (meta, body, cost) = self._ram.popitem(last=False)
        self._ram_used -= cost
        if self.disk_dir is not None and \
                self._disk_make_room(cost + 256) and \
                self._disk_write(kind, key, meta, body):
            self._stats["demotions"] += 1
        else:
            self._stats["evictions"] += 1

    def _ram_put(self, kind: str, key: str, meta: dict,
                 body: bytes, cost: Optional[int] = None) -> None:
        if cost is None:
            cost = len(body) + len(json.dumps(meta))
        old = self._ram.pop((kind, key), None)
        if old is not None:
            self._ram_used -= old[2]
        self._ram[(kind, key)] = (meta, body, cost)
        self._ram_used += cost
        self._ram.move_to_end((kind, key))
        while self._ram_used > self.ram_bytes and len(self._ram) > 1:
            self._ram_evict_one()
        if self._ram_used > self.ram_bytes:
            # The sole entry alone overflows RAM: demote it straight to
            # disk (put() pre-checked that SOME tier can hold it).
            self._ram_evict_one()

    # -- public surface ----------------------------------------------------

    def put(self, kind: str, key: str, meta: Dict[str, Any],
            body: bytes, stamp: bool = True) -> None:
        """Store one entry (replacing any same-key one).  Raises
        :class:`KVTierFull` when the body can never fit either tier's
        budget — an explicit rejection, never a hang or a silent
        drop.  ``stamp=False`` preserves the meta's EXISTING writer
        stamp instead of merging ours — a fabric-replicated artifact
        must keep its original weights_version/gen fence, or a stale
        copy re-stamped by a fresh holder would stop reading as
        stale."""
        if kind not in KINDS:
            raise ValueError(f"unknown kv tier kind {kind!r} "
                             f"(have: {KINDS})")
        body = bytes(body)
        meta = dict(meta)
        if stamp:
            meta.update(self.stamp)
        # Budget by the FULL entry cost (body + serialized meta): a
        # session meta embeds the whole conversation history, and a
        # hard bound that ignored it would drift with history length.
        cost = len(body) + len(json.dumps(meta))
        fits_ram = cost <= self.ram_bytes
        fits_disk = (self.disk_dir is not None
                     and cost + 256 <= self.disk_bytes)
        if not fits_ram and not fits_disk:
            raise KVTierFull(
                f"{kind} entry {key!r} ({cost} bytes incl. meta) "
                f"exceeds the tier budgets (ram {self.ram_bytes}, disk "
                f"{self.disk_bytes if self.disk_dir else 0})")
        with self._lock:
            if fits_ram:
                self._ram_put(kind, key, meta, body, cost=cost)
            else:
                # Straight to disk; drop any stale RAM twin.
                old = self._ram.pop((kind, key), None)
                if old is not None:
                    self._ram_used -= old[2]
                if not self._disk_make_room(cost + 256) \
                        or not self._disk_write(kind, key, meta, body):
                    # An OS-level write failure must be as loud as a
                    # capacity rejection — a silent drop would count a
                    # successful park that never happened.
                    raise KVTierFull(
                        f"{kind} entry {key!r} cannot be stored in the "
                        f"disk tier ({self.disk_bytes} bytes budget, "
                        f"or the write failed)")

    def restamp(self, weights_version: Optional[str] = None,
                adapter: str = "") -> None:
        """Re-identify the store's writer/reader stamp after an online
        weight change: ``weights_version`` replaces the base label
        (``None`` keeps it — the adapter-fold case), a non-empty
        ``adapter`` composes as ``"<base>+<adapter>"``.  Entries
        written under the OLD stamp become version misses (cold
        re-prefill, never stale KV) and new writes carry the new one.
        The batcher calls this from its weight-update fence
        (``swap_adapter`` / ``set_weights``)."""
        with self._lock:
            if weights_version is not None:
                self._base_wv = str(weights_version)
            wv = self._base_wv
            if adapter:
                wv = f"{wv or ''}+{adapter}"
            stamp = dict(self.stamp)
            if wv:
                stamp["weights_version"] = wv
            else:
                stamp.pop("weights_version", None)
            self.stamp = stamp

    def _stamp_ok(self, meta: dict) -> bool:
        """Weights-version fence: an entry stamped with a DIFFERENT
        version than this reader's stamp is stale KV and must miss.
        Unstamped entries (or an unstamped reader) pass — the fence
        rejects provably stale state, like the registry's."""
        want = self.stamp.get("weights_version")
        have = meta.get("weights_version")
        if want and have and str(have) != str(want):
            return False
        return True

    def get(self, kind: str, key: str
            ) -> Optional[Tuple[Dict[str, Any], bytes]]:
        """``(meta, body)`` or ``None``.  A disk hit promotes the entry
        back into the RAM tier (it is hot again)."""
        with self._lock:
            hit = self._ram.get((kind, key))
            if hit is not None:
                if not self._stamp_ok(hit[0]):
                    self._stats["version_miss"] += 1
                    self._stats["misses"] += 1
                    return None
                self._ram.move_to_end((kind, key))
                self._stats["hits"] += 1
                return hit[0], hit[1]
            if self.disk_dir is not None:
                got = self._disk_read(kind, key)
                if got is not None:
                    if not self._stamp_ok(got[0]):
                        self._stats["version_miss"] += 1
                        self._stats["misses"] += 1
                        return None
                    self._stats["hits"] += 1
                    cost = len(got[1]) + len(json.dumps(got[0]))
                    if cost <= self.ram_bytes:
                        self._ram_put(kind, key, got[0], got[1],
                                      cost=cost)
                    return got
            self._stats["misses"] += 1
            return None

    def delete(self, kind: str, key: str) -> None:
        with self._lock:
            old = self._ram.pop((kind, key), None)
            if old is not None:
                self._ram_used -= old[2]
            if self.disk_dir is not None:
                path = self._path(kind, key)
                try:
                    os.unlink(path)
                except OSError:
                    pass
                dold = self._disk_keys.pop(os.path.basename(path), None)
                if dold is not None:
                    self._disk_used -= dold[2]

    # -- kind-specific sugar ----------------------------------------------

    def would_accept(self, nbytes: int) -> bool:
        """Whether an entry of roughly ``nbytes`` could EVER be stored
        (O(1); eviction makes room for anything that fits a budget).
        The batcher pre-checks this before paying a device-to-host
        gather for a spill the tier would only reject."""
        return (nbytes <= self.ram_bytes
                or (self.disk_dir is not None
                    and nbytes + 256 <= self.disk_bytes))


    def put_prefix(self, digest_hex: str, meta: Dict[str, Any],
                   body: bytes) -> None:
        """Park one evicted prefix-cache page (content-addressed by its
        chain digest).  A full tier just declines — spilling a page the
        tier cannot hold must not fail the eviction that freed it."""
        try:
            self.put("prefix", digest_hex, meta, body)
        except KVTierFull:
            self.count("evictions")
            return
        self.count("spills")

    def get_prefix(self, digest_hex: str
                   ) -> Optional[Tuple[Dict[str, Any], bytes]]:
        return self.get("prefix", digest_hex)

    def park(self, session_id: str, meta: Dict[str, Any],
             body: bytes) -> None:
        """Park one session's KV artifact between turns.  Raises
        :class:`KVTierFull` (counted ``park_rejected``) when it cannot
        fit — the caller's completion is unaffected."""
        try:
            self.put("session", session_id, meta, body)
        except KVTierFull:
            self.count("park_rejected")
            raise
        self.count("park")

    def resume(self, session_id: str
               ) -> Optional[Tuple[Dict[str, Any], bytes]]:
        """The parked artifact for ``session_id`` (counts hit/miss;
        the batcher counts ``resume`` only after validating it)."""
        return self.get("session", session_id)

    # -- wire-facing summary ----------------------------------------------

    def summary(self, max_entries: int = 32) -> Dict[str, Any]:
        """Heartbeat payload: recent parked session ids (the router's
        session-affinity key), the spilled prefix digests in the
        device cache's summary shape (so the router's prefix-affinity
        matcher can steer shared prompts at TIER-resident pages too),
        plus counters and occupancy."""
        with self._lock:
            sessions: List[str] = []
            hashes: List[str] = []
            for (kind, key) in reversed(self._ram):
                if len(sessions) >= max_entries \
                        and len(hashes) >= max_entries:
                    break
                if kind == "session" and len(sessions) < max_entries:
                    sessions.append(key)
                elif kind == "prefix" and len(hashes) < max_entries:
                    hashes.append(key)
            for _, (kind, key, _s) in reversed(self._disk_keys.items()):
                if len(sessions) >= max_entries \
                        and len(hashes) >= max_entries:
                    break
                if kind == "session" and key not in sessions \
                        and len(sessions) < max_entries:
                    sessions.append(key)
                elif kind == "prefix" and key not in hashes \
                        and len(hashes) < max_entries:
                    hashes.append(key)
            out: Dict[str, Any] = {
                "sessions": sessions,
                "counters": dict(self._stats),
                "ram_bytes_used": self._ram_used,
                "ram_bytes": self.ram_bytes,
                # Whether parked state survives this replica (a
                # host-shared disk tier) — the model trader's victim
                # tie-break reads it: trading away a replica whose
                # sessions are parked on disk loses nothing resumable.
                "disk": self.disk_dir is not None,
            }
            geom = self.prefix_geometry
        if geom and hashes:
            out["prefix"] = {"page": geom.get("page"),
                             "first": geom.get("first"),
                             "seed": geom.get("seed"),
                             "hashes": hashes}
        return out


# -- the cross-host fabric ---------------------------------------------------


def rendezvous_order(key: str, addrs: List[str]) -> List[str]:
    """Deterministic per-key peer preference (highest-random-weight /
    rendezvous hashing): every replica computes the SAME order from the
    same alive set, so the parker's replica picks and a later resumer's
    locate agree on where copies should live without any coordinator."""
    return sorted(addrs, key=lambda a: hashlib.sha256(
        f"{key}\x00{a}".encode("utf-8")).hexdigest())


def fabric_rpc(addr: str, meta: Dict[str, Any], body: Optional[bytes] = None,
               token: str = "", timeout: float = 10.0,
               self_addr: str = "") -> Any:
    """One synchronous request/reply exchange with a peer replica over
    a fresh authenticated connection: JSON frame without a ``body``,
    raw HMAC frame with one; the single reply may be either kind.  The
    socket is tagged with the CALLER's advertised addr so chaos
    ``partition`` faults can match the peer pair."""
    sock = wire.connect(addr, timeout=timeout)
    try:
        sock.settimeout(timeout)
        if self_addr:
            wire.tag_socket(sock, self_addr)
        if body is None:
            wire.send_msg(sock, meta, token)
        else:
            wire.send_raw_msg(sock, meta, body, token)
        return wire.recv_msg(sock, token, allow_raw=True)
    finally:
        try:
            sock.close()
        except OSError:
            pass


class KVFabric:
    """The cross-host face of one replica's :class:`KVTierStore`:
    K-way replicated session parking plus peer fetch on miss, so a
    parked conversation survives the loss of the host that parked it
    (docs/SERVING.md "Cross-host KV fabric").

    Wraps a local store and presents the SAME surface the batcher
    binds (``park``/``resume``/``put_prefix``/``summary``/``count``/
    ...), delegating everything it does not override.  What it adds:

    * ``park`` — local park first (the primary copy; capacity
      rejections propagate exactly as before), then SYNCHRONOUS pushes
      of the stamped artifact to ``replication - 1`` peers in
      rendezvous order (``kv_put`` raw frames over :func:`fabric_rpc`).
      The park returns only after the push attempts complete: with at
      least one peer copy landed it is ``park_replicated``; with
      eligible peers that all failed it is ``park_degraded`` (counted,
      logged — the local copy stands, so availability is never traded
      for a replication error the counters already surface).
    * ``resume``/``fetch`` — on a local miss, ask the registry WHERE
      the artifact lives (``kv_locate`` over the heartbeat-advertised
      placement map — this is what forwards surviving copies after
      parker death or scale-to-zero), ``kv_fetch`` it from a holder,
      and install it WITHOUT re-stamping (``put(stamp=False)``) so the
      local store's weights_version fence judges the ORIGINAL writer's
      stamp: a stale-fence peer's old-version artifact reads as a
      ``version_miss``, never as wrong KV.  Gang-sharded artifacts are
      shape-checked (:func:`unpack_gang_shards`) before install — a
      torn gang is rejected loudly, never imported smaller.

    ``rpc`` and ``peers`` are injectable (the chaos/simulator
    discipline): tests and the sim substitute in-process fabrics with
    zero sockets.  ``peers()`` returns dicts with at least ``addr``
    (plus optional ``role``/``weights_version``); the default source
    asks the registry's ``kv_peers`` op and caches for ``peer_ttl``.
    """

    def __init__(self, store: KVTierStore, token: str = "",
                 self_addr: str = "", registry_addr: Optional[str] = None,
                 replication: int = 2, rpc=None, peers=None,
                 clock=time.monotonic, peer_ttl: float = 1.0,
                 push_timeout: float = 10.0,
                 placement: str = "rendezvous"):
        if replication < 1:
            raise ValueError(f"replication must be >= 1, "
                             f"got {replication}")
        if placement not in ("rendezvous", "loaded"):
            raise ValueError(f"placement must be 'rendezvous' or "
                             f"'loaded', got {placement!r}")
        self.store = store
        self.token = token
        self.self_addr = self_addr
        self.registry_addr = registry_addr
        self.replication = int(replication)
        self.placement = placement
        self._rpc = rpc or (lambda addr, meta, body=None, timeout=10.0:
                            fabric_rpc(addr, meta, body, token=self.token,
                                       timeout=timeout,
                                       self_addr=self.self_addr))
        self._peer_source = peers
        self._clock = clock
        self.peer_ttl = float(peer_ttl)
        self.push_timeout = float(push_timeout)
        self._peer_cache: Tuple[float, List[Dict[str, Any]]] = (-1e18, [])
        self.log = get_logger("tfmesos_tpu.fleet.kvfabric")

    # -- delegation: the fabric IS the batcher's kv tier -------------------

    def __getattr__(self, name: str) -> Any:
        return getattr(self.store, name)

    @property
    def prefix_geometry(self) -> Optional[Dict[str, Any]]:
        return self.store.prefix_geometry

    @prefix_geometry.setter
    def prefix_geometry(self, geom: Optional[Dict[str, Any]]) -> None:
        # The batcher ASSIGNS this; plain __getattr__ delegation would
        # strand the write on the wrapper and hide it from summary().
        self.store.prefix_geometry = geom

    # -- peer placement ----------------------------------------------------

    def peers(self) -> List[Dict[str, Any]]:
        """Alive fabric peers (self excluded), from the injected source
        or the registry's ``kv_peers`` op (TTL-cached: park runs on the
        batcher loop and must not pay a registry round trip per
        session)."""
        if self._peer_source is not None:
            raw = list(self._peer_source())
        else:
            if self.registry_addr is None:
                return []
            t, cached = self._peer_cache
            if self._clock() - t < self.peer_ttl:
                raw = cached
            else:
                try:
                    reply = self._rpc(self.registry_addr,
                                      {"op": "kv_peers"},
                                      timeout=self.push_timeout)
                    raw = reply.get("peers") or [] \
                        if isinstance(reply, dict) else []
                except (OSError, wire.WireError) as e:
                    self.log.warning("kv_peers lookup failed: %s", e)
                    raw = cached    # stale beats empty mid-blip
                self._peer_cache = (self._clock(), raw)
        out = []
        for p in raw:
            if isinstance(p, dict) and p.get("addr") \
                    and p["addr"] != self.self_addr:
                out.append(p)
        return out

    def _order(self, key: str, peers: List[Dict[str, Any]]
               ) -> List[str]:
        """One eligibility class's candidate order.  Pure rendezvous by
        default (deterministic hash spread — every fabric node computes
        the same order, which is what makes locate-free probing work).
        ``placement='loaded'`` re-scores the SAME rendezvous candidates
        by their heartbeat-advertised tier occupancy, quantized to
        coarse buckets so placement only deviates from the hash order
        when a peer's tier is materially fuller — parks drift away from
        nearly-full peers without shredding the deterministic probe
        order that fetch-on-miss relies on."""
        ranked = rendezvous_order(key, [p["addr"] for p in peers])
        if self.placement != "loaded":
            return ranked
        occ: Dict[str, Any] = {p["addr"]: p.get("occupancy")
                               for p in peers}

        def bucket(addr: str) -> int:
            o = occ.get(addr)
            if not isinstance(o, (int, float)) or o != o or o < 0:
                return 0    # unknown load reads as empty, not as full
            return min(int(float(o) * 4.0), 4)

        rank = {a: i for i, a in enumerate(ranked)}
        return sorted(ranked, key=lambda a: (bucket(a), rank[a]))

    def _replica_targets(self, key: str) -> List[str]:
        """The ordered peer addrs eligible to hold a copy of ``key``:
        dedicated KV-role peers first (they exist to hold state), then
        same-weights_version peers (any other version would fence the
        copy on its own reads), unstamped peers last — each class
        ordered by :meth:`_order` (rendezvous, optionally
        load-scored)."""
        wv = self.store.stamp.get("weights_version")
        kv_role, same, rest = [], [], []
        for p in self.peers():
            pwv = p.get("weights_version")
            if p.get("role") == "kv":
                kv_role.append(p)
            elif not wv or not pwv or str(pwv) == str(wv):
                same.append(p)
            else:
                rest.append(p)
        return (self._order(key, kv_role)
                + self._order(key, same)
                + self._order(key, rest))

    # -- replicated park ---------------------------------------------------

    def park(self, session_id: str, meta: Dict[str, Any],
             body: bytes) -> None:
        self.store.park(session_id, meta, body)
        if self.replication <= 1:
            return
        # Push the STAMPED artifact (what the local tier actually
        # holds), so every copy carries the same fence.
        smeta = dict(meta)
        smeta.update(self.store.stamp)
        self._replicate("session", session_id, smeta, body)

    def _replicate(self, kind: str, key: str, meta: Dict[str, Any],
                   body: bytes) -> int:
        """Synchronously land ``replication - 1`` copies on peers
        (bounded: one attempt per peer, rendezvous order, stop when
        enough landed).  Returns the number of peer copies made;
        counts ``park_replicated`` / ``park_degraded``."""
        want = self.replication - 1
        targets = self._replica_targets(key)
        landed = 0
        for addr in targets:
            if landed >= want:
                break
            self.store.count("fabric_push")
            try:
                reply = self._rpc(
                    addr, {"op": "kv_put", "kind": kind, "key": key,
                           "meta": meta},
                    body, timeout=self.push_timeout)
            except (OSError, wire.WireError) as e:
                self.store.count("fabric_push_fail")
                self.log.warning("fabric push of %s/%s to %s failed: %s",
                                 kind, key, addr, e)
                continue
            if isinstance(reply, dict) and reply.get("op") == "kv_put_ok":
                landed += 1
                self.store.count("fabric_push_bytes", len(body))
            else:
                self.store.count("fabric_push_fail")
                self.log.warning("fabric push of %s/%s to %s rejected: "
                                 "%r", kind, key, addr, reply)
        if landed >= min(want, len(targets)) and landed > 0:
            self.store.count("park_replicated")
        elif targets:
            self.store.count("park_degraded")
            self.log.warning(
                "fabric park of %s/%s degraded: %d/%d peer copies "
                "landed (%d peers eligible)", kind, key, landed, want,
                len(targets))
        return landed

    # -- remote fetch on miss ----------------------------------------------

    def resume(self, session_id: str
               ) -> Optional[Tuple[Dict[str, Any], bytes]]:
        got = self.store.resume(session_id)
        if got is not None:
            return got
        return self.fetch("session", session_id)

    def get_prefix(self, digest_hex: str
                   ) -> Optional[Tuple[Dict[str, Any], bytes]]:
        """Prefix fetch-through: a spilled prefix page missing from the
        LOCAL tier rides the same locate/fetch surface sessions use, so
        a shared system prompt prefilled once per fleet survives its
        host dying — any replica that spilled (or fabric-received) the
        page serves it, and the fetched copy installs locally for the
        next hit.  Content-addressed by chain digest, so a copy from
        ANY holder is the right bytes; the weights_version fence still
        applies on the local re-read."""
        got = self.store.get_prefix(digest_hex)
        if got is not None:
            return got
        got = self.fetch("prefix", digest_hex)
        if got is not None:
            self.store.count("fabric_prefix_fetches")
        return got

    def fetch(self, kind: str, key: str
              ) -> Optional[Tuple[Dict[str, Any], bytes]]:
        """Locate-and-fetch one artifact from a surviving holder; None
        when no holder has a usable copy.  The fetched copy installs
        un-restamped and re-reads through the LOCAL store, so the
        weights_version fence applies exactly as it does to local
        entries."""
        holders = self.locate(kind, key)
        if not holders:
            # The placement map is heartbeat-fed and truncated (a
            # replica advertises only its most recent entries), so an
            # empty locate is not proof of loss: probe the rendezvous
            # heads — the same peers a replicated park would have
            # chosen — before giving up.  Bounded: replication + 1
            # probes, not a fleet sweep.
            holders = self._replica_targets(key)[:self.replication + 1]
        for addr in holders:
            if addr == self.self_addr:
                continue
            self.store.count("fabric_fetch")
            try:
                reply = self._rpc(addr,
                                  {"op": "kv_fetch", "kind": kind,
                                   "key": key},
                                  timeout=self.push_timeout)
            except (OSError, wire.WireError) as e:
                self.store.count("fabric_fetch_fail")
                self.log.warning("fabric fetch of %s/%s from %s failed: "
                                 "%s", kind, key, addr, e)
                continue
            if not isinstance(reply, wire.RawFrame) \
                    or not isinstance(reply.meta, dict) \
                    or reply.meta.get("op") != "kv_artifact":
                self.store.count("fabric_fetch_miss")
                continue
            ameta = reply.meta.get("meta")
            if not isinstance(ameta, dict):
                self.store.count("fabric_fetch_miss")
                continue
            if "gang_size" in ameta:
                # Gang-sharded artifacts re-import WHOLE or not at all:
                # a torn/truncated gang must reject loudly here, never
                # surface as a smaller gang to the importer.
                try:
                    unpack_gang_shards(ameta, reply.body)
                except ValueError as e:
                    self.store.count("fabric_reject_torn")
                    self.log.warning(
                        "fabric fetch of %s/%s from %s returned a torn "
                        "gang artifact (%s); rejecting", kind, key,
                        addr, e)
                    continue
            try:
                self.store.put(kind, key, ameta, reply.body, stamp=False)
            except KVTierFull:
                self.store.count("fabric_fetch_fail")
                return None     # nowhere to land it locally
            got = self.store.get(kind, key)
            if got is None:
                # The local fence rejected the copy (stale-fence holder
                # offering old-version state): drop it and keep looking
                # — another holder may have a current copy.
                self.store.count("fabric_reject_stale")
                self.store.delete(kind, key)
                continue
            self.store.count("fabric_fetch_hit")
            self.store.count("fabric_fetch_bytes", len(reply.body))
            return got
        return None

    def locate(self, kind: str, key: str) -> List[str]:
        """Holder addrs for one artifact, from the registry's
        placement map (``kv_locate`` — built from the session/prefix
        lists every replica's heartbeat already advertises)."""
        if self.registry_addr is None:
            return []
        try:
            reply = self._rpc(self.registry_addr,
                              {"op": "kv_locate", "kind": kind,
                               "key": key},
                              timeout=self.push_timeout)
        except (OSError, wire.WireError) as e:
            self.log.warning("kv_locate of %s/%s failed: %s", kind,
                             key, e)
            return []
        if isinstance(reply, dict) and isinstance(reply.get("addrs"),
                                                  list):
            return [a for a in reply["addrs"] if isinstance(a, str)]
        return []

    # -- wire ops the owning replica serves --------------------------------

    def handle_put(self, msg: "wire.RawFrame") -> Dict[str, Any]:
        """Serve one peer's ``kv_put``: install the artifact WITHOUT
        re-stamping (the original writer's fence must survive the
        hop)."""
        meta = msg.meta
        kind = meta.get("kind")
        key = meta.get("key")
        ameta = meta.get("meta")
        if kind not in KINDS or not isinstance(key, str) or not key \
                or not isinstance(ameta, dict):
            return {"op": "error", "kind": "bad_request",
                    "error": "malformed kv_put"}
        try:
            self.store.put(kind, key, ameta, msg.body, stamp=False)
        except KVTierFull as e:
            return {"op": "error", "kind": "kv_tier_full",
                    "error": str(e)}
        self.store.count("fabric_store")
        return {"op": "kv_put_ok", "kind": kind, "key": key}

    def handle_fetch(self, msg: Dict[str, Any]) -> Any:
        """Serve one peer's ``kv_fetch``: the artifact as a raw frame,
        or an explicit miss.  Reads via the RAW store (no fabric
        re-fetch — a locate loop between two replicas that both miss
        must terminate here)."""
        kind = msg.get("kind")
        key = msg.get("key")
        if kind not in KINDS or not isinstance(key, str) or not key:
            return {"op": "error", "kind": "bad_request",
                    "error": "malformed kv_fetch"}
        got = self.store.get(kind, key)
        if got is None:
            return {"op": "kv_miss", "kind": kind, "key": key}
        meta, body = got
        self.store.count("fabric_serve")
        return wire.RawFrame({"op": "kv_artifact", "kind": kind,
                              "key": key, "meta": meta}, body)
