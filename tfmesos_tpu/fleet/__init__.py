"""Fleet serving gateway: online multi-replica inference.

The reference framework is control-plane-only (its examples end at
training) and our serving stack is a powerful but single-process
``ContinuousBatcher``.  This package is the layer between the scheduler
and the batcher — a thin replica-management abstraction in the spirit of
TF-Replicator (PAPERS.md) over the existing execution engine:

* :mod:`~tfmesos_tpu.fleet.registry` — replica liveness via heartbeats
  over the authenticated wire protocol (alive → draining → dead →
  evicted).
* :mod:`~tfmesos_tpu.fleet.router` — least-outstanding-requests routing
  with power-of-two-choices sampling, plus bounded retry-with-backoff
  onto a different replica when a connection dies mid-request.
* :mod:`~tfmesos_tpu.fleet.admission` — backpressure: a bounded ingress
  queue, queue-depth shedding with explicit ``Overloaded`` rejections,
  and a token-bucket rate limiter.
* :mod:`~tfmesos_tpu.fleet.gateway` — the event-loop TCP front door
  (one selector thread per gateway, a worker pool for dispatch) that
  accepts client requests, routes them, and relays completions back —
  streamed per token when asked; N stateless gateways may front one
  fleet (docs/SERVING.md "Front-door scaling").
* :mod:`~tfmesos_tpu.fleet.metrics` — counters + latency histograms
  (TTFT, tokens/s, queue depth, shed/retry counts) as a JSON snapshot,
  a periodic log line, and Prometheus exposition behind an optional
  stdlib HTTP endpoint.
* :mod:`~tfmesos_tpu.fleet.tracing` — end-to-end request tracing:
  per-request trace ids on the wire, per-component flight recorders,
  tail-based retention in the gateway's trace book, and the ``tfserve
  trace`` waterfall.
* :mod:`~tfmesos_tpu.fleet.kvtier` — the tiered KV store (bounded
  host-RAM → disk, HMAC-framed disk entries, weights-version fencing):
  prefix pages evicted from the device pool spill into it and promote
  back on the next hit, and session-labeled requests park their
  conversation KV between turns (docs/SERVING.md "KV tiering &
  sessions").
* :mod:`~tfmesos_tpu.fleet.replica` — the replica process: a
  ``ContinuousBatcher`` behind a TCP server, fed through the batcher's
  incremental submission API; launched as a Mode-B task through the
  backend abstraction (so ``LocalBackend`` runs whole fleets on CPU).
* :mod:`~tfmesos_tpu.fleet.launcher` — ``FleetServer``: one object that
  brings the whole thing up (registry + gateway + dynamically-launched
  replicas) and tears it down, plus the blue-green
  ``FleetServer.rollout`` control op.
* :mod:`~tfmesos_tpu.fleet.autoscaler` — the control-plane feedback
  loop that grows and shrinks each tier from live load signals
  (queue-wait p99 for prompt tiers, KV headroom for decode) within
  min/max bounds, with hysteresis, per-tier cooldowns, drain-then-kill
  scale-down, and a never-below-one-alive invariant.
* :mod:`~tfmesos_tpu.fleet.sim` / :mod:`~tfmesos_tpu.fleet.workload` —
  the trace-driven fleet simulator (docs/SIMULATOR.md): a virtual-clock
  discrete-event harness that runs the REAL admission/router/
  containment/registry/autoscaler code against simulated replicas —
  1000-replica fleets and millions of requests in seconds of CPU —
  with synthesized or trace-replayed workloads, named scenarios
  (``tfserve simulate``), policy-constant sweeps, and a seeded
  soak-replay fidelity gate in tier-1.

Disaggregated prefill/decode serving (docs/SERVING.md) rides the same
pieces: replicas advertise ``role: prefill|decode|unified`` (plus
KV-page headroom) on heartbeats, the router becomes two-tier — prefill
pick by prefix-affinity/load, decode pick by page headroom — and the
gateway's generate path orchestrates prefill → raw-frame KV transfer →
decode with bounded retry, falling back to the unified tier whenever a
role tier is empty.

Everything here except :mod:`replica` is jax-free — the gateway process
never touches an accelerator.
"""

from __future__ import annotations

from tfmesos_tpu.fleet.admission import (AdmissionController,
                                         DeadlineExceeded, Overloaded,
                                         RateLimited, TokenBucket)
from tfmesos_tpu.fleet.autoscaler import AutoscalerConfig, FleetAutoscaler
from tfmesos_tpu.fleet.client import (ConnectionLost, FleetClient,
                                      MuxConnection, RequestFailed)
from tfmesos_tpu.fleet.containment import (BreakerBoard, BreakerConfig,
                                           RetryBudget)
from tfmesos_tpu.fleet.gateway import Gateway
from tfmesos_tpu.fleet.kvtier import KVTierFull, KVTierStore
from tfmesos_tpu.fleet.launcher import FleetServer, RolloutError
from tfmesos_tpu.fleet.metrics import FleetMetrics
from tfmesos_tpu.fleet.registry import (DECODE, PREFILL, UNIFIED,
                                        ReplicaInfo, ReplicaRegistry)
from tfmesos_tpu.fleet.router import Router, RoutingError
from tfmesos_tpu.fleet.sim import (FleetSim, ReplicaModel, SimConfig,
                                   SimEngine, VirtualClock,
                                   run_scenario, run_sweep)
from tfmesos_tpu.fleet.tracing import (FlightRecorder, TraceBook,
                                       TraceContext, format_waterfall)
from tfmesos_tpu.fleet.workload import (Request, SyntheticWorkload,
                                        fit_replica_model,
                                        replay_from_traces)

__all__ = [
    "AdmissionController", "Overloaded", "RateLimited",
    "DeadlineExceeded", "TokenBucket",
    "AutoscalerConfig", "FleetAutoscaler", "RolloutError",
    "BreakerBoard", "BreakerConfig", "RetryBudget",
    "ConnectionLost", "FleetClient", "MuxConnection", "RequestFailed",
    "Gateway", "FleetServer", "FleetMetrics", "KVTierFull",
    "KVTierStore", "ReplicaInfo",
    "ReplicaRegistry", "Router", "RoutingError",
    "FlightRecorder", "TraceBook", "TraceContext", "format_waterfall",
    "FleetSim", "ReplicaModel", "SimConfig", "SimEngine",
    "VirtualClock", "run_scenario", "run_sweep",
    "Request", "SyntheticWorkload", "fit_replica_model",
    "replay_from_traces",
    "UNIFIED", "PREFILL", "DECODE",
]
