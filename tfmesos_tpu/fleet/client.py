"""Multiplexed request/reply over the authenticated wire protocol.

One persistent connection carries many in-flight requests, matched by a
connection-local ``id`` the sender assigns — the transport both sides of
the fleet share: the router uses :class:`MuxConnection` to talk to
replicas (its ``outstanding`` count is what least-outstanding routing
balances on), and :class:`FleetClient` wraps the same machinery for
callers talking to the gateway.

Failure model: when the peer closes or the socket errors, EVERY pending
call fails promptly with :class:`ConnectionLost` — nothing blocks until
a timeout just because a replica died (the router turns that into a
retry on a different replica).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from tfmesos_tpu import wire
from tfmesos_tpu.fleet.admission import Overloaded, RateLimited
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["ConnectionLost", "CallTimeout", "RequestFailed",
           "MuxConnection", "FleetClient"]


class ConnectionLost(OSError):
    """The peer went away (EOF, reset, or bad frame) with calls pending."""


class CallTimeout(TimeoutError):
    """No reply within the caller's deadline (the connection is still up)."""


class RequestFailed(RuntimeError):
    """The peer replied with an error (``kind`` names which)."""

    def __init__(self, message: str, kind: str = "error"):
        super().__init__(message)
        self.kind = kind


class MuxConnection:
    """Thread-safe multiplexed calls over one authenticated socket.

    ``call()`` may be invoked from any number of threads; a reader
    thread dispatches replies to waiters by ``id``.  ``outstanding`` is
    the number of calls awaiting replies — the router's load signal.
    """

    def __init__(self, addr: str, token: str = "",
                 connect_timeout: float = 10.0):
        self.addr = addr
        self._token = token
        self._sock = wire.connect(addr, timeout=connect_timeout)
        # Idle mux connections are normal (a replica with no traffic);
        # per-call deadlines live in call(), not on the socket.
        self._sock.settimeout(None)
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._slots: Dict[int, list] = {}   # id -> [Event, reply|None]
        self._next_id = 0
        self._closed = False
        self._error: Optional[str] = None
        self._reader = threading.Thread(target=self._read_loop,
                                        name=f"mux-{addr}", daemon=True)
        self._reader.start()

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def outstanding(self) -> int:
        with self._lock:
            return len(self._slots)

    def call(self, msg: Dict[str, Any],
             timeout: Optional[float] = None) -> Any:
        """Send ``msg`` (its ``id`` field is overwritten with ours) and
        block for the matching reply — a dict, or a
        :class:`~tfmesos_tpu.wire.RawFrame` when the peer replies in
        the raw binary framing (a prefill replica's KV export)."""
        return self._call(msg, None, timeout)

    def call_raw(self, meta: Dict[str, Any], body,
                 timeout: Optional[float] = None) -> Any:
        """Like :meth:`call`, but ships ``meta`` + ``body`` as ONE raw
        binary frame (zero-copy body) — the KV handoff's transport into
        a decode replica.  The reply is matched by ``meta['id']`` like
        any other call."""
        return self._call(meta, body, timeout)

    def _call(self, msg: Dict[str, Any], raw_body,
              timeout: Optional[float] = None) -> Any:
        with self._lock:
            if self._closed:
                raise ConnectionLost(self._error or "connection closed")
            self._next_id += 1
            mid = self._next_id
            slot = [threading.Event(), None]
            self._slots[mid] = slot
        out = dict(msg)
        out["id"] = mid
        try:
            with self._send_lock:
                if raw_body is not None:
                    wire.send_raw_msg(self._sock, out, raw_body,
                                      self._token)
                else:
                    wire.send_msg(self._sock, out, self._token)
        except wire.WireError:
            # Encode-time rejection (oversized raw meta/frame), raised
            # BEFORE any bytes hit the socket: the connection is still
            # good and no other call is disturbed — release the slot
            # and surface it as deterministic for THIS payload, never
            # as a dead peer.
            with self._lock:
                self._slots.pop(mid, None)
            raise
        except OSError as e:
            with self._lock:
                self._slots.pop(mid, None)
            self._fail(f"send failed: {e}")
            raise ConnectionLost(str(e)) from e
        if not slot[0].wait(timeout):
            with self._lock:
                self._slots.pop(mid, None)
                # The reply may have raced the timeout (the reader
                # stores it under this lock) — honor it if so.
                if slot[1] is not None:
                    return slot[1]
            raise CallTimeout(f"no reply from {self.addr} "
                              f"within {timeout}s")
        if slot[1] is None:     # woken by _fail, not by a reply
            raise ConnectionLost(self._error or "connection closed")
        return slot[1]

    def _read_loop(self) -> None:
        # We dialed this peer ourselves; raw replies (a prefill
        # replica's KV export) are expected on mux links.
        framer = wire.Framer(self._token, allow_raw=True)
        try:
            for msg in wire.iter_msgs(self._sock, framer):
                if isinstance(msg, wire.RawFrame):
                    mid = (msg.meta.get("id")
                           if isinstance(msg.meta, dict) else None)
                elif isinstance(msg, dict):
                    mid = msg.get("id")
                else:
                    continue
                with self._lock:
                    # The reply lands under the lock so a caller whose
                    # wait() just timed out still finds it (its own pop
                    # serializes after this one).
                    slot = self._slots.pop(mid, None)
                    if slot is not None:
                        slot[1] = msg
                if slot is not None:
                    slot[0].set()
            self._fail("EOF from peer")
        except (OSError, wire.WireError) as e:
            self._fail(str(e))

    def _fail(self, why: str) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._error = why
            pending: List[list] = list(self._slots.values())
            self._slots.clear()
        try:
            self._sock.close()
        except OSError:
            pass
        for slot in pending:    # wake every waiter; slot[1] stays None
            slot[0].set()

    def close(self) -> None:
        self._fail("closed by caller")


class FleetClient:
    """Caller-side handle on a fleet gateway.

    Thread-safe: many threads may ``generate()`` concurrently over the
    one multiplexed connection.  Overload rejections surface as
    :class:`~tfmesos_tpu.fleet.admission.Overloaded` — the explicit
    backpressure signal callers are expected to handle (back off,
    retry later, or spill).
    """

    def __init__(self, addr: str, token: str = "", timeout: float = 120.0,
                 connect_timeout: float = 10.0):
        self.addr = addr
        self.timeout = timeout
        self.log = get_logger("tfmesos_tpu.fleet.client")
        self._mux = MuxConnection(addr, token,
                                  connect_timeout=connect_timeout)

    def generate(self, prompt, max_new_tokens: int,
                 stop_token: Optional[int] = None,
                 timeout: Optional[float] = None,
                 priority: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 trace=None) -> Dict[str, Any]:
        """One generation request; returns the completion dict
        (``tokens``, ``ttft_ms``, ``total_ms``, ``trace_id``).  Raises
        ``Overloaded`` on shed, :class:`RequestFailed` on any other
        error reply.  ``priority`` names the gateway admission class
        this request rides in (e.g. ``"background"``); unlabeled
        requests take the fleet's default (first-listed) class.
        ``deadline_ms`` is the END-TO-END budget from gateway receipt:
        expired work is shed in the admission queue, failed fast by the
        router, and cancelled inside the replicas (surfacing here as
        :class:`RequestFailed` with kind ``deadline_exceeded``); no
        deadline preserves the flat server-side timeout behavior.
        ``trace`` asks the fleet to retain FULL span detail for this
        request's trace: ``True`` under a gateway-minted id, a string
        to supply the trace id yourself; every request is
        summary-traced regardless, and the reply's ``trace_id`` (also
        set on raised ``Overloaded``/``RequestFailed`` exceptions)
        fetches the waterfall via :meth:`trace` / ``tfserve trace``."""
        msg = {"op": "generate", "prompt": [int(t) for t in prompt],
               "max_new_tokens": int(max_new_tokens),
               "stop_token": stop_token}
        if priority is not None:
            msg["priority"] = str(priority)
        if deadline_ms is not None:
            if not deadline_ms > 0:
                raise ValueError(f"deadline_ms must be > 0, got "
                                 f"{deadline_ms}")
            msg["deadline_ms"] = float(deadline_ms)
        if trace is not None and trace is not False:
            msg["trace"] = str(trace) if isinstance(trace, str) else True
        reply = self._mux.call(
            msg, timeout=timeout if timeout is not None else self.timeout)
        if isinstance(reply, dict) and reply.get("op") == "completion":
            return reply
        kind = reply.get("kind", "error") if isinstance(reply, dict) else "error"
        error = reply.get("error", repr(reply)) if isinstance(reply, dict) \
            else repr(reply)
        tid = reply.get("trace_id") if isinstance(reply, dict) else None
        if kind == "rate_limited":
            exc: Exception = RateLimited(error)
        elif kind == "overloaded":
            exc = Overloaded(error)
        else:
            exc = RequestFailed(error, kind=kind)
        exc.trace_id = tid
        raise exc

    def trace(self, trace_id: Optional[str] = None,
              slowest: Optional[int] = None, failed: bool = False,
              limit: int = 20, timeout: float = 10.0) -> list:
        """Fetch trace records from the gateway's book: one by id (full
        waterfall), the N ``slowest``, the newest ``failed``, or the
        recent summaries (docs/SERVING.md "Observability")."""
        msg: Dict[str, Any] = {"op": "trace", "limit": int(limit)}
        if trace_id:
            msg["trace_id"] = str(trace_id)
        elif slowest:
            msg["slowest"] = int(slowest)
        elif failed:
            msg["failed"] = True
        reply = self._mux.call(msg, timeout=timeout)
        if isinstance(reply, dict):
            return reply.get("traces") or []
        return []

    def metrics(self, timeout: float = 10.0) -> Dict[str, Any]:
        """The gateway's live metrics snapshot."""
        reply = self._mux.call({"op": "metrics"}, timeout=timeout)
        return reply.get("snapshot", {})

    def rollout(self, weights_version: str,
                timeout: float = 900.0) -> Dict[str, Any]:
        """Drive a blue-green weight rollout through the gateway's
        control op and block until it completes (a rollout spans a full
        tier's warmup plus the old tier's drain — size ``timeout``
        accordingly).  Returns the gateway's summary dict; raises
        :class:`RequestFailed` (kind ``rollout_failed``) on abort."""
        reply = self._mux.call({"op": "rollout",
                                "weights_version": str(weights_version)},
                               timeout=timeout)
        if isinstance(reply, dict) and reply.get("op") == "rollout":
            return reply
        kind = reply.get("kind", "error") if isinstance(reply, dict) \
            else "error"
        error = reply.get("error", repr(reply)) if isinstance(reply, dict) \
            else repr(reply)
        raise RequestFailed(error, kind=kind)

    @property
    def outstanding(self) -> int:
        return self._mux.outstanding

    def close(self) -> None:
        self._mux.close()

    def __enter__(self) -> "FleetClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
