"""Multiplexed request/reply over the authenticated wire protocol.

One persistent connection carries many in-flight requests, matched by a
connection-local ``id`` the sender assigns — the transport both sides of
the fleet share: the router uses :class:`MuxConnection` to talk to
replicas (its ``outstanding`` count is what least-outstanding routing
balances on), and :class:`FleetClient` wraps the same machinery for
callers talking to the gateway.

Failure model: when the peer closes or the socket errors, EVERY pending
call fails promptly with :class:`ConnectionLost` — nothing blocks until
a timeout just because a replica died (the router turns that into a
retry on a different replica).  A reader-thread death that is NOT a
clean transport failure (a bug, an unexpected decode path) fails them
just as promptly with the distinguishable :class:`ReaderDied` — callers
must never ride their full per-call timeout because the thread that
would have delivered the reply is gone.

Streaming: a reply stream may interleave PARTIAL frames
(``{"op": "tokens", "id", "off", "tokens"}``) before the final
completion — the per-token streaming path (docs/SERVING.md "Front-door
scaling").  Partials dispatch to the call's ``on_partial`` callback
without resolving it; the matching final reply resolves it as always.

Multi-gateway failover: :class:`FleetClient` accepts a LIST of gateway
addresses.  ``generate`` is idempotent (completions are deterministic
functions of the request and nothing was delivered when a gateway died
mid-call), so a :class:`ConnectionLost` mid-generate re-resolves the
gateway list (the ``gateways`` discovery op) and REPLAYS the request on
a surviving gateway; streamed tokens are de-duplicated by offset, so
the caller's ``on_tokens`` sees each token exactly once even across a
replay.  Non-idempotent ops (``rollout``) never replay.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from tfmesos_tpu import wire
from tfmesos_tpu.fleet.admission import Overloaded, RateLimited
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["ConnectionLost", "ReaderDied", "CallTimeout", "RequestFailed",
           "MuxConnection", "FleetClient"]


class ConnectionLost(OSError):
    """The peer went away (EOF, reset, or bad frame) with calls pending."""


class ReaderDied(ConnectionLost):
    """The mux reader thread died on an UNEXPECTED error (not a clean
    EOF / socket failure): every outstanding call fails immediately
    with this — distinguishable from an ordinary peer death, because it
    names a client-side bug rather than replica health (the router must
    not mark a replica dead for it)."""


class CallTimeout(TimeoutError):
    """No reply within the caller's deadline (the connection is still up)."""


class RequestFailed(RuntimeError):
    """The peer replied with an error (``kind`` names which)."""

    def __init__(self, message: str, kind: str = "error"):
        super().__init__(message)
        self.kind = kind


class MuxConnection:
    """Thread-safe multiplexed calls over one authenticated socket.

    ``call()`` may be invoked from any number of threads; a reader
    thread dispatches replies to waiters by ``id``.  ``outstanding`` is
    the number of calls awaiting replies — the router's load signal.
    """

    def __init__(self, addr: str, token: str = "",
                 connect_timeout: float = 10.0):
        self.addr = addr
        self._token = token
        self._sock = wire.connect(addr, timeout=connect_timeout)
        # Idle mux connections are normal (a replica with no traffic);
        # per-call deadlines live in call(), not on the socket.
        self._sock.settimeout(None)
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()
        # id -> [Event, reply|None, on_partial|None]
        self._slots: Dict[int, list] = {}
        self._next_id = 0
        self._closed = False
        self._error: Optional[str] = None
        self._reader_died = False
        self._reader = threading.Thread(target=self._read_loop,
                                        name=f"mux-{addr}", daemon=True)
        self._reader.start()

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def outstanding(self) -> int:
        with self._lock:
            return len(self._slots)

    def _lost(self) -> ConnectionLost:
        why = self._error or "connection closed"
        return ReaderDied(why) if self._reader_died \
            else ConnectionLost(why)

    def call(self, msg: Dict[str, Any],
             timeout: Optional[float] = None,
             on_partial: Optional[Callable[[Any], None]] = None) -> Any:
        """Send ``msg`` (its ``id`` field is overwritten with ours) and
        block for the matching reply — a dict, or a
        :class:`~tfmesos_tpu.wire.RawFrame` when the peer replies in
        the raw binary framing (a prefill replica's KV export).
        ``on_partial`` receives any PARTIAL frames (``op: tokens``)
        matched to this call before the final reply — the streaming
        path; it runs on the reader thread and must not block."""
        return self._call(msg, None, timeout, on_partial)

    def call_raw(self, meta: Dict[str, Any], body,
                 timeout: Optional[float] = None,
                 on_partial: Optional[Callable[[Any], None]] = None
                 ) -> Any:
        """Like :meth:`call`, but ships ``meta`` + ``body`` as ONE raw
        binary frame (zero-copy body) — the KV handoff's transport into
        a decode replica.  The reply is matched by ``meta['id']`` like
        any other call."""
        return self._call(meta, body, timeout, on_partial)

    def notify(self, msg: Dict[str, Any]) -> bool:
        """Fire-and-forget one-way send: ``msg`` goes out with ``id`` 0
        (call ids start at 1, so the peer's reply — if it sends one —
        matches no slot and the reader drops it).  Used for advisory
        control traffic like mid-stream ``cancel``: best-effort by
        design, so send failures report ``False`` instead of raising —
        a cancel that can't reach a dying peer costs nothing."""
        out = dict(msg)
        out["id"] = 0
        try:
            with self._send_lock:
                wire.send_msg(self._sock, out, self._token)
            return True
        except (OSError, wire.WireError):
            return False

    def _call(self, msg: Dict[str, Any], raw_body,
              timeout: Optional[float] = None,
              on_partial: Optional[Callable[[Any], None]] = None) -> Any:
        with self._lock:
            if self._closed:
                raise self._lost()
            self._next_id += 1
            mid = self._next_id
            slot = [threading.Event(), None, on_partial]
            self._slots[mid] = slot
        out = dict(msg)
        out["id"] = mid
        try:
            with self._send_lock:
                if raw_body is not None:
                    wire.send_raw_msg(self._sock, out, raw_body,
                                      self._token)
                else:
                    wire.send_msg(self._sock, out, self._token)
        except wire.WireError:
            # Encode-time rejection (oversized raw meta/frame), raised
            # BEFORE any bytes hit the socket: the connection is still
            # good and no other call is disturbed — release the slot
            # and surface it as deterministic for THIS payload, never
            # as a dead peer.
            with self._lock:
                self._slots.pop(mid, None)
            raise
        except OSError as e:
            with self._lock:
                self._slots.pop(mid, None)
            self._fail(f"send failed: {e}")
            raise ConnectionLost(str(e)) from e
        if not slot[0].wait(timeout):
            with self._lock:
                self._slots.pop(mid, None)
                # The reply may have raced the timeout (the reader
                # stores it under this lock) — honor it if so.
                if slot[1] is not None:
                    return slot[1]
            raise CallTimeout(f"no reply from {self.addr} "
                              f"within {timeout}s")
        if slot[1] is None:     # woken by _fail, not by a reply
            raise self._lost()
        return slot[1]

    def _read_loop(self) -> None:
        # We dialed this peer ourselves; raw replies (a prefill
        # replica's KV export) are expected on mux links.
        framer = wire.Framer(self._token, allow_raw=True)
        try:
            for msg in wire.iter_msgs(self._sock, framer):
                if isinstance(msg, wire.RawFrame):
                    head = msg.meta if isinstance(msg.meta, dict) else {}
                elif isinstance(msg, dict):
                    head = msg
                else:
                    continue
                mid = head.get("id")
                if head.get("op") == "tokens":
                    # A streaming PARTIAL: dispatch to the call's
                    # callback WITHOUT resolving the slot — the final
                    # completion still lands through the normal path.
                    with self._lock:
                        slot = self._slots.get(mid)
                        cb = slot[2] if slot is not None else None
                    if cb is not None:
                        try:
                            cb(msg)
                        except Exception:
                            pass    # a broken consumer costs its stream
                    continue
                with self._lock:
                    # The reply lands under the lock so a caller whose
                    # wait() just timed out still finds it (its own pop
                    # serializes after this one).
                    slot = self._slots.pop(mid, None)
                    if slot is not None:
                        slot[1] = msg
                if slot is not None:
                    slot[0].set()
            self._fail("EOF from peer")
        except (OSError, wire.WireError) as e:
            self._fail(str(e))
        except BaseException as e:  # noqa: BLE001 - reader must not die
            # An unexpected reader death (a bug, not the transport):
            # waiters would otherwise ride their FULL per-call timeout
            # for replies nobody can deliver anymore.  Fail them all
            # NOW, distinguishably.
            self._fail(f"reader thread died: {e!r}", died=True)
            raise

    def _fail(self, why: str, died: bool = False) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._error = why
            self._reader_died = died
            pending: List[list] = list(self._slots.values())
            self._slots.clear()
        # shutdown before close: when _fail comes from close()/a send
        # error, the reader thread is still blocked in recv and close()
        # alone would leave it parked (and the peer unaware) until the
        # kernel's recv reference drains.
        wire.shutdown_socket(self._sock)
        try:
            self._sock.close()
        except OSError:
            pass
        for slot in pending:    # wake every waiter; slot[1] stays None
            slot[0].set()

    def close(self) -> None:
        self._fail("closed by caller")


class FleetClient:
    """Caller-side handle on a fleet gateway (or a SET of them).

    Thread-safe: many threads may ``generate()`` concurrently over the
    one multiplexed connection.  Overload rejections surface as
    :class:`~tfmesos_tpu.fleet.admission.Overloaded` — the explicit
    backpressure signal callers are expected to handle (back off,
    retry later, or spill).

    ``addr`` may be one ``host:port`` or a list of them (a
    multi-gateway fleet, ``tfserve --gateways N``): the client connects
    to the first reachable gateway, refreshes the full set through the
    ``gateways`` discovery op, and — when its gateway dies mid-stream —
    fails over by REPLAYING idempotent in-flight ``generate`` calls on
    a survivor (streamed tokens de-duplicated by offset, so
    ``on_tokens`` sees each token exactly once).  ``max_failovers``
    bounds the replays per call; 0 disables failover entirely (the
    single-gateway behavior of old)."""

    def __init__(self, addr: Union[str, Sequence[str]], token: str = "",
                 timeout: float = 120.0, connect_timeout: float = 10.0,
                 max_failovers: int = 2):
        addrs = [addr] if isinstance(addr, str) else list(addr)
        if not addrs:
            raise ValueError("FleetClient needs at least one gateway "
                             "address")
        self.addr = addrs[0]
        self.timeout = timeout
        self.connect_timeout = float(connect_timeout)
        self.max_failovers = int(max_failovers)
        self._token = token
        self.log = get_logger("tfmesos_tpu.fleet.client")
        self._mlock = threading.Lock()
        self._addrs: List[str] = addrs
        self._mux: Optional[MuxConnection] = None
        self._closed = False
        # Dial eagerly (constructor-raises-on-unreachable is the
        # contract tests and tfserve rely on), trying each address.
        self._connection()

    # -- connection management ---------------------------------------------

    @property
    def addrs(self) -> List[str]:
        """The currently known gateway set (discovery-refreshed)."""
        with self._mlock:
            return list(self._addrs)

    def _connection(self) -> MuxConnection:
        """The live mux, dialing down the known-gateway list if the
        current one is gone.  Raises the last dial error when every
        address fails.  Dials happen OUTSIDE the lock: a blocked
        connect (up to connect_timeout per dead address) must not
        stall every other caller — including close() — on the lock; a
        dial race keeps the first registered connection and closes the
        loser."""
        with self._mlock:
            if self._closed:
                raise ConnectionLost("client closed")
            mux = self._mux
            if mux is not None and not mux.closed:
                return mux
            addrs = list(self._addrs)
        last: Optional[Exception] = None
        for a in addrs:
            try:
                mux = MuxConnection(a, self._token,
                                    connect_timeout=self.connect_timeout)
            except OSError as e:
                last = e
                continue
            with self._mlock:
                if self._closed:
                    mux.close()
                    raise ConnectionLost("client closed")
                cur = self._mux
                if cur is not None and not cur.closed:
                    mux.close()     # lost the race; use the winner
                    return cur
                self._mux = mux
                self.addr = a
            return mux
        raise ConnectionLost(
            f"no gateway reachable among {addrs}: {last}")

    def _drop(self, mux: MuxConnection) -> None:
        """Forget a dead connection and rotate its address to the back
        of the list so the next dial tries a different gateway first."""
        with self._mlock:
            if self._mux is mux:
                self._mux = None
            if mux.addr in self._addrs and len(self._addrs) > 1:
                self._addrs.remove(mux.addr)
                self._addrs.append(mux.addr)
        mux.close()

    def _refresh_gateways(self) -> None:
        """Best-effort discovery: merge the gateway's own view of the
        fleet's front doors into ours (new gateways become failover
        targets without a client restart)."""
        try:
            mux = self._connection()
            reply = mux.call({"op": "gateways"}, timeout=5.0)
        except Exception:
            return
        if not isinstance(reply, dict):
            return
        got = reply.get("gateways")
        if not isinstance(got, list):
            return
        fresh = [a for a in got if isinstance(a, str) and a]
        if not fresh:
            return
        with self._mlock:
            known = set(self._addrs)
            self._addrs.extend(a for a in fresh if a not in known)

    def gateways(self, timeout: float = 10.0) -> List[str]:
        """The fleet's registered gateway addresses (the ``gateways``
        discovery op — ``tfserve gateways``)."""
        reply = self._connection().call({"op": "gateways"},
                                        timeout=timeout)
        if isinstance(reply, dict) and isinstance(
                reply.get("gateways"), list):
            return [a for a in reply["gateways"] if isinstance(a, str)]
        return []

    # -- requests ----------------------------------------------------------

    def generate(self, prompt, max_new_tokens: int,
                 stop_token: Optional[int] = None,
                 timeout: Optional[float] = None,
                 priority: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 trace=None,
                 session: Optional[str] = None,
                 model: Optional[str] = None,
                 on_tokens: Optional[Callable[[List[int]], None]] = None
                 ) -> Dict[str, Any]:
        """One generation request; returns the completion dict
        (``tokens``, ``ttft_ms``, ``total_ms``, ``trace_id``).  Raises
        ``Overloaded`` on shed, :class:`RequestFailed` on any other
        error reply.  ``priority`` names the gateway admission class
        this request rides in (e.g. ``"background"``); unlabeled
        requests take the fleet's default (first-listed) class.
        ``deadline_ms`` is the END-TO-END budget from gateway receipt:
        expired work is shed in the admission queue, failed fast by the
        router, and cancelled inside the replicas (surfacing here as
        :class:`RequestFailed` with kind ``deadline_exceeded``); no
        deadline preserves the flat server-side timeout behavior.
        ``trace`` asks the fleet to retain FULL span detail for this
        request's trace: ``True`` under a gateway-minted id, a string
        to supply the trace id yourself; every request is
        summary-traced regardless, and the reply's ``trace_id`` (also
        set on raised ``Overloaded``/``RequestFailed`` exceptions)
        fetches the waterfall via :meth:`trace` / ``tfserve trace``.
        ``session`` names a multi-turn conversation: on a KV-tiered
        fleet (``tfserve --kv-tier-mb``) the finished request's KV
        parks under the id and a later call whose prompt EXTENDS the
        conversation (prior prompt + returned tokens + the new turn)
        resumes from it — prefilling only the new tail — routed to the
        replica holding the parked state (session affinity).  The
        completion is byte-identical either way; the label is purely a
        latency hint (docs/SERVING.md "KV tiering & sessions").
        ``on_tokens(new_tokens)`` streams the completion INCREMENTALLY:
        called (from the reader thread — do not block) with each fresh
        chunk as the replica's batcher emits it, exactly-once per token
        even across a mid-stream gateway failover; the returned
        completion still carries the full list."""
        msg = {"op": "generate", "prompt": [int(t) for t in prompt],
               "max_new_tokens": int(max_new_tokens),
               "stop_token": stop_token}
        if priority is not None:
            msg["priority"] = str(priority)
        if deadline_ms is not None:
            if not deadline_ms > 0:
                raise ValueError(f"deadline_ms must be > 0, got "
                                 f"{deadline_ms}")
            msg["deadline_ms"] = float(deadline_ms)
        if trace is not None and trace is not False:
            msg["trace"] = str(trace) if isinstance(trace, str) else True
        if session is not None:
            if not isinstance(session, str) or not session:
                raise ValueError(f"session must be a non-empty string, "
                                 f"got {session!r}")
            msg["session"] = session
        if model is not None:
            # Model-catalog label (docs/SERVING.md "Model catalog"):
            # names the catalog entry this request targets; absent
            # rides the fleet's DEFAULT entry, so model-less callers
            # need no change against a catalog fleet.
            if not isinstance(model, str) or not model:
                raise ValueError(f"model must be a non-empty string, "
                                 f"got {model!r}")
            msg["model"] = model

        on_partial = None
        if on_tokens is not None:
            msg["stream"] = True
            # Exactly-once across retries/replays: a replayed request
            # re-streams from offset 0 (deterministic completions), so
            # only tokens past the high-water mark reach the caller.
            seen = [0]
            lock = threading.Lock()

            def on_partial(frame) -> None:
                toks = frame.get("tokens")
                if not isinstance(toks, list) or not toks:
                    return
                off = frame.get("off")
                off = int(off) if isinstance(off, (int, float)) \
                    and not isinstance(off, bool) else 0
                with lock:
                    start = max(0, seen[0] - off)
                    new = toks[start:]
                    if not new:
                        return
                    seen[0] = max(seen[0], off + len(toks))
                    # Deliver INSIDE the lock: it is this stream's own
                    # lock (never contended across requests), and
                    # releasing first would let a failover's new reader
                    # overtake a preempted old one — out-of-order
                    # chunks at the caller.
                    on_tokens([int(t) for t in new])

        timeout = timeout if timeout is not None else self.timeout
        reply = None
        for attempt in range(self.max_failovers + 1):
            mux = self._connection()
            try:
                reply = mux.call(msg, timeout=timeout,
                                 on_partial=on_partial)
                break
            except ConnectionLost as e:
                # The gateway died with this call in flight (or before
                # it could be sent).  generate is idempotent — nothing
                # was delivered, completions are deterministic, and
                # streamed tokens de-dup by offset — so REPLAY it on a
                # surviving gateway.  A deliberate client close() is
                # NOT a gateway death: never replay a cancelled call.
                if self._closed:
                    raise
                self._drop(mux)
                if attempt >= self.max_failovers:
                    raise
                self.log.warning(
                    "gateway %s lost mid-request (%s); failing over "
                    "(attempt %d/%d)", mux.addr, e, attempt + 1,
                    self.max_failovers)
                self._refresh_gateways()
        if isinstance(reply, dict) and reply.get("op") == "completion":
            if on_partial is not None:
                # The final completion carries the FULL token list;
                # feeding it through the same offset de-dup emits
                # exactly the not-yet-streamed tail (a row that
                # finishes inside a decode block streams its last
                # chunk only here — and an old non-streaming replica
                # degenerates to one on_tokens call with everything).
                on_partial({"tokens": reply.get("tokens") or [],
                            "off": 0})
            return reply
        kind = reply.get("kind", "error") if isinstance(reply, dict) else "error"
        error = reply.get("error", repr(reply)) if isinstance(reply, dict) \
            else repr(reply)
        tid = reply.get("trace_id") if isinstance(reply, dict) else None
        if kind == "rate_limited":
            exc: Exception = RateLimited(error)
        elif kind == "overloaded":
            exc = Overloaded(error)
        else:
            exc = RequestFailed(error, kind=kind)
        exc.trace_id = tid
        raise exc

    def trace(self, trace_id: Optional[str] = None,
              slowest: Optional[int] = None, failed: bool = False,
              limit: int = 20, timeout: float = 10.0) -> list:
        """Fetch trace records from the gateway's book: one by id (full
        waterfall), the N ``slowest``, the newest ``failed``, or the
        recent summaries (docs/SERVING.md "Observability")."""
        msg: Dict[str, Any] = {"op": "trace", "limit": int(limit)}
        if trace_id:
            msg["trace_id"] = str(trace_id)
        elif slowest:
            msg["slowest"] = int(slowest)
        elif failed:
            msg["failed"] = True
        reply = self._connection().call(msg, timeout=timeout)
        if isinstance(reply, dict):
            return reply.get("traces") or []
        return []

    def metrics(self, timeout: float = 10.0) -> Dict[str, Any]:
        """The gateway's live metrics snapshot."""
        reply = self._connection().call({"op": "metrics"},
                                        timeout=timeout)
        return reply.get("snapshot", {})

    def rollout(self, weights_version: str,
                timeout: float = 900.0) -> Dict[str, Any]:
        """Drive a blue-green weight rollout through the gateway's
        control op and block until it completes (a rollout spans a full
        tier's warmup plus the old tier's drain — size ``timeout``
        accordingly).  Returns the gateway's summary dict; raises
        :class:`RequestFailed` (kind ``rollout_failed``) on abort.
        NEVER replayed on failover: a rollout is not idempotent (the
        second attempt would race the first's drains)."""
        reply = self._connection().call(
            {"op": "rollout", "weights_version": str(weights_version)},
            timeout=timeout)
        if isinstance(reply, dict) and reply.get("op") == "rollout":
            return reply
        kind = reply.get("kind", "error") if isinstance(reply, dict) \
            else "error"
        error = reply.get("error", repr(reply)) if isinstance(reply, dict) \
            else repr(reply)
        raise RequestFailed(error, kind=kind)

    def swap_adapter(self, model_id: str, adapter_version: str,
                     delta: Dict[str, Any],
                     timeout: float = 900.0) -> Dict[str, Any]:
        """Hot-swap a LoRA-style weight delta onto every replica of
        one catalog model through the gateway's control op and block
        until every replica has folded it (in-flight requests finish
        on the old delta first — size ``timeout`` for a generation's
        tail).  ``delta`` maps param paths to numpy arrays; it ships
        base64 to the gateway and as raw HMAC frames to the replicas.
        NEVER replayed on failover (like rollout — the second attempt
        would race the first's folds)."""
        from tfmesos_tpu.fleet.catalog import encode_adapter_fields

        reply = self._connection().call(
            {"op": "swap_adapter", "model_id": str(model_id),
             "adapter_version": str(adapter_version),
             "delta": encode_adapter_fields(delta)},
            timeout=timeout)
        if isinstance(reply, dict) and reply.get("op") == "swap_adapter":
            return reply
        kind = reply.get("kind", "error") if isinstance(reply, dict) \
            else "error"
        error = reply.get("error", repr(reply)) \
            if isinstance(reply, dict) else repr(reply)
        raise RequestFailed(error, kind=kind)

    @property
    def outstanding(self) -> int:
        with self._mlock:
            mux = self._mux
        return mux.outstanding if mux is not None else 0

    def close(self) -> None:
        """Terminal: in-flight calls fail with ConnectionLost (never
        replayed — a cancelled call must not resurrect the
        connection), and later calls raise instead of re-dialing."""
        with self._mlock:
            self._closed = True
            mux = self._mux
            self._mux = None
        if mux is not None:
            mux.close()

    def __enter__(self) -> "FleetClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
