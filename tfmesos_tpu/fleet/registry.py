"""Replica registry: who is serving, how loaded, and are they alive.

Replicas dial the registry and stream heartbeats over the authenticated
wire protocol (the same HMAC framing the rendezvous uses — an
unauthenticated process cannot register itself into the serving path).
Liveness is graded, not boolean:

* ``warming``  — registered and heartbeating with ``status: warming``
  (the replica is still compiling its jitted entry points —
  ``ContinuousBatcher.warmup``); NOT eligible for requests yet.  The
  replica flips itself to alive by simply dropping the status field
  once warmup returns.
* ``alive``    — heartbeating; eligible for new requests.
* ``draining`` — heartbeats stale (or the replica announced a drain);
  no NEW requests are routed, in-flight ones may still finish.
  A drain announcement beats ``warming`` — an exiting replica must
  never re-enter the routable path through a late warming beat window.
* ``dead``     — hard heartbeat timeout, heartbeat-connection EOF (the
  usual signal of process death, since the connection lives inside the
  replica), or the router observed a connection failure.  Dead entries
  are EVICTED from the table after a grace window.

A dead/draining replica that heartbeats again is revived (to alive, or
to warming if the beat still says so) — so a transient network blip
(or an overeager router ``mark_dead``) self-heals instead of requiring
operator action.  A malformed ``status`` field costs the field, not
the beat: the beat still counts for liveness and the state defaults to
alive, exactly like the other optional heartbeat fields.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time
from typing import Any, Dict, List, Optional

from tfmesos_tpu import wire
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["WARMING", "ALIVE", "DRAINING", "DEAD", "UNIFIED", "PREFILL",
           "DECODE", "KV", "ROLES", "MODEL_ID_RE", "validate_model_id",
           "ReplicaInfo", "ReplicaRegistry"]

WARMING = "warming"
ALIVE = "alive"
DRAINING = "draining"
DEAD = "dead"


UNIFIED = "unified"
PREFILL = "prefill"
DECODE = "decode"
#: dedicated KV-fabric replicas: jax-free artifact holders (a
#: KVTierStore behind the replica wire surface, no batcher) that park
#: other replicas' sessions — never routable for generate/prefill (no
#: router tier picks the role), but first-choice fabric targets.
KV = "kv"
ROLES = (UNIFIED, PREFILL, DECODE, KV)

#: model ids share ``weights_version``'s charset and for the same
#: reason: the label joins a ``shell=True`` Mode-B replica command
#: line (``--model-id``) and becomes a Prometheus metric-name
#: component, so the charset is a SECURITY boundary, not cosmetics.
#: fullmatch, never match-with-$ ('$' would accept a trailing newline
#: that shell=True reads as a command terminator).
MODEL_ID_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9._-]{0,63}")


def validate_model_id(model_id: str) -> str:
    """The one model-id gate every ingress shares (catalog, CLI,
    gateway op, replica argv); raises ``ValueError`` with the charset
    spelled out."""
    if not isinstance(model_id, str):
        raise TypeError(f"model_id must be a string, got "
                        f"{type(model_id).__name__}")
    if not MODEL_ID_RE.fullmatch(model_id):
        raise ValueError(
            f"model_id {model_id!r} is not a valid label: want 1-64 "
            f"chars of [A-Za-z0-9._-] starting alphanumeric (it joins "
            f"the replica command line and Prometheus metric names, so "
            f"the charset is a security boundary)")
    return model_id


@dataclasses.dataclass
class ReplicaInfo:
    """One serving replica as the registry sees it."""

    addr: str               # host:port the replica serves requests on
    capacity: int = 0       # concurrent rows it can decode
    outstanding: int = 0    # its own in-flight count, self-reported
    state: str = ALIVE
    last_beat: float = 0.0  # monotonic time of the last heartbeat
    # Prefix-cache summary piggybacked on heartbeats ({page, first,
    # seed, hashes} per serving.prefix_cache_summary) — what the
    # router's prefix-affinity choice matches prompts against.  None
    # until the replica advertises one.
    prefix: Optional[dict] = None
    # KV-tier summary (fleet/kvtier.py), another heartbeat field:
    # parked session ids (the router's session-affinity key), spilled
    # prefix digests in the same summary shape as ``prefix`` (so the
    # affinity matcher can steer shared prompts at TIER-resident pages
    # too), plus counters/occupancy for the gateway's kv_tier gauge.
    kv_tier: Optional[dict] = None
    # Speculative-decoding summary piggybacked on heartbeats
    # ({acceptance_rate, rounds, row_rounds, committed, n_draft}) —
    # the draft acceptance rate is THE spec-serving health number, and
    # this is how it becomes visible fleet-wide (the gateway's ``spec``
    # gauge aggregates it).  None until a draft-equipped replica
    # advertises one.
    spec: Optional[dict] = None
    # Disaggregated serving: the replica's advertised tier (prefill /
    # decode / unified — unified when it never says) and its free-KV-
    # page headroom, both heartbeat fields.  Decode-tier routing places
    # imported prefills by headroom; -1 = never advertised.
    role: str = UNIFIED
    kv_headroom: int = -1
    # The replica announced a drain (operator intent, not staleness).
    # While set, a late ``status: warming`` beat must NOT revive the
    # entry — an exiting replica never re-enters through its own
    # warmup; only a plain (routable) beat clears it.
    announced_drain: bool = False
    # Drain-for-scale-down vs drain-for-death: a PINNED drain is set by
    # the control plane (autoscaler shrink, rollout reap) on a replica
    # that is still healthy and heartbeating — its plain alive beats
    # refresh liveness but must NOT revive it to routable while its
    # outstanding work flushes.  The pin dies with the process (a beat
    # after DEAD is a new process) or is reset by a beat carrying a
    # weights_version DIFFERENT from the one pinned (a relaunch with
    # upgraded weights on a reused addr must not inherit a stale drain).
    drain_pinned: bool = False
    pinned_version: str = ""
    # Blue-green rollout identity, both heartbeat fields: the weights
    # version this replica serves (rides the hello and every beat — the
    # router's version-preference tier keys off it) and the launch
    # generation it was fenced into (PR 3's epoch, via
    # TPUMESOS_GENERATION); -1 / "" = never advertised.
    weights_version: str = ""
    gen: int = -1
    # The scheduler-side identity ("job:index") of the Mode-B task this
    # replica runs under — how the control plane maps a registry addr
    # back to a killable task.
    node: str = ""
    # Model catalog (docs/SERVING.md "Model catalog"), all heartbeat
    # fields: the model this replica serves ("" = model-less — the
    # single-model fleet of old, or a warm-pool member awaiting
    # adoption), whether it is an undedicated WARM-POOL member (alive
    # and pre-warmed but excluded from every router pick until the
    # trader assigns it a model), and the last adapter delta folded
    # into its weights ("" = base weights) — a suspended mid-stream
    # export may only resume under the SAME adapter version.
    model_id: str = ""
    warm_pool: bool = False
    adapter_version: str = ""
    # Gang replicas (docs/SERVING.md "Gang replicas"), all heartbeat
    # fields carried in one ``gang`` dict on the LEADER's beats: the
    # gang's launch label (scheduler add_gang identity), how many
    # member tasks form the mesh (1 = the single-process replica of
    # old), how many members are currently joined to the leader
    # (-1 = never advertised), and the leader's member-rendezvous
    # address — what ``gang_lookup`` hands a booting member.  The
    # fleet routes to the LEADER only; members never register here.
    gang_id: str = ""
    gang_size: int = 1
    gang_live: int = -1
    gang_coord: str = ""


def _advertises_prefix(rep: "ReplicaInfo") -> int:
    """1 when this entry carries prompt-matchable prefix digests — a
    device prefix-cache summary OR a KV tier's spilled-page summary —
    the quantity the router's O(1) affinity-scan gate counts."""
    if rep.prefix is not None:
        return 1
    if isinstance(rep.kv_tier, dict) and rep.kv_tier.get("prefix"):
        return 1
    return 0


class ReplicaRegistry:
    """Heartbeat listener + liveness sweeper over a replica table.

    ``clock`` is injectable (the chaos/autoscaler determinism
    discipline): production runs on ``time.monotonic``; the fleet
    simulator (:mod:`tfmesos_tpu.fleet.sim`) runs the same table code
    on a virtual clock, delivering beats through :meth:`observe` and
    driving liveness with :meth:`sweep` instead of the listener/sweeper
    threads (``start()`` is never called there — no sockets exist)."""

    def __init__(self, token: str = "", host: str = "127.0.0.1",
                 suspect_after: float = 1.5, dead_after: float = 3.0,
                 evict_after: float = 10.0, sweep_interval: float = 0.2,
                 metrics=None, chaos=None, clock=time.monotonic):
        self.token = token
        self.host = host
        self.suspect_after = float(suspect_after)
        self.dead_after = float(dead_after)
        self.evict_after = float(evict_after)
        self.sweep_interval = float(sweep_interval)
        self.metrics = metrics
        # Optional chaos.FaultPlan: consulted per heartbeat so tests can
        # drop beats (simulated partitions) without touching the replica.
        self.chaos = chaos
        self._clock = clock
        self.log = get_logger("tfmesos_tpu.fleet.registry")
        self.addr: Optional[str] = None
        self._server: Optional[wire.WireServer] = None
        self._table: Dict[str, ReplicaInfo] = {}
        self._conns: Dict[str, object] = {}
        # Registered fleet front doors (the `gateways` discovery op):
        # each Gateway registers its addr at start and removes it on a
        # GRACEFUL stop — a killed gateway stays listed (discovery is
        # best-effort; client failover skips dead entries itself).
        # Front-door discovery set.  Values carry liveness: ``None`` is
        # a PERMANENT entry (registered in-process by the launcher —
        # its stop() unregisters it); a float is an EXPIRY deadline for
        # a wire-registered gateway process, refreshed by its periodic
        # ``register_gateway`` frames and swept like a heartbeat — a
        # SIGKILLed gateway process falls out of discovery on its own.
        # Keyed by the LEASE key — the process's private scrape addr
        # when it has one, else the public addr — because with
        # SO_REUSEPORT N processes share ONE public addr and each still
        # needs its own lease (and its own metrics scrape target).
        # Values are (public_addr, expiry-or-None).
        self._gateways: Dict[str, tuple] = {}
        # Membership version + cached routable views: bumped ONLY when
        # the set a router pick iterates could change (entry add/evict,
        # state or role transition) — NOT on per-beat field refreshes
        # (outstanding, kv_headroom), which the cached entries reflect
        # live.  This is what keeps routing O(1) per request at
        # 1000-replica scale instead of copying the whole table per
        # pick (see alive_view).
        self._version = 0
        self._views: Dict[tuple, tuple] = {}
        # Count of entries advertising a prefix-cache summary: the
        # router skips its O(replicas) affinity scan entirely while
        # this is zero (the common non-prefix-cache deployment).
        self._prefix_count = 0
        # Count of warm-pool members: the router's O(1) gate in front
        # of its pool-exclusion filter (a fleet without a warm pool
        # must not pay a per-pick scan for it).
        self._pool_count = 0
        # Generation fence floor: beats stamped with a gen BELOW this
        # are dropped entirely — a straggler of a reaped rollout
        # generation can never re-register and serve stale weights.
        self._min_gen: int = 0
        self._fence_logged: set = set()
        # Per-role replica targets (what the control plane WANTS), shown
        # next to actuals in role_summary so the roles gauge reads as
        # target-vs-actual at a glance.
        self._targets: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReplicaRegistry":
        # The intake is a WireServer event loop: every heartbeat
        # connection of the whole fleet rides ONE selector thread
        # instead of one blocked-in-recv thread per replica — at
        # 1000-replica scale the thread-per-connection registry was the
        # second front-door ceiling after the gateway (docs/SERVING.md
        # "Front-door scaling").
        self._server = wire.WireServer(
            self._on_msg, token=self.token, host=self.host,
            name="registry", on_close=self._on_conn_close,
            advertise_host=(None if self.host in ("0.0.0.0", "::")
                            else self.host)).start()
        self.addr = self._server.addr
        self.log.info("replica registry listening on %s (event-loop "
                      "I/O)", self.addr)
        s = threading.Thread(target=self._sweep_loop,
                             name="registry-sweep", daemon=True)
        s.start()
        self._threads = [s]
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.stop()
        with self._lock:
            self._conns.clear()
        for t in self._threads:
            t.join(timeout=2.0)

    # -- heartbeat intake --------------------------------------------------

    def _on_msg(self, conn, msg) -> None:
        """Event-loop handler: apply one frame to the table.  A bad
        frame (wrong token, oversize) never reaches here — the
        WireServer's Framer rejects it and drops the connection, same
        pre-auth discipline as the threaded loop had."""
        if isinstance(msg, dict) and msg.get("op") == "gang_lookup":
            # Member rendezvous: a booting gang member polls for its
            # leader's coordination address (the leader advertises it
            # in the ``gang`` field of its beats).  Served on the
            # heartbeat socket — the one address every launched task
            # already knows.
            try:
                conn.send(self.gang_lookup(msg.get("gang_id")))
            except Exception as e:
                self.log.warning("gang_lookup reply failed: %s", e)
            return
        if isinstance(msg, dict) and msg.get("op") in ("kv_peers",
                                                       "kv_locate"):
            # KV-fabric placement queries, served on the heartbeat
            # socket like gang_lookup: ``kv_peers`` lists replication
            # targets, ``kv_locate`` resolves which hosts currently
            # advertise an artifact (the registry-driven placement map
            # that lets a resume find surviving copies after the
            # parker died).
            try:
                if msg["op"] == "kv_peers":
                    conn.send(self.kv_peers())
                else:
                    conn.send(self.kv_locate(msg.get("kind"),
                                             msg.get("key")))
            except Exception as e:
                self.log.warning("%s reply failed: %s", msg["op"], e)
            return
        if isinstance(msg, dict) and msg.get("op") == "registry_view":
            # The multi-process gateway sidecar's poll: the whole table
            # as heartbeat-shaped dicts it replays into its local
            # registry, plus the gateway discovery set.  Served on the
            # heartbeat socket like every other read — a gateway
            # process is just one more wire peer.
            try:
                conn.send(self.registry_view())
            except Exception as e:
                self.log.warning("registry_view reply failed: %s", e)
            return
        if isinstance(msg, dict) and msg.get("op") == "register_gateway":
            # A gateway PROCESS leasing itself into discovery; always
            # TTL'd (clamped) — only the in-process launcher path may
            # create permanent entries, so a wire peer can never park
            # an unreapable address in the discovery set.
            gaddr = msg.get("addr")
            if isinstance(gaddr, str) and gaddr and len(gaddr) <= 256:
                raw_ttl = msg.get("ttl")
                try:
                    ttl = float(raw_ttl) if raw_ttl is not None else 10.0
                except (TypeError, ValueError):
                    ttl = 10.0
                scrape = msg.get("scrape")
                if not (isinstance(scrape, str) and scrape
                        and len(scrape) <= 256):
                    scrape = None
                self.register_gateway(gaddr,
                                      ttl=max(1.0, min(ttl, 300.0)),
                                      scrape=scrape)
                try:
                    conn.send({"op": "gateway_registered", "addr": gaddr})
                except Exception as e:
                    self.log.warning("register_gateway reply failed: %s",
                                     e)
            return
        addr = self.observe(msg, conn)
        if addr is not None:
            # Remember which replica this connection speaks for, so its
            # EOF can be attributed (the earliest death signal).
            conn.replica_addr = addr

    def _on_conn_close(self, conn) -> None:
        if self._stop.is_set():
            return
        addr = getattr(conn, "replica_addr", None)
        if addr is None:
            return
        # The heartbeat connection lives INSIDE the replica process;
        # its EOF is the earliest death signal we get — far ahead of
        # the heartbeat timeout.  (A reconnecting replica re-registers
        # through a new connection, which replaces this one in _conns
        # first.)
        with self._lock:
            stale = self._conns.get(addr) is conn
            if stale:
                del self._conns[addr]
        if stale:
            self.mark_dead(addr, why="heartbeat connection closed")

    def observe(self, msg, conn=None) -> Optional[str]:
        """Apply one registry message (``hello`` / ``heartbeat`` /
        ``drain``) to the table.  The wire path calls this per received
        frame (``conn`` is the event loop's ``WireConn``); the fleet
        simulator calls it directly with ``conn=None`` — beats from
        simulated replicas run the exact same table logic, fences and
        all."""
        if not isinstance(msg, dict):
            return None
        addr = msg.get("addr")
        op = msg.get("op")
        if not addr or op not in ("hello", "heartbeat", "drain"):
            self.log.warning("unexpected registry message: %r", msg)
            return None
        # Beat-bearing messages only ("hello" IS the first beat — the
        # table code below treats them identically); a "drain" is an
        # operator intent, not liveness, and must neither count toward
        # nor be swallowed by heartbeat faults.
        if (op != "drain" and self.chaos is not None
                and self.chaos.on_heartbeat(addr)):
            return None         # chaos drop: the beat never arrived
        # Optional rollout-identity fields, parsed up front: the
        # generation fence must see ``gen`` before the beat can touch
        # the table, and the pinned-drain reset keys off the beat's
        # ``weights_version``.  Malformed values cost the field, never
        # the beat.
        gen: Optional[int] = None
        if "gen" in msg:
            try:
                gen = int(msg["gen"])
            except (TypeError, ValueError):
                gen = None
        wv: Optional[str] = None
        raw_wv = msg.get("weights_version")
        # bool is an int subclass: True must cost the FIELD (like any
        # malformed value), not coerce to the version label "True" —
        # which could spuriously match the relaunch-with-new-weights
        # heuristic and clear a pinned scale-down drain.
        if (isinstance(raw_wv, (str, int, float))
                and not isinstance(raw_wv, bool)):
            wv = str(raw_wv)
        # The beat's announced state: ``status: warming`` marks a
        # replica still compiling (ContinuousBatcher.warmup) — present
        # and heartbeating, but not routable; anything else (including
        # a malformed status) costs the FIELD, not the beat, and the
        # state defaults to alive like every other optional field.
        target = WARMING if msg.get("status") == WARMING else ALIVE
        with self._lock:
            if gen is not None and gen < self._min_gen:
                # Generation fence (blue-green rollout): this process
                # belongs to a reaped generation — its beats (hello
                # included: a straggler RE-REGISTERING) are dropped
                # whole, so it can never re-enter the table and serve
                # stale weights.  Its entry, if any, goes stale → dead
                # → evicted on the sweeper's clocks.
                if addr not in self._fence_logged:
                    self._fence_logged.add(addr)
                    self.log.warning(
                        "dropping fenced beat from %s (generation %d < "
                        "fence %d): stale-weights straggler", addr, gen,
                        self._min_gen)
                return None
            rep = self._table.get(addr)
            if op == "drain":
                if rep is not None and rep.state in (ALIVE, WARMING):
                    rep.state = DRAINING
                    rep.announced_drain = True
                    self._version += 1
                    self.log.info("replica %s draining", addr)
                return addr
            if rep is None:
                rep = self._table[addr] = ReplicaInfo(addr=addr,
                                                      state=target)
                self._version += 1
                self.log.info("replica %s registered (%s)", addr, target)
            if rep.state == DEAD:
                # A DEAD entry's beat comes from a NEW process on the
                # old addr (or a revived one whose drain is moot) — the
                # announced drain died with the process, so honor the
                # beat's own status: a relaunched replica on a reused
                # port must show as warming, not stay pinned dead.
                rep.announced_drain = False
                rep.drain_pinned = False
            if (rep.drain_pinned and wv is not None
                    and wv != rep.pinned_version):
                # A scale-down drain pins the weights version it was
                # announced against; a beat advertising a DIFFERENT
                # version is a relaunch with upgraded weights on a
                # reused addr — the stale drain must not survive it.
                self.log.info("replica %s drain reset by weights_version "
                              "%s (pinned at %s)", addr, wv,
                              rep.pinned_version)
                rep.drain_pinned = False
                rep.announced_drain = False
            if rep.announced_drain and target == WARMING:
                # Drain beats warming: an exiting replica's late
                # warming beat refreshes liveness but never re-enters
                # the table's routable path.
                target = rep.state
            if rep.drain_pinned and target == ALIVE:
                # Drain-for-scale-down: the replica is healthy and
                # still heartbeating plain (routable) beats while its
                # outstanding work flushes — liveness refreshes, but
                # the control plane's drain is not its to clear.
                target = rep.state
            if rep.state != target:
                self.log.info("replica %s %s -> %s", addr, rep.state,
                              target)
                rep.state = target
                self._version += 1
            if target == ALIVE:
                rep.announced_drain = False
            if gen is not None:
                rep.gen = gen
            if wv is not None:
                rep.weights_version = wv
            if isinstance(msg.get("node"), str):
                rep.node = msg["node"]
            if "capacity" in msg:
                rep.capacity = int(msg["capacity"])
            if "outstanding" in msg:
                rep.outstanding = int(msg["outstanding"])
            if "prefix_cache" in msg or "kv_tier" in msg \
                    or "spec" in msg:
                # Prefix-advertisement accounting only when the beat
                # could change it — the plain liveness beat (the 10k-
                # replica steady state) skips both scans.
                before = _advertises_prefix(rep)
                if isinstance(msg.get("prefix_cache"), dict):
                    rep.prefix = msg["prefix_cache"]
                if isinstance(msg.get("kv_tier"), dict):
                    # A tier advertising spilled prefix digests joins
                    # the affinity-scan gate the same way a device
                    # summary does.
                    rep.kv_tier = msg["kv_tier"]
                if isinstance(msg.get("spec"), dict):
                    rep.spec = msg["spec"]
                self._prefix_count += _advertises_prefix(rep) - before
            if msg.get("role") in ROLES and rep.role != msg["role"]:
                rep.role = msg["role"]
                self._version += 1
            # Model-catalog fields.  A malformed model_id costs the
            # FIELD, not the beat (the PR 4/5 optional-field
            # convention) — and the charset check is load-bearing: the
            # value reaches Prometheus metric names and trade logs, so
            # a replica cannot smuggle an arbitrary string into the
            # table by heartbeating it.
            raw_model = msg.get("model_id")
            if isinstance(raw_model, str) \
                    and (raw_model == ""
                         or MODEL_ID_RE.fullmatch(raw_model)) \
                    and rep.model_id != raw_model:
                rep.model_id = raw_model
                self._version += 1      # per-model views change
            if "warm_pool" in msg:
                pool = msg.get("warm_pool") is True
                if rep.warm_pool != pool:
                    rep.warm_pool = pool
                    self._pool_count += 1 if pool else -1
                    self._version += 1
            raw_av = msg.get("adapter_version")
            if isinstance(raw_av, str) \
                    and (raw_av == "" or MODEL_ID_RE.fullmatch(raw_av)):
                rep.adapter_version = raw_av
            if "kv_headroom" in msg:
                try:
                    rep.kv_headroom = int(msg["kv_headroom"])
                except (TypeError, ValueError):
                    pass    # a bad field never costs the beat
            raw_gang = msg.get("gang")
            if isinstance(raw_gang, dict):
                # Gang identity rides the leader's beats as one dict;
                # each sub-field is optional and a malformed sub-field
                # costs THAT field, never the beat (the PR 4/5
                # convention).  live is clamped to [0, size] — a leader
                # cannot advertise more joined members than the gang
                # has.
                gid = raw_gang.get("id")
                if isinstance(gid, str) and len(gid) <= 128:
                    rep.gang_id = gid
                try:
                    size = int(raw_gang["size"])
                    if size >= 1:
                        rep.gang_size = size
                except (KeyError, TypeError, ValueError):
                    pass
                try:
                    live = int(raw_gang["live"])
                    if live >= 0:
                        rep.gang_live = min(live, rep.gang_size)
                except (KeyError, TypeError, ValueError):
                    pass
                coord = raw_gang.get("coord")
                if isinstance(coord, str) and len(coord) <= 128:
                    rep.gang_coord = coord
            rep.last_beat = self._clock()
            if conn is not None:
                self._conns[addr] = conn
        return addr

    # -- liveness sweeping -------------------------------------------------

    def _sweep_loop(self) -> None:
        while not self._stop.wait(self.sweep_interval):
            self.sweep()

    def sweep(self, now: Optional[float] = None) -> None:
        """One liveness pass over the table (stale → draining → dead →
        evicted).  The sweeper thread runs this every
        ``sweep_interval``; the fleet simulator calls it directly per
        virtual tick."""
        now = self._clock() if now is None else now
        with self._lock:
            for addr, rep in list(self._table.items()):
                age = now - rep.last_beat
                if age > self.evict_after:
                    del self._table[addr]
                    self._conns.pop(addr, None)
                    self._prefix_count -= _advertises_prefix(rep)
                    if rep.warm_pool:
                        self._pool_count -= 1
                    self._version += 1
                    self.log.info("replica %s evicted (%s, last beat "
                                  "%.1fs ago)", addr, rep.state, age)
                elif age > self.dead_after and rep.state != DEAD:
                    rep.state = DEAD
                    self._version += 1
                    self.log.warning("replica %s dead (no heartbeat "
                                     "for %.1fs)", addr, age)
                    if self.metrics is not None:
                        self.metrics.inc("replicas_died")
                elif age > self.suspect_after and rep.state == ALIVE:
                    rep.state = DRAINING
                    self._version += 1
                    self.log.warning("replica %s draining (heartbeat "
                                     "stale %.1fs)", addr, age)
            for key in [k for k, (_, exp) in self._gateways.items()
                        if exp is not None and exp <= now]:
                gaddr = self._gateways.pop(key)[0]
                self.log.warning("gateway %s lease expired (process "
                                 "gone?); leaving discovery", gaddr)

    # -- queries / writes --------------------------------------------------

    def alive(self) -> List[ReplicaInfo]:
        """Replicas eligible for NEW requests (copies, race-free).
        This is the ONE routability query every router tier goes
        through — warming replicas are excluded here, so no pick
        (unified, prefill, or decode) can ever select one."""
        with self._lock:
            return [dataclasses.replace(r) for r in self._table.values()
                    if r.state == ALIVE]

    def alive_view(self, roles: tuple) -> List[ReplicaInfo]:
        """The ALIVE members of the given tiers as a CACHED list of
        live table entries — the router's per-request candidate set,
        O(1) amortized at any fleet size.  The list is rebuilt only
        when membership could have changed (state/role transitions,
        adds, evictions — the version bumps above); per-beat field
        refreshes (outstanding, kv_headroom, prefix, weights_version)
        show through the shared entries immediately.  Contract: the
        returned list and its entries are SHARED — callers filter into
        new lists and never mutate (the router does exactly that)."""
        # Lock-free cache hit: dict.get and the int compare are atomic
        # under the GIL, views are REPLACED (never mutated in place),
        # and a stale read costs at worst one pick a one-version-old
        # list — the same staleness any pick already tolerates between
        # heartbeats.  This runs several times per routed request.
        hit = self._views.get(roles)
        if hit is not None and hit[0] == self._version:
            return hit[1]
        with self._lock:
            hit = self._views.get(roles)
            if hit is not None and hit[0] == self._version:
                return hit[1]
            view = [r for r in self._table.values()
                    if r.state == ALIVE and (r.role or UNIFIED) in roles]
            self._views[roles] = (self._version, view)
            return view

    def has_prefix_summaries(self) -> bool:
        """Whether ANY table entry advertises a prefix-cache summary —
        the O(1) gate in front of the router's O(candidates) affinity
        scan (a fleet with no prefix caches must not pay the scan on
        every prompt-bearing request)."""
        return self._prefix_count > 0

    def warming(self) -> List[ReplicaInfo]:
        """Replicas registered but still compiling (copies) — present
        for bring-up accounting and the gateway's gauge, invisible to
        routing."""
        with self._lock:
            return [dataclasses.replace(r) for r in self._table.values()
                    if r.state == WARMING]

    def members(self, role: Optional[str] = None,
                model: Optional[str] = None) -> List[ReplicaInfo]:
        """Every table entry (copies), optionally filtered to one tier
        and/or one model — the control plane's membership query (any
        state, unlike ``alive()``)."""
        with self._lock:
            return [dataclasses.replace(r) for r in self._table.values()
                    if (role is None or (r.role or UNIFIED) == role)
                    and (model is None or r.model_id == model)]

    def has_pool(self) -> bool:
        """Whether ANY table entry is a warm-pool member — the O(1)
        gate in front of the router's pool-exclusion filter."""
        return self._pool_count > 0

    def model_summary(self) -> Dict[str, dict]:
        """Per-model replica counts, aggregate outstanding, and
        adapter-version distribution — the gateway's ``models`` gauge
        (docs/SERVING.md "Model catalog").  Warm-pool members land
        under the ``(pool)`` row; model-less replicas under ``""``
        only when any exist (a model-less fleet reports one anonymous
        row, a catalog fleet none)."""
        out: Dict[str, dict] = {}
        with self._lock:
            for rep in self._table.values():
                label = "(pool)" if rep.warm_pool else rep.model_id
                d = out.setdefault(label, {
                    "alive": 0, "warming": 0, "draining": 0, "dead": 0,
                    "outstanding": 0, "adapters": {}})
                d[rep.state] = d.get(rep.state, 0) + 1
                if rep.state == ALIVE:
                    d["outstanding"] += rep.outstanding
                    av = rep.adapter_version or ""
                    d["adapters"][av] = d["adapters"].get(av, 0) + 1
        return out

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dataclasses.asdict(r) for r in self._table.values()]

    def role_summary(self) -> Dict[str, dict]:
        """Per-role replica counts and aggregate self-reported
        outstanding — exported as the gateway's ``roles`` gauge so
        fleet metrics (and the disagg bench) can assert each tier
        actually exists and served traffic."""
        out: Dict[str, dict] = {}
        with self._lock:
            for rep in self._table.values():
                d = out.setdefault(rep.role or UNIFIED,
                                   {"alive": 0, "warming": 0,
                                    "draining": 0, "dead": 0,
                                    "outstanding": 0, "kv_headroom": 0,
                                    "versions": {}, "gangs": 0,
                                    "gang_members": 0, "gang_live": 0})
                d[rep.state] = d.get(rep.state, 0) + 1
                if rep.gang_size > 1:
                    # Gang replicas: one table entry = one leader = N
                    # member tasks; the member-liveness sum is what an
                    # operator watches during a re-form.
                    d["gangs"] += 1
                    d["gang_members"] += rep.gang_size
                    d["gang_live"] += max(0, rep.gang_live)
                if rep.state == ALIVE:
                    d["outstanding"] += rep.outstanding
                    if rep.kv_headroom > 0:
                        d["kv_headroom"] += rep.kv_headroom
                    # Weights-version distribution of the ROUTABLE tier
                    # members — what an operator watches converge during
                    # a blue-green rollout.
                    v = rep.weights_version or ""
                    d["versions"][v] = d["versions"].get(v, 0) + 1
            for role, target in self._targets.items():
                d = out.setdefault(role, {"alive": 0, "warming": 0,
                                          "draining": 0, "dead": 0,
                                          "outstanding": 0,
                                          "kv_headroom": 0,
                                          "versions": {}, "gangs": 0,
                                          "gang_members": 0,
                                          "gang_live": 0})
                d["target"] = target
        return out

    def gang_lookup(self, gang_id) -> Dict[str, Any]:
        """Resolve one gang's leader-coordination address and launch
        generation (the member-rendezvous reply).  ``found`` stays
        False until the leader's first coord-bearing beat lands — a
        booting member polls."""
        out: Dict[str, Any] = {"op": "gang_info",
                               "gang_id": gang_id if isinstance(
                                   gang_id, str) else "",
                               "found": False}
        if not isinstance(gang_id, str) or not gang_id:
            return out
        with self._lock:
            for rep in self._table.values():
                if (rep.gang_id == gang_id and rep.gang_coord
                        and rep.state != DEAD):
                    out.update(found=True, coord=rep.gang_coord,
                               gen=rep.gen, size=rep.gang_size)
                    break
        return out

    def gang_summary(self) -> Dict[str, Any]:
        """Fleet-wide gang aggregate (the gateway's ``gangs`` gauge —
        a FLAT numeric dict, because the Prometheus exposition only
        flattens one label level): how many gang replicas the table
        holds, their summed member slots, how many members are
        currently joined, and how many gangs run degraded (fewer
        members joined than the mesh needs — the window between a
        member death and the teardown/re-form)."""
        agg = {"gangs": 0, "members": 0, "live": 0, "warming": 0,
               "degraded": 0}
        with self._lock:
            for rep in self._table.values():
                if rep.gang_size <= 1 or rep.state == DEAD:
                    # A dead gang is debris awaiting eviction, not a
                    # serving gang the gauge should count.
                    continue
                agg["gangs"] += 1
                agg["members"] += rep.gang_size
                live = max(0, rep.gang_live)
                agg["live"] += live
                if rep.state == WARMING:
                    agg["warming"] += 1
                elif rep.state == ALIVE and live < rep.gang_size:
                    # Only an ALIVE gang with members missing is
                    # degraded.  A re-forming gang (WARMING with
                    # live < size) already counts under ``warming`` —
                    # counting it degraded too would double-book the
                    # whole re-form window.
                    agg["degraded"] += 1
        return agg

    def kv_tier_summary(self) -> Dict[str, Any]:
        """Fleet-wide KV-tier aggregate (the gateway's ``kv_tier``
        gauge, reachable through ``tfserve metrics`` and the Prometheus
        exposition): summed counters
        (``kv_tier_{hits,misses,spills,promotions,park,resume}`` and
        friends), total occupancy, parked-session count, and how many
        replicas run a tier at all."""
        agg: Dict[str, Any] = {"replicas": 0, "sessions": 0,
                               "ram_bytes_used": 0, "ram_bytes": 0}
        with self._lock:
            for rep in self._table.values():
                kt = rep.kv_tier
                if not isinstance(kt, dict):
                    continue
                agg["replicas"] += 1
                sess = kt.get("sessions")
                if isinstance(sess, list):
                    agg["sessions"] += len(sess)
                for field in ("ram_bytes_used", "ram_bytes"):
                    used = kt.get(field)
                    if isinstance(used, (int, float)) \
                            and not isinstance(used, bool):
                        agg[field] += int(used)
                counters = kt.get("counters")
                if isinstance(counters, dict):
                    for k, v in counters.items():
                        if isinstance(v, (int, float)) \
                                and not isinstance(v, bool):
                            agg[k] = agg.get(k, 0) + int(v)
        return agg

    def kv_peers(self) -> Dict[str, Any]:
        """The KV fabric's replication-target list: every routable
        replica that runs a KV tier, plus every dedicated KV-role
        replica (tier or not — a booting KV holder is still a valid
        push target).  Dedicated holders sort first so ``KVFabric``
        prefers parking on hosts whose whole job is parking.  Reply is
        a plain dict served on the heartbeat socket (see ``_on_msg``)."""
        peers: List[dict] = []
        with self._lock:
            for rep in self._table.values():
                if rep.state not in (ALIVE, DRAINING):
                    continue
                role = rep.role or UNIFIED
                if role != KV and not isinstance(rep.kv_tier, dict):
                    continue
                peer = {"addr": rep.addr, "role": role,
                        "weights_version": rep.weights_version or ""}
                # Heartbeat-advertised tier fullness (0.0..1.0+), the
                # load signal behind ``placement=loaded``: parks drift
                # away from peers whose RAM tier is nearly full.
                kt = rep.kv_tier
                if isinstance(kt, dict):
                    used = kt.get("ram_bytes_used")
                    cap = kt.get("ram_bytes")
                    if isinstance(used, (int, float)) \
                            and isinstance(cap, (int, float)) \
                            and not isinstance(used, bool) \
                            and not isinstance(cap, bool) and cap > 0:
                        peer["occupancy"] = round(float(used)
                                                  / float(cap), 4)
                peers.append(peer)
        peers.sort(key=lambda p: (p["role"] != KV, p["addr"]))
        return {"op": "kv_peers", "peers": peers}

    def registry_view(self) -> Dict[str, Any]:
        """The whole table as HEARTBEAT-SHAPED dicts (plus each entry's
        current ``state`` and the gateway discovery set) — the
        multi-process gateway sidecar polls this and REPLAYS every
        entry into its process-local registry through the normal
        ``observe``/``mark_dead`` surface, so each gateway process
        routes off the same states and fences the central table holds
        without any shared memory.  Optional fields appear only when
        the replica advertised them, mirroring real beats."""
        reps: List[Dict[str, Any]] = []
        with self._lock:
            for rep in self._table.values():
                d: Dict[str, Any] = {
                    "op": "heartbeat", "addr": rep.addr,
                    "state": rep.state, "capacity": rep.capacity,
                    "outstanding": rep.outstanding, "role": rep.role,
                }
                if rep.state == WARMING:
                    d["status"] = WARMING
                if rep.weights_version:
                    d["weights_version"] = rep.weights_version
                if rep.gen >= 0:
                    d["gen"] = rep.gen
                if rep.node:
                    d["node"] = rep.node
                if rep.kv_headroom >= 0:
                    d["kv_headroom"] = rep.kv_headroom
                if isinstance(rep.prefix, dict):
                    d["prefix_cache"] = rep.prefix
                if isinstance(rep.kv_tier, dict):
                    d["kv_tier"] = rep.kv_tier
                if isinstance(rep.spec, dict):
                    d["spec"] = rep.spec
                if rep.model_id:
                    d["model_id"] = rep.model_id
                if rep.warm_pool:
                    d["warm_pool"] = True
                if rep.adapter_version:
                    d["adapter_version"] = rep.adapter_version
                if rep.gang_id or rep.gang_size > 1:
                    d["gang"] = {"id": rep.gang_id,
                                 "size": rep.gang_size,
                                 "live": rep.gang_live,
                                 "coord": rep.gang_coord}
                reps.append(d)
        return {"op": "registry_view", "replicas": reps,
                "gateways": self.gateway_addrs()}

    def kv_locate(self, kind, key) -> Dict[str, Any]:
        """Resolve which hosts currently advertise one artifact — the
        placement map a resume walks after its parker died.  Built
        from the same heartbeat-carried ``kv_tier`` summaries the
        gateway gauges read: a holder that died stops advertising
        within one sweep, so forwarding never dials a corpse for long.
        Session keys match the advertised ``sessions`` list; prefix
        keys the ``prefix.hashes`` list.  Reply always carries an
        ``addrs`` list (possibly empty) — ``KVFabric.locate`` reads
        exactly that key."""
        out: Dict[str, Any] = {"op": "kv_addrs",
                               "kind": kind if isinstance(kind, str)
                               else "",
                               "key": key if isinstance(key, str)
                               else "",
                               "addrs": []}
        if not isinstance(kind, str) or not isinstance(key, str) \
                or not key:
            return out
        with self._lock:
            for rep in self._table.values():
                if rep.state not in (ALIVE, DRAINING):
                    continue
                kt = rep.kv_tier
                if not isinstance(kt, dict):
                    continue
                if kind == "session":
                    held = kt.get("sessions")
                else:
                    pfx = kt.get("prefix")
                    held = pfx.get("hashes") if isinstance(
                        pfx, dict) else None
                if isinstance(held, list) and key in held:
                    out["addrs"].append(rep.addr)
        # Dedicated KV holders first, mirroring kv_peers: they are the
        # cheapest hosts to serve a fetch (no decode work competing).
        with self._lock:
            kv_addrs = {r.addr for r in self._table.values()
                        if (r.role or UNIFIED) == KV}
        out["addrs"].sort(key=lambda a: (a not in kv_addrs, a))
        return out

    def spec_summary(self) -> Dict[str, Any]:
        """Fleet-wide speculative-decoding aggregate (the gateway's
        ``spec`` gauge, reachable through ``tfserve metrics`` and the
        Prometheus exposition): how many replicas serve with a draft,
        summed round/commit counters, and the fleet-wide draft
        ACCEPTANCE RATE — accepted proposals over proposal
        opportunities, recomputed from the per-replica sums so
        replicas with different traffic weigh by their actual rounds.
        ``acceptance_rate`` is present only once a speculative round
        has run somewhere (a dict-gauge key that would be None is
        omitted rather than poisoning the exposition)."""
        agg: Dict[str, Any] = {"replicas": 0, "rounds": 0,
                               "committed": 0}
        row_rounds = 0
        opportunities = 0

        def _int(v):
            return (int(v) if isinstance(v, int)
                    and not isinstance(v, bool) and v >= 0 else None)

        with self._lock:
            for rep in self._table.values():
                sp = rep.spec
                if not isinstance(sp, dict):
                    continue
                agg["replicas"] += 1
                # A replica's counters fold in ATOMICALLY or not at
                # all: summing a malformed replica's committed into
                # the numerator while its row_rounds drop out of the
                # denominator would inflate the fleet rate past 1.0
                # (the mixed-version-fleet shape).
                vals = [_int(sp.get(k)) for k in
                        ("rounds", "committed", "row_rounds",
                         "n_draft")]
                if any(v is None for v in vals):
                    continue
                rounds, committed, rr, nd = vals
                agg["rounds"] += rounds
                agg["committed"] += committed
                row_rounds += rr
                opportunities += rr * nd
        if opportunities > 0:
            agg["acceptance_rate"] = round(
                (agg["committed"] - row_rounds) / opportunities, 4)
        return agg

    def register_gateway(self, addr: str,
                         ttl: Optional[float] = None,
                         scrape: Optional[str] = None) -> None:
        """Record one fleet front door for client-side discovery (the
        gateway's ``gateways`` op hands the set out; multi-gateway
        failover dials down it).  ``ttl`` (seconds) makes the entry
        LEASED — a gateway PROCESS re-registers over the wire on every
        sidecar poll, so a killed process expires out of discovery
        instead of lingering; ``None`` (the in-process default) is
        permanent until :meth:`unregister_gateway`.  ``scrape`` is the
        process's PRIVATE per-process wire address (metrics scrape +
        lease identity): with SO_REUSEPORT every process shares one
        public ``addr``, so the scrape addr is what keeps N leases
        distinct."""
        key = scrape or addr
        with self._lock:
            known = key in self._gateways
            self._gateways[key] = (
                addr, None if ttl is None
                else self._clock() + float(ttl))
        if not known:
            self.log.info(
                "gateway %s registered%s%s", addr,
                "" if ttl is None else f" (ttl {ttl:.0f}s)",
                f" scrape {scrape}" if scrape else "")

    def unregister_gateway(self, addr: str) -> None:
        """Graceful gateway stop: leave the discovery set.  A KILLED
        gateway never calls this — its stale entry is harmless
        (clients skip unreachable addresses while failing over)."""
        with self._lock:
            self._gateways = {k: v for k, v in self._gateways.items()
                              if k != addr and v[0] != addr}

    def set_gateways(self, addrs: List[str]) -> None:
        """Replace the discovery set wholesale — the gateway sidecar
        syncing the CENTRAL registry's view into its process-local
        table, so any gateway process answers the ``gateways`` op with
        the full fleet set (entries here are mirror copies; liveness is
        the central registry's job)."""
        with self._lock:
            self._gateways = {a: (a, None) for a in addrs
                              if isinstance(a, str) and a}

    def gateway_addrs(self) -> List[str]:
        """The registered front doors, stable order, deduplicated
        (SO_REUSEPORT processes share one public addr); expired leases
        excluded — the sweeper reaps them, this just never hands one
        out in the window before it runs."""
        now = self._clock()
        with self._lock:
            return sorted({a for a, exp in self._gateways.values()
                           if exp is None or exp > now})

    def gateway_leases(self) -> List[str]:
        """One dialable address PER GATEWAY PROCESS (the scrape addr
        when the lease carries one, else the public addr) — what the
        launcher's metrics fan-in walks, and how bring-up counts
        processes that share a REUSEPORT public addr."""
        now = self._clock()
        with self._lock:
            return sorted(k for k, (_, exp) in self._gateways.items()
                          if exp is None or exp > now)

    def set_target(self, role: str, n: Optional[int]) -> None:
        """Record the control plane's WANTED replica count for one tier
        (``None`` clears it); surfaces as ``target`` in
        :meth:`role_summary` next to the actual counts."""
        with self._lock:
            if n is None:
                self._targets.pop(role, None)
            else:
                self._targets[role] = int(n)

    def begin_drain(self, addr: str, pinned: bool = True) -> bool:
        """Control-plane drain (autoscaler scale-down, rollout reap):
        the replica leaves the routable path NOW, in-flight work may
        finish.  ``pinned`` (the scale-down default) survives the
        replica's own plain alive beats — a healthy replica being
        shrunk away keeps heartbeating and must not revive itself; the
        pin is recorded against the replica's current weights_version
        so a relaunch with NEWER weights on the same addr resets it.
        False when the addr is unknown."""
        with self._lock:
            rep = self._table.get(addr)
            if rep is None:
                return False
            if rep.state in (ALIVE, WARMING):
                rep.state = DRAINING
                self._version += 1
            rep.announced_drain = True
            if pinned:
                rep.drain_pinned = True
                rep.pinned_version = rep.weights_version
        self.log.info("replica %s draining (%s)", addr,
                      "scale-down, pinned" if pinned else "announced")
        return True

    def clear_drain(self, addr: str) -> None:
        """Cancel a control-plane drain: the next routable beat revives
        the entry.  The autoscaler releases a drain this way when the
        victim cannot be mapped back to a killable task — a replica
        stuck pinned-DRAINING forever would block tier convergence."""
        with self._lock:
            rep = self._table.get(addr)
            if rep is None:
                return
            rep.drain_pinned = False
            rep.announced_drain = False
        self.log.info("replica %s drain cleared", addr)

    def fence_generation(self, min_gen: int) -> None:
        """Raise the generation fence floor: beats (re-registrations
        included) stamped with ``gen < min_gen`` are dropped whole from
        here on — PR 3's fencing epoch applied to the serving path, so
        a straggler of a reaped rollout generation can never serve
        stale weights.  Monotone: the floor never lowers."""
        with self._lock:
            raised = min_gen > self._min_gen
            if raised:
                self._min_gen = int(min_gen)
                self._fence_logged.clear()
        if raised:
            self.log.info("registry generation fence raised to %d",
                          min_gen)

    def gen_allowed(self, gen) -> bool:
        """Whether a launch generation is at or above the fence floor —
        the router consults this before re-placing a drain-migration's
        suspended KV export, so a reaped-generation zombie's artifact
        can never land on a live replica (the serving-path twin of the
        heartbeat fence above).  Unknown/malformed generations pass:
        the fence rejects provably stale state, absence of a stamp is
        a version-blind deployment."""
        if gen is None:
            return True
        try:
            g = int(gen)
        except (TypeError, ValueError):
            return True
        with self._lock:
            return g >= self._min_gen

    def mark_dead(self, addr: str, why: str = "reported by router") -> None:
        """Out-of-band death report (router connection failure).  The
        next heartbeat revives the entry if the replica is in fact
        fine."""
        with self._lock:
            rep = self._table.get(addr)
            if rep is None or rep.state == DEAD:
                return
            rep.state = DEAD
            self._version += 1
        self.log.warning("replica %s marked dead: %s", addr, why)
        if self.metrics is not None:
            self.metrics.inc("replicas_died")

    def wait_for(self, n: int, timeout: float = 60.0) -> bool:
        """Block until ``n`` replicas are alive (fleet bring-up)."""
        deadline = self._clock() + timeout
        while self._clock() < deadline:
            if len(self.alive()) >= n:
                return True
            if self._stop.wait(0.05):
                return False
        return len(self.alive()) >= n
