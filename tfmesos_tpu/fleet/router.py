"""Load-aware request routing across the replica fleet.

Replica choice is **prefix-affinity first, then
least-outstanding-requests with power-of-two-choices sampling**:

* Affinity: replicas running a cross-request prefix cache advertise
  their resident chunk digests on registry heartbeats; the router
  hashes the incoming prompt's leading page-aligned chunks
  (:mod:`tfmesos_tpu.prefixhash` — the same chain both sides compute)
  and prefers the replica with the LONGEST match, so requests sharing
  a system/few-shot prefix concentrate where the prefix's KV pages
  already live and prefill only their tails.  A saturated favorite
  (outstanding >= its advertised capacity) is skipped — affinity must
  never turn into a hot-spot pile-up.
* Fallback (no summaries, no match, favorite saturated): p2c — with
  many alive replicas, sampling two uniformly and taking the
  less-loaded one gets within a constant of full least-loaded routing
  at O(1) cost and — crucially — without the herd behavior of everyone
  chasing the single globally-least-loaded replica between load
  updates.

The load signal is the router's OWN outstanding count per replica link
(what we have in hand is exact and instantaneous; the registry's
self-reported count lags a heartbeat).

Failure handling is **bounded retry-with-backoff onto a DIFFERENT
replica**: a connection failure (dial refused, mid-request EOF, bad
frame) marks the replica dead in the registry, drops its link, and the
request is retried elsewhere — safe for generation because replica
outputs are deterministic functions of the request (greedy streams are
bit-identical across replicas; the dead replica never delivered a
completion, so nothing double-counts).  After ``max_retries`` failovers
the request fails with :class:`RoutingError` and the gateway reports it
to the client explicitly — never a silent hang.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, Iterable, Optional

from tfmesos_tpu import prefixhash, wire
from tfmesos_tpu.fleet.client import CallTimeout, ConnectionLost, MuxConnection
from tfmesos_tpu.fleet.metrics import FleetMetrics
from tfmesos_tpu.fleet.registry import ReplicaRegistry
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["RoutingError", "Router"]


class RoutingError(RuntimeError):
    """No replica could serve the request within the retry budget."""


class Router:
    """Routes one request dict to one replica and returns its reply."""

    def __init__(self, registry: ReplicaRegistry, metrics: FleetMetrics,
                 token: str = "", max_retries: int = 2,
                 backoff_s: float = 0.05, request_timeout: float = 120.0,
                 connect_timeout: float = 10.0,
                 rng: Optional[random.Random] = None):
        self.registry = registry
        self.metrics = metrics
        self.token = token
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.request_timeout = float(request_timeout)
        self.connect_timeout = float(connect_timeout)
        self.log = get_logger("tfmesos_tpu.fleet.router")
        self._rng = rng or random.Random()
        self._links: Dict[str, MuxConnection] = {}
        self._lock = threading.Lock()

    # -- load signal -------------------------------------------------------

    def outstanding(self, addr: str) -> int:
        with self._lock:
            link = self._links.get(addr)
        return link.outstanding if link is not None and not link.closed else 0

    # -- replica choice ----------------------------------------------------

    def _affinity_pick(self, cands, prompt) -> Optional[str]:
        """The unsaturated replica whose advertised prefix-cache
        summary matches the most leading chunks of ``prompt`` (ties:
        least outstanding); ``None`` when nothing matches."""
        best = None
        digests: Dict[tuple, list] = {}     # one hash pass per geometry
        for r in cands:
            summ = r.prefix
            if not isinstance(summ, dict) or not summ.get("hashes"):
                continue
            try:
                key = (int(summ.get("page") or 0),
                       int(summ.get("first") or 0),
                       str(summ.get("seed") or ""))
                if key[0] < 1:
                    continue
                if key not in digests:
                    digests[key] = prefixhash.prompt_digests(
                        prompt, key[0], key[1], bytes.fromhex(key[2]))
                depth = prefixhash.match_depth(digests[key],
                                               summ["hashes"])
            except (ValueError, TypeError):
                continue        # malformed summary: ignore, p2c covers
            if not depth:
                continue
            out = self.outstanding(r.addr)
            if r.capacity > 0 and out >= r.capacity:
                continue        # saturated favorite: fall back, don't pile
            score = (depth, -out)
            if best is None or score > best[0]:
                best = (score, r.addr)
        return best[1] if best is not None else None

    def pick(self, exclude: Iterable[str] = (),
             prompt=None) -> Optional[str]:
        """Prefix-affinity choice when ``prompt`` is given and some
        replica advertises a matching cache summary, else
        power-of-two-choices over alive replicas not in ``exclude``;
        ``None`` when no eligible replica exists."""
        exclude = set(exclude)
        cands = [r for r in self.registry.alive()
                 if r.addr not in exclude]
        if not cands:
            return None
        if prompt is not None and len(prompt):
            fav = self._affinity_pick(cands, prompt)
            self.metrics.inc("affinity_hits" if fav is not None
                             else "affinity_misses")
            if fav is not None:
                return fav
        addrs = [r.addr for r in cands]
        if len(addrs) <= 2:
            return min(addrs, key=self.outstanding)
        a, b = self._rng.sample(addrs, 2)
        return a if self.outstanding(a) <= self.outstanding(b) else b

    # -- link management ---------------------------------------------------

    def _link(self, addr: str) -> MuxConnection:
        with self._lock:
            link = self._links.get(addr)
            if link is not None and not link.closed:
                return link
        # Dial OUTSIDE the lock: a black-holed endpoint blocks the dial
        # for up to connect_timeout, and holding the router-wide lock
        # through that would stall every worker's pick()/route() on the
        # healthy replicas too.  A dial race just keeps the first link
        # registered and closes the loser.
        link = MuxConnection(addr, self.token,
                             connect_timeout=self.connect_timeout)
        with self._lock:
            existing = self._links.get(addr)
            if existing is not None and not existing.closed:
                pass    # lost the race
            else:
                self._links[addr] = link
                return link
        link.close()
        return existing

    def _drop_link(self, addr: str) -> None:
        with self._lock:
            link = self._links.pop(addr, None)
        if link is not None:
            link.close()

    # -- the routing loop --------------------------------------------------

    def route(self, msg: Dict[str, Any]) -> Any:
        """Send ``msg`` to a replica; on connection failure, retry on a
        different one (up to ``max_retries`` failovers, exponential
        backoff)."""
        tried = set()
        last: Optional[BaseException] = None
        prompt = msg.get("prompt") if isinstance(msg, dict) else None
        for attempt in range(self.max_retries + 1):
            addr = self.pick(exclude=tried, prompt=prompt)
            if addr is None:
                break       # nothing (left) to try
            try:
                link = self._link(addr)
                return link.call(msg, timeout=self.request_timeout)
            except CallTimeout as e:
                # The CONNECTION is still up (per CallTimeout's
                # contract) — only this request is slow.  Retry it
                # elsewhere, but do NOT collapse the shared link
                # (that would abort every other in-flight request on
                # this replica) and do NOT mark the replica dead.
                # The eventual late reply finds its slot gone and is
                # dropped; deterministic generation makes the
                # duplicated work harmless.
                last = e
                tried.add(addr)
                self.metrics.inc("retries")
                self.log.warning("request timed out on %s after %.0fs; "
                                 "retrying on another replica "
                                 "(attempt %d/%d)", addr,
                                 self.request_timeout, attempt + 1,
                                 self.max_retries + 1)
            except (ConnectionLost, OSError, wire.WireError) as e:
                last = e
                tried.add(addr)
                self._drop_link(addr)
                self.registry.mark_dead(
                    addr, why=f"{type(e).__name__}: {e}")
                self.metrics.inc("retries")
                self.log.warning("replica %s failed (%s); retrying on "
                                 "another replica (attempt %d/%d)", addr, e,
                                 attempt + 1, self.max_retries + 1)
                time.sleep(self.backoff_s * (2 ** attempt))
        if last is not None:
            raise RoutingError(
                f"no replica could serve the request after trying "
                f"{sorted(tried)}: {last}") from last
        raise RoutingError("no alive replicas")

    def close(self) -> None:
        with self._lock:
            links = list(self._links.values())
            self._links.clear()
        for link in links:
            link.close()
