"""Load-aware request routing across the replica fleet.

Replica choice is **prefix-affinity first, then
least-outstanding-requests with power-of-two-choices sampling**:

* Affinity: replicas running a cross-request prefix cache advertise
  their resident chunk digests on registry heartbeats; the router
  hashes the incoming prompt's leading page-aligned chunks
  (:mod:`tfmesos_tpu.prefixhash` — the same chain both sides compute)
  and prefers the replica with the LONGEST match, so requests sharing
  a system/few-shot prefix concentrate where the prefix's KV pages
  already live and prefill only their tails.  A saturated favorite
  (outstanding >= its advertised capacity) is skipped — affinity must
  never turn into a hot-spot pile-up.
* Fallback (no summaries, no match, favorite saturated): p2c — with
  many alive replicas, sampling two uniformly and taking the
  less-loaded one gets within a constant of full least-loaded routing
  at O(1) cost and — crucially — without the herd behavior of everyone
  chasing the single globally-least-loaded replica between load
  updates.

The load signal is the router's OWN outstanding count per replica link
(what we have in hand is exact and instantaneous; the registry's
self-reported count lags a heartbeat).

Failure handling is **bounded retry-with-backoff onto a DIFFERENT
replica**: a connection failure (dial refused, mid-request EOF, bad
frame) marks the replica dead in the registry, drops its link, and the
request is retried elsewhere — safe for generation because replica
outputs are deterministic functions of the request (greedy streams are
bit-identical across replicas; the dead replica never delivered a
completion, so nothing double-counts).  After ``max_retries`` failovers
the request fails with :class:`RoutingError` and the gateway reports it
to the client explicitly — never a silent hang.

**Disaggregated (role-aware) routing**: replicas advertise
``role: prefill|decode|unified`` on heartbeats.  When BOTH a prefill
tier and a decode tier are alive, a generate request takes the
two-phase path: (1) pick a prefill replica — prefix-affinity first
(shared system prompts concentrate where their KV pages live), then
least-outstanding p2c — and call its ``prefill`` op; (2) forward the
returned KV artifact (one raw binary frame, never re-encoded) to a
decode replica picked by KV-page headroom (p2c over heartbeat-
advertised free pages, saturated replicas skipped), which imports the
pages and decodes.  Each phase retries onto a different replica within
the shared ``max_retries`` budget; when a tier is empty — or the
disaggregated path exhausts its retries — the request FALLS BACK to
the unified tier, so an all-unified fleet (every existing deployment)
routes exactly as before.  Plain generates never land on a
prefill-role replica.

**Drain migration (suspended replies)**: a replica being drained away
(autoscaler scale-down, blue-green reap — ``tfserve``'s
drain-migrate-kill) answers its in-flight generates with ``suspended``
instead of a completion: a raw HMAC frame carrying the row's resumable
KV artifact (pages + mid-stream sampler state), or a plain requeue
marker when the request held no exportable state.  The router
RE-PLACES either form transparently: an artifact resumes on a replica
advertising the SAME ``weights_version`` (resuming old-weights KV
under new weights would be a silently wrong stream — the one failure
mode this path must never have) whose launch generation passes the
registry fence (a reaped-rollout zombie's export can never land);
anything else — requeue, fence rejection, version mismatch, artifact
rejection — falls back to RE-RUNNING the whole request on another
replica, which is equally lossless because nothing was delivered and
completions are deterministic.  The client sees one completion, never
the move; ``migration_*`` counters make each path observable.

**Failure containment** (docs/SERVING.md "Deadlines & failure
containment"): three mechanisms bound every failure's blast radius:

* **End-to-end deadlines**: a request forwarded with a ``deadline``
  (absolute, gateway-stamped) fails FAST with ``deadline_exceeded``
  the moment its budget runs out — at the loop head, never mid-retry —
  and every wire call's timeout is a SLICE of the remaining budget
  (non-final attempts keep half back for a retry, the disagg prefill
  phase keeps three quarters back for decode) instead of the flat
  ``request_timeout``, so one hung replica can never consume the whole
  budget.  The remaining budget is re-stamped onto the wire as
  ``deadline_ms`` per attempt, so the replica's own in-batcher cancel
  works from the same (shrinking) clock.
* **Fleet retry budget** (:class:`~tfmesos_tpu.fleet.containment.
  RetryBudget`): every failover debits a token-ratio budget refilled by
  delivered completions — under a brown-out the fleet degrades to ~1
  attempt per request instead of multiplying its own load, and an
  exhausted budget converts retryable errors into fast deterministic
  failures (``retry_budget_exhausted`` counter).
* **Per-replica circuit breakers** (:class:`~tfmesos_tpu.fleet.
  containment.BreakerBoard`): consecutive failures OR a success-latency
  EWMA far above the peer median trip a replica out of every candidate
  set — the latter is the first mechanism that catches a GRAY failure,
  a replica the heartbeat registry still reports alive but that serves
  100x slow.  Half-open single-probe recovery; state exported through
  ``describe()`` and the gateway's ``breakers`` gauge.

**Warming replicas** (registered with ``status: warming`` while
``ContinuousBatcher.warmup`` compiles their entry points) are excluded
by EVERY pick — ``pick``/``pick_prefill``/``pick_decode`` all candidate
through ``registry.alive()``, which a warming replica is not in.  A
tier whose only members are warming behaves exactly like an empty
tier: the unified path raises :class:`RoutingError`'s "no alive
replicas" (or retries another tier member), and the disaggregated path
falls back to unified — the same fallback semantics as above, so a
re-warming relaunch is indistinguishable from a not-yet-launched
replica to routing.
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

from tfmesos_tpu import prefixhash, wire
from tfmesos_tpu.fleet import tracing
from tfmesos_tpu.fleet.client import CallTimeout, ConnectionLost, MuxConnection
from tfmesos_tpu.fleet.containment import (BreakerBoard, BreakerConfig,
                                           RetryBudget)
from tfmesos_tpu.fleet.metrics import FleetMetrics

#: process-wide transfer-id stream for direct peer-to-peer KV pushes
#: (the pid prefix keeps two gateway processes' ids from colliding in
#: one decode replica's staging area).
_XFER_SEQ = itertools.count(1)


def _new_xfer_id() -> str:
    return f"xf-{os.getpid()}-{next(_XFER_SEQ)}"
from tfmesos_tpu.fleet.registry import (DECODE, PREFILL, UNIFIED,
                                        ReplicaInfo, ReplicaRegistry)
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["RoutingError", "Router"]


class RoutingError(RuntimeError):
    """No replica could serve the request within the retry budget."""


class Router:
    """Routes one request dict to one replica and returns its reply."""

    def __init__(self, registry: ReplicaRegistry, metrics: FleetMetrics,
                 token: str = "", max_retries: int = 2,
                 backoff_s: float = 0.05, request_timeout: float = 120.0,
                 connect_timeout: float = 10.0,
                 rng: Optional[random.Random] = None,
                 breakers: bool = True,
                 breaker_config: Optional[BreakerConfig] = None,
                 retry_budget: Optional[RetryBudget] = None,
                 clock=time.monotonic, sleep=time.sleep,
                 link_factory=None):
        self.registry = registry
        self.metrics = metrics
        self.token = token
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.request_timeout = float(request_timeout)
        self.connect_timeout = float(connect_timeout)
        self.log = get_logger("tfmesos_tpu.fleet.router")
        self._rng = rng or random.Random()
        # Injectable time base (the chaos/autoscaler discipline,
        # finished): EVERY clock reading on the routing path — deadline
        # checks, timeout slices, breaker latency samples, retry
        # backoff — goes through these two, so the same code runs on
        # time.monotonic in production and on the fleet simulator's
        # virtual clock with zero real sleeping (docs/SIMULATOR.md).
        self._clock = clock
        self._sleep = sleep
        # link_factory(addr) -> MuxConnection-shaped transport: the
        # simulator substitutes virtual links; production dials TCP.
        self._link_factory = link_factory or (
            lambda addr: MuxConnection(
                addr, self.token, connect_timeout=self.connect_timeout))
        self._links: Dict[str, MuxConnection] = {}
        self._lock = threading.Lock()
        # Failure containment (module docstring): per-replica circuit
        # breakers (None = disabled — the bench's control arm) and the
        # fleet-wide retry budget.
        self.breakers: Optional[BreakerBoard] = \
            BreakerBoard(breaker_config, clock=clock) if breakers else None
        self.budget = retry_budget or RetryBudget()
        # Blue-green rollout: when set, every tier's candidate set is
        # narrowed to replicas advertising THIS weights_version whenever
        # at least one such replica is routable — the shift point of
        # FleetServer.rollout().  Replicas of other versions remain the
        # FALLBACK (the old tier keeps serving through the bake window
        # if the new tier empties), so the shift itself can never cause
        # an outage.  One attribute write = the atomic shift.
        self._preferred_version: Optional[str] = None
        # Model catalog (docs/SERVING.md "Model catalog"): requests
        # carrying a model ride a per-MODEL tier above the role tiers —
        # every candidate set narrows to replicas advertising that
        # model_id (never a fallback: serving model A's request with
        # model B's weights would be silently wrong).  When a model has
        # NO routable replica (scaled to zero), the hook below asks the
        # control plane to cold-start it (warm-pool adoption or a
        # launch) and the request WAITS — bounded by its deadline and
        # ``model_wait_s`` — instead of failing, so scale-to-zero is an
        # economy measure, not an availability hole.
        self.on_model_demand = None
        self.model_wait_s = 30.0

    # -- load signal -------------------------------------------------------

    def outstanding(self, addr: str) -> int:
        # Lock-free read: dict.get is atomic under the GIL and a
        # racing link swap costs at worst one stale load sample on one
        # pick — this runs twice per p2c choice, so it must be cheap.
        link = self._links.get(addr)
        return link.outstanding if link is not None and not link.closed else 0

    # -- replica choice ----------------------------------------------------

    @staticmethod
    def _summary_depth(summ, prompt, digests: Dict[tuple, list]) -> int:
        """Leading-chunk match depth of ``prompt`` against one
        prefix-cache-shaped summary ({page, first, seed, hashes});
        0 on no match or a malformed summary.  ``digests`` memoizes
        one hash pass per chunk geometry across candidates."""
        if not isinstance(summ, dict) or not summ.get("hashes"):
            return 0
        try:
            key = (int(summ.get("page") or 0),
                   int(summ.get("first") or 0),
                   str(summ.get("seed") or ""))
            if key[0] < 1:
                return 0
            if key not in digests:
                digests[key] = prefixhash.prompt_digests(
                    prompt, key[0], key[1], bytes.fromhex(key[2]))
            return prefixhash.match_depth(digests[key], summ["hashes"])
        except (ValueError, TypeError):
            return 0            # malformed summary: ignore, p2c covers

    def _affinity_pick(self, cands, prompt) -> Optional[str]:
        """The unsaturated replica whose advertised prefix digests
        match the most leading chunks of ``prompt`` — DEVICE-resident
        pages (the heartbeat prefix-cache summary) first, then
        TIER-resident ones (the KV tier's spilled-page summary: the
        pages promote back into the pool on admission, so steering the
        prompt there still skips the prefill).  Ties: device beats
        tier, then least outstanding; ``None`` when nothing matches."""
        best = None
        digests: Dict[tuple, list] = {}     # one hash pass per geometry
        for r in cands:
            dev = self._summary_depth(r.prefix, prompt, digests)
            tier = 0
            if isinstance(r.kv_tier, dict):
                tier = self._summary_depth(r.kv_tier.get("prefix"),
                                           prompt, digests)
            depth = max(dev, tier)
            if not depth:
                continue
            out = self.outstanding(r.addr)
            if r.capacity > 0 and out >= r.capacity:
                continue        # saturated favorite: fall back, don't pile
            score = (depth, 1 if dev >= tier else 0, -out)
            if best is None or score > best[0]:
                best = (score, r.addr)
        return best[1] if best is not None else None

    def _session_pick(self, cands, session: str) -> Optional[str]:
        """The unsaturated replica advertising ``session`` in its KV
        tier's parked-session list (ties: least outstanding) — a
        resumed turn lands where the conversation's KV is parked and
        prefills only the new tail.  ``None`` sends the request down
        the normal affinity/p2c path (a shared disk tier may still
        serve the resume there; a full miss re-prefills cold — always
        correct)."""
        best = None
        for r in cands:
            kt = r.kv_tier
            if not isinstance(kt, dict):
                continue
            sess = kt.get("sessions")
            if not isinstance(sess, (list, tuple)) or session not in sess:
                continue
            out = self.outstanding(r.addr)
            if r.capacity > 0 and out >= r.capacity:
                continue        # saturated: don't pile onto the parker
            if best is None or out < best[0]:
                best = (out, r.addr)
        return best[1] if best is not None else None

    def set_preferred_version(self, version: Optional[str]) -> None:
        """Shift routing to prefer replicas serving ``version`` (the
        blue-green cutover); ``None`` restores version-blind routing.
        Takes effect on the next pick — no in-flight request moves."""
        self._preferred_version = version
        self.log.info("router weights_version preference -> %r", version)

    def _alive_by_role(self, roles, exclude=(),
                       model: Optional[str] = None) -> List[ReplicaInfo]:
        """Alive candidates of the given tiers, the model tier and
        version-preference applied on top: a ``model``-carrying
        request narrows to replicas advertising exactly that
        ``model_id`` (no fallback — wrong weights are worse than
        unavailable); model-less requests exclude warm-pool members
        (undedicated replicas must never take traffic).  With a
        preferred weights_version set, replicas advertising it crowd
        out every other version whenever at least one is routable;
        otherwise (new tier empty or draining away) the full candidate
        set remains the fallback.

        The no-exclusions common case reads the registry's CACHED
        per-tier view (``alive_view`` — O(1) amortized, the change
        that makes 1000-replica routing feasible); retries (non-empty
        ``exclude``) filter it, and registries without the cache (test
        stubs) fall back to the original full scan."""
        view = getattr(self.registry, "alive_view", None)
        if view is not None:
            cands = view(tuple(roles))
            if exclude:
                exclude = set(exclude)
                cands = [r for r in cands if r.addr not in exclude]
        else:
            exclude = set(exclude)
            cands = [r for r in self.registry.alive()
                     if r.addr not in exclude
                     and (r.role or UNIFIED) in roles]
        if model:
            cands = [r for r in cands
                     if getattr(r, "model_id", "") == model]
        else:
            # Warm-pool members are invisible to model-less picks too;
            # the registry's O(1) count gates the scan so fleets
            # without a pool (every deployment of old) pay nothing.
            has_pool = getattr(self.registry, "has_pool", None)
            if has_pool is not None and has_pool():
                cands = [r for r in cands
                         if not getattr(r, "warm_pool", False)]
        pref = self._preferred_version
        if pref:
            preferred = [r for r in cands if r.weights_version == pref]
            if preferred:
                return self._breaker_filter(preferred)
            if cands:
                # Served by the non-preferred fallback: visible in the
                # counters so a stuck rollout (bake window over, old
                # version still serving) cannot hide.
                self.metrics.inc("version_fallbacks")
        return self._breaker_filter(cands)

    def _breaker_filter(self, cands: List[ReplicaInfo]
                        ) -> List[ReplicaInfo]:
        """Drop candidates whose circuit breaker is open (a half-open
        breaker with no probe in flight stays eligible — the next pick
        of it IS the probe).  When EVERY candidate is tripped the full
        set comes back: an all-open tier means the breakers have no
        healthy alternative to offer, and failing every request fast
        would turn a brown-out into a self-inflicted outage — the
        ``breaker_saturated`` counter makes that state visible."""
        if self.breakers is None or not cands \
                or self.breakers.all_closed():
            return cands
        allowed = [r for r in cands if self.breakers.eligible(r.addr)]
        if allowed:
            if len(allowed) < len(cands):
                skipped = len(cands) - len(allowed)
                self.metrics.inc("breaker_skips", skipped)
                tracing.cur_event(
                    "router", "breaker_skip", skipped=skipped,
                    addrs=",".join(sorted(
                        r.addr for r in cands if r not in allowed)))
            return allowed
        self.metrics.inc("breaker_saturated")
        tracing.cur_event("router", "breaker_saturated",
                          candidates=len(cands))
        return cands

    # -- containment hooks (breakers + budget + deadlines) -----------------

    def _breaker_dispatch(self, addr: str) -> bool:
        """True when THIS dispatch claimed the breaker's half-open
        probe — threaded back into the outcome records so only the
        sanctioned probe can close or re-open the breaker."""
        if self.breakers is not None:
            return self.breakers.on_dispatch(addr)
        return False

    def _breaker_ok(self, addr: str, t0: float,
                    probe: bool = False) -> None:
        if self.breakers is not None:
            self.breakers.record_success(
                addr, (self._clock() - t0) * 1000.0, probe=probe)

    def _breaker_fail(self, addr: str, probe: bool = False) -> None:
        if self.breakers is not None:
            self.breakers.record_failure(addr, probe=probe)

    def _charge_retry(self) -> bool:
        """Debit the fleet retry budget for one failover; False means
        the budget is exhausted — the caller fails fast instead of
        retrying (brown-out containment: the fleet must not multiply
        its own load when most requests are already failing)."""
        if self.budget.try_retry():
            tracing.cur_event("router", "budget_debit",
                              level=round(self.budget.level(), 3))
            return True
        self.metrics.inc("retry_budget_exhausted")
        tracing.cur_event("router", "budget_exhausted")
        self.log.warning("retry budget exhausted; failing fast instead "
                         "of retrying")
        return False

    def breaker_summary(self):
        """The gateway's ``breakers`` gauge (None = breakers off)."""
        return self.breakers.summary() if self.breakers is not None \
            else None

    def retry_budget_level(self) -> float:
        """The gateway's ``retry_budget`` gauge: 0..1 of budget left."""
        return round(self.budget.level(), 3)

    def describe(self) -> Dict[str, Any]:
        """Containment state: per-replica breaker detail plus the
        retry-budget level — the on-call's brown-out snapshot."""
        return {
            "breakers": (self.breakers.describe()
                         if self.breakers is not None else {}),
            "retry_budget": self.budget.level(),
        }

    @staticmethod
    def _trace_attempt(name: str, att0: Optional[float], addr: str,
                       outcome: str, reply=None, **attrs) -> None:
        """Close one attempt span on the current trace: duration from
        ``att0`` (captured before the wire call), the replica picked,
        and the outcome taxonomy.  When the reply piggybacked the
        replica's hop spans they are POPPED off it (the client must not
        receive span payloads) and stitched in re-anchored at the
        attempt's start — hop-local durations on our timeline."""
        tr = tracing.current()
        if tr is None or att0 is None:
            return
        spans = None
        if isinstance(reply, dict):
            spans = reply.pop("trace", None)
        elif isinstance(reply, wire.RawFrame) \
                and isinstance(reply.meta, dict):
            spans = reply.meta.pop("trace", None)
        if spans:
            tr.absorb(spans, att0, addr=addr)
        tr.add("router", name, att0, tr.elapsed_ms() - att0,
               addr=addr, outcome=outcome, **attrs)

    @staticmethod
    def _deadline_of(msg) -> Optional[float]:
        """The gateway-stamped ABSOLUTE deadline riding the forward
        dict (``time.monotonic`` base — same process as the gateway;
        it never crosses the wire, see :meth:`_wire_msg`)."""
        if not isinstance(msg, dict):
            return None
        dl = msg.get("deadline")
        return float(dl) if isinstance(dl, (int, float)) \
            and not isinstance(dl, bool) else None

    def _expired_reply(self, what: str) -> Dict[str, Any]:
        self.metrics.inc("deadline_expired_route")
        tracing.cur_event("router", "deadline_expired", what=what)
        return {"op": "error", "kind": "deadline_exceeded",
                "error": f"request deadline expired {what}"}

    def _wire_msg(self, msg: Dict[str, Any],
                  deadline: Optional[float]) -> Dict[str, Any]:
        """The dict that actually goes on the wire: the internal
        absolute ``deadline`` stripped (a monotonic reading means
        nothing on another host's clock) and the REMAINING budget
        re-stamped as ``deadline_ms`` — recomputed per attempt, so a
        retry hands the replica only what is actually left.  The
        internal ``_trace`` CONTEXT is stripped the same way (it is a
        live object, not wire data); what crosses instead is the
        ``trace_id`` plus the detail/slow-threshold knobs, so the
        replica's hop spans come back attributable — hop-LOCAL offsets
        only, absolute clocks never cross the wire."""
        tr = tracing.current()
        if deadline is None and tr is None \
                and "deadline" not in msg and "_trace" not in msg \
                and "_emit" not in msg and "_model" not in msg \
                and "_background" not in msg:
            return msg
        out = {k: v for k, v in msg.items()
               if k not in ("deadline", "_trace", "_emit", "_model",
                            "_background")}
        if "_model" in msg:
            # The resolved model id DOES cross the wire (as ``model``):
            # the replica cross-checks it against the model it serves,
            # so a pick racing a warm-pool adoption can never silently
            # answer with another model's weights.
            out["model"] = msg["_model"]
        if deadline is not None:
            out["deadline_ms"] = round(
                max(1.0, (deadline - self._clock()) * 1000.0), 3)
        if tr is not None:
            out["trace_id"] = tr.trace_id
            if tr.detailed:
                out["trace_detail"] = True
            if tr.slow_ms is not None:
                out["trace_slow_ms"] = tr.slow_ms
        return out

    def _call_timeout(self, deadline: Optional[float],
                      final_attempt: bool = True,
                      share: float = 1.0) -> float:
        """The per-call wire timeout a phase may spend: the flat
        ``request_timeout`` without a deadline; with one, a slice of
        the remaining budget — non-final attempts keep half back for a
        retry, and the disagg prefill phase passes ``share`` to keep
        most of the budget for its decode phase."""
        if deadline is None:
            return self.request_timeout
        rem = (deadline - self._clock()) * share
        if not final_attempt:
            rem *= 0.5
        return min(self.request_timeout, max(0.05, rem))

    def _load_pick(self, cands) -> Optional[str]:
        """Least-outstanding with p2c sampling over ``cands`` — O(1)
        regardless of tier size (two index draws, never a full-list
        materialization: at 1000 replicas an O(n) pick would dominate
        every request)."""
        n = len(cands)
        if not n:
            return None
        if n <= 2:
            return min((r.addr for r in cands), key=self.outstanding)
        # Two distinct uniform indices without rng.sample's setup cost.
        rr = self._rng.randrange
        i = rr(n)
        j = rr(n - 1)
        if j >= i:
            j += 1
        a, b = cands[i].addr, cands[j].addr
        return a if self.outstanding(a) <= self.outstanding(b) else b

    def _pick_role(self, roles, exclude, prompt,
                   session: Optional[str] = None,
                   model: Optional[str] = None,
                   background: bool = False) -> Optional[str]:
        """One choice policy for both prompt-bearing tiers:
        session-affinity first (the replica holding the conversation's
        parked KV), then prefix-affinity when ``prompt`` is given and
        some candidate advertises a matching cache summary, else
        least-outstanding p2c; ``None`` when no eligible replica
        exists.  ``model`` nests the model tier ABOVE everything:
        affinity, p2c, and version preference all operate inside it.
        ``background`` (batch-lane work) narrows to replicas with FREE
        slots when any exist: p2c alone can draw two saturated
        replicas while an idle one sits empty, queueing deadline-less
        work exactly where interactive load is hot."""
        cands = self._alive_by_role(roles, exclude, model=model)
        if not cands:
            return None
        if background:
            idle = [r for r in cands
                    if not (r.capacity > 0
                            and self.outstanding(r.addr) >= r.capacity)]
            cands = idle or cands
        if session:
            fav = self._session_pick(cands, session)
            self.metrics.inc("session_affinity_hits" if fav is not None
                             else "session_affinity_misses")
            if fav is not None:
                return fav
        if prompt is not None and len(prompt):
            # The O(candidates) affinity scan runs only when some
            # replica actually advertises a prefix-cache summary
            # (registry-counted, O(1)); otherwise the request counts a
            # miss straight away — a no-prefix-cache fleet must not pay
            # the scan per prompt-bearing request at 1000 replicas.
            have = getattr(self.registry, "has_prefix_summaries", None)
            if have is None or have():
                fav = self._affinity_pick(cands, prompt)
                self.metrics.inc("affinity_hits" if fav is not None
                                 else "affinity_misses")
                if fav is not None:
                    return fav
            else:
                self.metrics.inc("affinity_misses")
        return self._load_pick(cands)

    def pick(self, exclude: Iterable[str] = (),
             prompt=None, session: Optional[str] = None,
             model: Optional[str] = None,
             background: bool = False) -> Optional[str]:
        """The UNIFIED-path choice over alive unified replicas not in
        ``exclude``.  Prefill-role replicas never appear here (they
        refuse generate); decode-role replicas are reserved for
        imported prefills, so the role split cannot silently turn a
        decode tier back into a unified one.  ``session`` steers a
        multi-turn conversation at the replica advertising its parked
        KV (session affinity); ``model`` narrows to that model's
        replicas (the model tier)."""
        return self._pick_role((UNIFIED,), exclude, prompt, session,
                               model, background=background)

    def pick_prefill(self, exclude: Iterable[str] = (),
                     prompt=None,
                     model: Optional[str] = None) -> Optional[str]:
        """The prefill-tier choice: prefix-affinity first (a prompt
        whose leading chunks are resident on some prefill replica
        prefills only its tail there), then least-outstanding p2c —
        the load signal is what spreads long prompts."""
        return self._pick_role((PREFILL,), exclude, prompt,
                               model=model)

    def pick_decode(self, exclude: Iterable[str] = (),
                    weights_version: Optional[str] = None,
                    model: Optional[str] = None
                    ) -> Optional[str]:
        """The decode-tier choice: p2c by advertised KV-page headroom
        (the imported pages must FIT — load alone would happily pick a
        replica whose pool is full of long-lived rows), saturated
        replicas (outstanding >= capacity) skipped, ties broken by the
        router's own outstanding count.  ``weights_version`` narrows
        the tier to replicas serving those exact weights — a suspended
        mid-stream artifact must never resume under different weights
        (same rule as :meth:`_pick_resume`)."""
        cands = self._alive_by_role((DECODE,), exclude, model=model)
        if weights_version:
            cands = [r for r in cands
                     if r.weights_version == weights_version]
        if not cands:
            return None
        unsat = [r for r in cands
                 if not (r.capacity > 0
                         and self.outstanding(r.addr) >= r.capacity)]
        cands = unsat or cands

        def score(r: ReplicaInfo):
            return (r.kv_headroom, -self.outstanding(r.addr))

        if len(cands) > 2:
            cands = self._rng.sample(cands, 2)
        return max(cands, key=score).addr

    # -- link management ---------------------------------------------------

    def control(self, addr: str, msg: Dict[str, Any],
                timeout: float = 30.0) -> Any:
        """One control call straight to a known replica over the shared
        mux link (the fleet's ``migrate`` request rides this) — no
        pick, no retry: control targets a SPECIFIC replica by
        design."""
        return self._link(addr).call(msg, timeout=timeout)

    def control_raw(self, addr: str, meta: Dict[str, Any], body,
                    timeout: float = 30.0) -> Any:
        """One RAW-frame control call straight to a known replica (the
        adapter hot-swap's delta ships this way — HMAC-tagged bytes on
        the existing mux link, never re-encoded)."""
        return self._link(addr).call_raw(meta, body, timeout=timeout)

    def _link(self, addr: str) -> MuxConnection:
        with self._lock:
            link = self._links.get(addr)
            if link is not None and not link.closed:
                return link
        # Dial OUTSIDE the lock: a black-holed endpoint blocks the dial
        # for up to connect_timeout, and holding the router-wide lock
        # through that would stall every worker's pick()/route() on the
        # healthy replicas too.  A dial race just keeps the first link
        # registered and closes the loser.
        link = self._link_factory(addr)
        with self._lock:
            existing = self._links.get(addr)
            if existing is not None and not existing.closed:
                pass    # lost the race
            else:
                self._links[addr] = link
                return link
        link.close()
        return existing

    def _drop_link(self, addr: str) -> None:
        with self._lock:
            link = self._links.pop(addr, None)
        if link is not None:
            link.close()

    # -- failure classification (ONE copy of the retry policy) -------------
    #
    # Every phase loop (unified route, disagg prefill, disagg decode)
    # shares the same taxonomy:
    #   * CallTimeout — the CONNECTION is still up (per CallTimeout's
    #     contract), only this request is slow.  Retry it elsewhere, but
    #     do NOT collapse the shared link (that would abort every other
    #     in-flight request on this replica) and do NOT mark the replica
    #     dead.  The eventual late reply finds its slot gone and is
    #     dropped; deterministic generation makes the duplicate work
    #     harmless.
    #   * ConnectionLost / OSError — the transport failed: drop the
    #     link, mark the replica dead, back off, retry elsewhere.
    #   * wire.WireError from call()/call_raw() is NEITHER: it is an
    #     encode-time rejection of the PAYLOAD (oversized raw meta or
    #     frame), raised before any bytes hit the socket — receive-side
    #     wire corruption surfaces as ConnectionLost instead.  Each
    #     phase handles it as deterministic for that payload: never
    #     drop the (healthy, shared) link, never mark the replica dead,
    #     never re-ship the identical doomed bytes to another replica.

    def _note_timeout(self, addr: str, tried: set, attempt: int,
                      what: str, clipped: bool = False,
                      probe: bool = False) -> bool:
        """Returns whether the caller may retry (the fleet retry budget
        gates every failover — see module docstring).  ``clipped=True``
        for timeouts on a DEADLINE-CLIPPED slice: a call cut short by
        the request's own budget says nothing about the replica's
        health (charging the breaker would let short-deadline traffic
        trip healthy replicas), and the retries it permits are bounded
        by the DEADLINE — the loop-head expiry check ends them — not by
        the fleet budget, which must keep its runway for real
        failures."""
        tried.add(addr)
        if not clipped:
            self._breaker_fail(addr, probe)
            if not self._charge_retry():
                return False
        self.metrics.inc("retries")
        tracing.cur_event("router", "retry", cause="timeout", addr=addr,
                          what=what, clipped=clipped)
        self.log.warning("%s timed out on %s; retrying on "
                         "another replica (attempt %d/%d)", what, addr,
                         attempt + 1, self.max_retries + 1)
        return True

    def _note_link_failure(self, e: BaseException, addr: str, tried: set,
                           attempt: int, what: str,
                           probe: bool = False) -> bool:
        """Like :meth:`_note_timeout` for transport failures: the link
        drops and the replica is marked dead REGARDLESS of the budget's
        answer (the death is a fact either way); only the retry itself
        is budget-gated."""
        tried.add(addr)
        self._drop_link(addr)
        self._breaker_fail(addr, probe)
        self.registry.mark_dead(addr, why=f"{type(e).__name__}: {e}")
        if not self._charge_retry():
            return False
        self.metrics.inc("retries")
        tracing.cur_event("router", "retry", cause="link_failure",
                          addr=addr, what=what,
                          error=f"{type(e).__name__}")
        self.log.warning("%s replica %s failed (%s); retrying on "
                         "another replica (attempt %d/%d)", what, addr, e,
                         attempt + 1, self.max_retries + 1)
        self._sleep(self.backoff_s * (2 ** attempt))
        return True

    def _note_replica_error(self, addr: str, tried: set,
                            err: "RoutingError",
                            probe: bool = False) -> bool:
        """One transient replica-side error reply (internal failure,
        pool exhaustion): breaker + budget bookkeeping shared by every
        phase loop.  Returns whether the caller may retry."""
        tried.add(addr)
        self._breaker_fail(addr, probe)
        if not self._charge_retry():
            return False
        self.metrics.inc("retries")
        tracing.cur_event("router", "retry", cause="replica_error",
                          addr=addr, error=str(err)[:200])
        return True

    # -- drain migration: suspended replies re-place elsewhere -------------

    @staticmethod
    def _suspended_of(reply) -> Optional[tuple]:
        """``(meta, body_or_None)`` when ``reply`` is a drained
        replica's ``suspended`` answer (raw frame = resumable artifact,
        dict = requeue marker); ``None`` for every normal reply."""
        if isinstance(reply, wire.RawFrame) \
                and isinstance(reply.meta, dict) \
                and reply.meta.get("op") == "suspended":
            return reply.meta, reply.body
        if isinstance(reply, dict) and reply.get("op") == "suspended":
            return reply, None
        return None

    def _pick_resume(self, tried, weights_version,
                     model: Optional[str] = None,
                     adapter: Optional[str] = None) -> Optional[str]:
        """A unified-tier replica a suspended artifact may RESUME on:
        same advertised weights_version — and, when the export stamped
        them, same model_id and adapter_version — because KV pages
        computed under one set of weights must never feed a decode
        under another (resume onto a mismatch would be a silently
        wrong stream), not already tried.  ``None`` = no eligible
        target; the caller re-runs the request instead."""
        cands = self._alive_by_role((UNIFIED,), exclude=tried,
                                    model=model)
        if weights_version:
            cands = [r for r in cands
                     if r.weights_version == weights_version]
        if adapter is not None:
            cands = [r for r in cands
                     if getattr(r, "adapter_version", "") == adapter]
        return self._load_pick(cands)

    def _resume_elsewhere(self, msg: Dict[str, Any], meta: dict,
                          body, tried: set) -> Optional[Any]:
        """Re-place one suspended export: retry the artifact onto
        eligible replicas within the shared budget; ``None`` means the
        caller should fall back to re-running the plain request (the
        equally-lossless path — nothing was delivered).  A resume
        target that is itself being drained can answer suspended again;
        the freshest artifact keeps moving until the budget runs out."""
        if body is None:
            # Either a plain requeue marker (just re-run) or a DIRECT-
            # PUSHED export: the victim already streamed its artifact
            # peer-to-peer to the brokered survivor, and only the small
            # reference rides the control plane.
            return self._resume_pushed(msg, meta, tried)
        gen = meta.get("gen")
        if not self.registry.gen_allowed(gen):
            # The victim belongs to a reaped (fenced) generation: its
            # KV pages are stale-weights state and must never land.
            self.metrics.inc("migration_fenced")
            self.log.warning("dropping suspended export from a fenced "
                             "generation (%r); re-running the request",
                             gen)
            return None
        wv = meta.get("weights_version")
        wv = wv if isinstance(wv, str) and wv else ""
        art_model = meta.get("model_id")
        art_model = art_model if isinstance(art_model, str) \
            and art_model else None
        art_adapter = meta.get("adapter_version")
        art_adapter = art_adapter if isinstance(art_adapter, str) \
            else None
        deadline = self._deadline_of(msg)

        emit = msg.get("_emit")

        def build_call(m):
            out = {k: v for k, v in m.items()
                   if k not in ("op", "id", "gen", "weights_version",
                                "trace")}
            out.update(op="generate", prompt=msg.get("prompt"),
                       max_new_tokens=msg.get("max_new_tokens"),
                       stop_token=msg.get("stop_token"),
                       priority=msg.get("priority"))
            if msg.get("stream"):
                # The resume target re-streams from offset 0 (its
                # imported row carries the already-emitted prefix);
                # the gateway's offset de-dup keeps the client stream
                # exactly-once.
                out["stream"] = True
            return out

        call = build_call(meta)
        for attempt in range(self.max_retries + 1):
            if deadline is not None and self._clock() >= deadline:
                return self._expired_reply("while resuming its "
                                           "migrated state")
            addr = self._pick_resume(tried, wv, model=art_model,
                                     adapter=art_adapter)
            if addr is None:
                break
            rprobe = self._breaker_dispatch(addr)
            att0 = tracing.cur_elapsed()
            t0 = self._clock()
            timeout = self._call_timeout(deadline,
                                         attempt >= self.max_retries)
            try:
                if emit is not None:
                    rlink = self._link(addr)
                    reply = rlink.call_raw(
                        self._wire_msg(call, deadline), body,
                        timeout=timeout,
                        on_partial=self._cancel_on_disconnect(emit,
                                                              rlink))
                else:
                    reply = self._link(addr).call_raw(
                        self._wire_msg(call, deadline), body,
                        timeout=timeout)
            except CallTimeout:
                self._trace_attempt("resume", att0, addr, "timeout",
                                    clipped=timeout < self.request_timeout)
                if not self._note_timeout(
                        addr, tried, attempt, "resume",
                        clipped=timeout < self.request_timeout,
                        probe=rprobe):
                    return None
                continue
            except wire.WireError:
                # The artifact cannot even be encoded for the wire:
                # deterministic for the PAYLOAD — re-run instead.
                return None
            except (ConnectionLost, OSError) as e:
                self._trace_attempt("resume", att0, addr,
                                    "link_failure")
                if not self._note_link_failure(e, addr, tried, attempt,
                                               "resume", probe=rprobe):
                    return None
                continue
            s = self._suspended_of(reply)
            if s is not None:
                # The resume target is being drained too: carry the
                # FRESHEST artifact onward (it holds more tokens).
                # Healthy outcome for the breaker (see route()).
                self._trace_attempt("resume", att0, addr, "suspended",
                                    reply=reply)
                self._breaker_ok(addr, t0, rprobe)
                tried.add(addr)
                self.metrics.inc("migration_exports")
                meta2, body2 = s
                if body2 is None or not self.registry.gen_allowed(
                        meta2.get("gen")):
                    return None
                call = build_call(meta2)
                body = body2
                continue
            if isinstance(reply, dict) and reply.get("op") == "error":
                self._trace_attempt("resume", att0, addr, "error_reply",
                                    reply=reply,
                                    kind=str(reply.get("kind")))
                if reply.get("kind") == "deadline_exceeded":
                    # The replica's own in-batcher cancel fired: final
                    # for the request, not a resume failure.
                    return reply
                if reply.get("kind") == "bad_request":
                    # Deterministic for THIS artifact (geometry/config
                    # mismatch): re-running the request still works.
                    self.metrics.inc("migration_rejected")
                    tracing.cur_event("router", "migration_rejected",
                                      addr=addr)
                    return None
                if not self._note_replica_error(
                        addr, tried, RoutingError(
                            f"resume failed on {addr}: "
                            f"{reply.get('error')}"),
                        probe=rprobe):
                    return None
                continue
            self._trace_attempt("resume", att0, addr, "ok", reply=reply)
            self._breaker_ok(addr, t0, rprobe)
            self.metrics.inc("migration_resumes")
            tracing.cur_event("router", "migration_resume", addr=addr)
            return reply
        return None

    def _resume_pushed(self, msg: Dict[str, Any], meta: dict,
                       tried: set) -> Optional[Any]:
        """Resume a DIRECT-PUSHED migration export: the victim already
        landed its artifact on the brokered survivor as a ``kv_stage``
        frame, so the resume is one small ``generate`` call carrying
        only the ``kv_ref``.  Single bounded attempt — the stage lives
        on exactly one host; any failure returns ``None`` and the
        caller re-runs the request from scratch (equally lossless, the
        stage just expires)."""
        addr = meta.get("push_to")
        xfer = meta.get("xfer")
        if not meta.get("pushed") or not isinstance(addr, str) \
                or not addr or not isinstance(xfer, str) or not xfer:
            return None                     # requeue marker: just re-run
        if not self.registry.gen_allowed(meta.get("gen")):
            self.metrics.inc("migration_fenced")
            self.log.warning("dropping pushed export from a fenced "
                             "generation (%r); re-running the request",
                             meta.get("gen"))
            return None
        deadline = self._deadline_of(msg)
        if deadline is not None and self._clock() >= deadline:
            return self._expired_reply("while resuming its migrated "
                                       "state")
        emit = msg.get("_emit")
        call = {"op": "generate", "kv_ref": xfer,
                "prompt": msg.get("prompt"),
                "max_new_tokens": msg.get("max_new_tokens"),
                "stop_token": msg.get("stop_token"),
                "priority": msg.get("priority")}
        if msg.get("stream"):
            call["stream"] = True
        rprobe = self._breaker_dispatch(addr)
        t0 = self._clock()
        timeout = self._call_timeout(deadline, True)
        try:
            if emit is not None:
                plink = self._link(addr)
                reply = plink.call(
                    self._wire_msg(call, deadline), timeout=timeout,
                    on_partial=self._cancel_on_disconnect(emit, plink))
            else:
                reply = self._link(addr).call(
                    self._wire_msg(call, deadline), timeout=timeout)
        except CallTimeout:
            self.metrics.inc("migration_push_failed")
            return None
        except wire.WireError:
            return None
        except (ConnectionLost, OSError):
            self._drop_link(addr)
            self.metrics.inc("migration_push_failed")
            return None
        s = self._suspended_of(reply)
        if s is not None:
            # The survivor is itself being drained: carry the freshest
            # artifact onward through the standard resume machinery.
            self._breaker_ok(addr, t0, rprobe)
            tried.add(addr)
            self.metrics.inc("migration_exports")
            meta2, body2 = s
            if body2 is not None:
                if not self.registry.gen_allowed(meta2.get("gen")):
                    return None
                return self._resume_elsewhere(msg, meta2, body2, tried)
            return self._resume_pushed(msg, meta2, tried)
        if isinstance(reply, dict) and reply.get("op") == "error":
            if reply.get("kind") == "deadline_exceeded":
                return reply
            # unknown kv_ref (stage expired), wrong model, anything
            # else: deterministic for the PUSH, not the request.
            self.metrics.inc("migration_rejected")
            return None
        self._breaker_ok(addr, t0, rprobe)
        self.metrics.inc("migration_resumes")
        self.metrics.inc("migration_direct")
        tracing.cur_event("router", "migration_resume", addr=addr,
                          direct=True)
        return reply

    def migration_target(self, victim_addr: str) -> Optional[str]:
        """The survivor a drain-migration victim should DIRECT-PUSH its
        suspended artifacts to: same model / weights_version / adapter
        as the victim (the fencing rules a relay resume enforces apply
        identically), picked by load.  ``None`` when no eligible
        survivor exists — the migrate op then runs without a push
        target and every artifact relays through the router exactly as
        before."""
        rep = None
        for r in self.registry.members():
            if r.addr == victim_addr:
                rep = r
                break
        if rep is None:
            return None
        return self._pick_resume(
            {victim_addr}, rep.weights_version or "",
            model=rep.model_id or None,
            adapter=getattr(rep, "adapter_version", "") or None)

    # -- client-disconnect cancel propagation ------------------------------

    def _cancel_on_disconnect(self, emit, link):
        """Wrap a streaming partial emitter so a client that vanished
        mid-stream releases its replica row instead of decoding to the
        bitter end.  The gateway's relay exposes an ``emit.cancelled``
        probe (true once the client connection is closed); on the first
        partial frame that finds it true, send ONE fire-and-forget
        ``cancel`` op back down the same link (the frame's ``id`` is
        the replica-side call id) and swallow all further frames.
        Best-effort by design: an emitter without the probe, or a link
        without :meth:`notify` (sim/test stubs), passes through
        unchanged, and a lost cancel merely costs the tokens the
        request would have decoded anyway."""
        if emit is None:
            return emit
        cancelled = getattr(emit, "cancelled", None)
        notify = getattr(link, "notify", None)
        if cancelled is None or notify is None:
            return emit
        state = {"sent": False}

        def wrapped(frame):
            if cancelled():
                if not state["sent"]:
                    state["sent"] = True
                    head = frame.meta \
                        if isinstance(getattr(frame, "meta", None), dict) \
                        else frame
                    target = head.get("id") \
                        if isinstance(head, dict) else None
                    if target is not None:
                        try:
                            notify({"op": "cancel", "target": target})
                        except Exception:
                            pass    # advisory: never disturb the stream
                self.metrics.inc("stream_cancelled_frames")
                return              # the client is gone; drop the frame
            emit(frame)

        return wrapped

    # -- the routing loop --------------------------------------------------

    def route(self, msg: Dict[str, Any]) -> Any:
        """Send ``msg`` to a replica; on connection failure, retry on a
        different one (up to ``max_retries`` failovers, exponential
        backoff).  When both a prefill and a decode tier are alive, a
        generate request takes the DISAGGREGATED prefill→transfer→
        decode path first and falls back to the unified tier only when
        that path cannot serve it.  A ``suspended`` reply (the replica
        is being drain-migrated away) re-places the request — resuming
        its exported KV artifact on a same-version survivor, or
        re-running it from scratch — before the retry budget is ever
        charged a failure.

        A ``_trace`` context riding ``msg`` (the gateway attaches one
        per request) is ACTIVATED thread-locally for the whole routing
        loop: every attempt records a span with its outcome taxonomy,
        deep helpers (breaker filter, budget charges, chaos firings)
        attribute themselves to it, and replica-piggybacked hop spans
        are stitched back in at each attempt's start offset."""
        tr = msg.get("_trace") if isinstance(msg, dict) else None
        if tr is None and tracing.current() is None:
            # Nothing to activate and nothing to restore: skip the
            # context manager on the untraced hot path.
            return self._route(msg)
        with tracing.activate(tr):
            return self._route(msg)

    def _route(self, msg: Dict[str, Any]) -> Any:
        last: Optional[BaseException] = None
        deadline = self._deadline_of(msg)
        if isinstance(msg, dict) and msg.get("op") == "generate":
            out, last = self._route_disagg(msg)
            if out is not None:
                return out
        tried = set()
        deadline_cut = False
        prompt = msg.get("prompt") if isinstance(msg, dict) else None
        session = msg.get("session") if isinstance(msg, dict) else None
        session = session if isinstance(session, str) and session else None
        model = msg.get("_model") if isinstance(msg, dict) else None
        model = model if isinstance(model, str) and model else None
        background = bool(msg.get("_background")) \
            if isinstance(msg, dict) else False
        demanded = False
        # Streaming: the gateway's partial-frame emitter rides the
        # forward as the internal `_emit` (stripped by _wire_msg); each
        # attempt's partial token frames pass straight through to it,
        # and the gateway's offset de-dup makes retries exactly-once.
        # Passed CONDITIONALLY at every call site — link_factory
        # substitutes (the simulator's _SimLink, test stubs) do not
        # accept the on_partial kwarg, and unstreamed routing must not
        # require them to.
        emit = msg.get("_emit") if isinstance(msg, dict) else None
        for attempt in range(self.max_retries + 1):
            if deadline is not None and self._clock() >= deadline:
                # Fail fast, at the loop head: the client has given up,
                # and every further attempt (including the first) would
                # be pure waste — this is what keeps retries from
                # burning TPU time on expired work.
                return self._expired_reply("before a replica could "
                                           "serve it")
            addr = self.pick(exclude=tried, prompt=prompt,
                             session=session, model=model,
                             background=background)
            if addr is None and model is not None and not demanded \
                    and not tried and self.on_model_demand is not None:
                # Scale-to-zero cold start: no replica serves this
                # model RIGHT NOW.  Ask the control plane to assign
                # one (warm-pool adoption, or a launch) and WAIT for
                # it to become routable — bounded by the request's own
                # deadline and model_wait_s, so a model the trader
                # cannot place still fails explicitly, never hangs.
                demanded = True
                addr = self._await_model(model, deadline, prompt,
                                         session)
            if addr is None:
                break       # nothing (left) to try
            probe = self._breaker_dispatch(addr)
            att0 = tracing.cur_elapsed()
            t0 = self._clock()
            timeout = self._call_timeout(deadline,
                                         attempt >= self.max_retries)
            try:
                link = self._link(addr)
                if emit is not None:
                    reply = link.call(
                        self._wire_msg(msg, deadline), timeout=timeout,
                        on_partial=self._cancel_on_disconnect(emit,
                                                              link))
                else:
                    reply = link.call(self._wire_msg(msg, deadline),
                                      timeout=timeout)
            except CallTimeout as e:
                last = e
                self._trace_attempt("attempt", att0, addr, "timeout",
                                    clipped=timeout < self.request_timeout)
                if timeout < self.request_timeout:
                    # The call was cut short by the DEADLINE slice, not
                    # the flat timeout: if the loop ends here, the
                    # deadline — not replica availability — is the root
                    # cause, and the client error must say so.
                    deadline_cut = True
                if not self._note_timeout(
                        addr, tried, attempt, "request",
                        clipped=timeout < self.request_timeout,
                        probe=probe):
                    break
                continue
            except wire.WireError as e:
                # Deterministic for this request (it could not even be
                # encoded): no replica can serve it.
                raise RoutingError(
                    f"request not encodable for {addr}: {e}") from e
            except (ConnectionLost, OSError) as e:
                last = e
                self._trace_attempt("attempt", att0, addr,
                                    "link_failure")
                if not self._note_link_failure(e, addr, tried, attempt,
                                               "generate", probe=probe):
                    break
                continue
            s = self._suspended_of(reply)
            if s is None:
                if isinstance(reply, dict) \
                        and reply.get("op") == "error":
                    self._trace_attempt(
                        "attempt", att0, addr, "error_reply",
                        reply=reply, kind=str(reply.get("kind")))
                    if reply.get("kind") in ("bad_request",
                                             "deadline_exceeded"):
                        # Deterministic rejection: FINAL for the
                        # request, but not a success — it must neither
                        # refill the retry budget (which refills on
                        # delivered completions only, or a brown-out
                        # failing fast would keep re-arming its own
                        # retries) nor feed the breaker's success EWMA
                        # (a fast rejection would dilute a gray-slow
                        # replica's average and delay its isolation).
                        return reply
                    # Transient replica-side failure: breaker food, and
                    # another replica may still serve it.
                    err = RoutingError(
                        f"request failed on {addr}: "
                        f"{reply.get('error')}")
                    last = err
                    if not self._note_replica_error(addr, tried, err,
                                                    probe=probe):
                        break
                    continue
                self._trace_attempt("attempt", att0, addr, "ok",
                                    reply=reply)
                self._breaker_ok(addr, t0, probe)
                self.budget.on_success()
                return reply
            # Drain migration: the replica gave the request back.  The
            # victim is excluded (it is leaving), the artifact resumes
            # elsewhere — or the loop continues and re-runs the plain
            # request on a survivor, losing nothing either way.  The
            # prompt reply is a HEALTHY outcome for the breaker (a
            # drain is control-plane intent, not a failure — and a
            # half-open probe answered with `suspended` must not wedge).
            self._trace_attempt("attempt", att0, addr, "suspended",
                                reply=reply)
            self._breaker_ok(addr, t0, probe)
            tried.add(addr)
            self.metrics.inc("migration_exports")
            out = self._resume_elsewhere(msg, s[0], s[1], tried)
            if out is not None:
                return out
            self.metrics.inc("migration_reruns")
            tracing.cur_event("router", "migration_rerun", addr=addr)
            last = RoutingError(
                f"replica {addr} suspended the request mid-stream")
        if deadline_cut and isinstance(last, CallTimeout):
            return self._expired_reply(
                "in flight (every budget slice timed out)")
        if last is not None:
            raise RoutingError(
                f"no replica could serve the request after trying "
                f"{sorted(tried)}: {last}") from last
        raise RoutingError(
            f"no alive replicas serving model {model!r}" if model
            else "no alive replicas")

    def _await_model(self, model: str, deadline: Optional[float],
                     prompt, session) -> Optional[str]:
        """Fire the cold-start demand hook once and poll for a
        routable replica of ``model``.  Returns the first pick, or
        ``None`` when the wait budget (the request deadline, capped at
        ``model_wait_s``) runs out."""
        t0 = self._clock()
        self.metrics.inc("model_cold_waits")
        tracing.cur_event("router", "model_cold_start", model=model)
        try:
            if not self.on_model_demand(model):
                return None     # unknown model / nothing to free
        except Exception:
            self.log.exception("model demand hook failed for %r", model)
            return None
        limit = t0 + self.model_wait_s
        if deadline is not None:
            limit = min(limit, deadline)
        while self._clock() < limit:
            addr = self.pick(prompt=prompt, session=session, model=model)
            if addr is not None:
                self.metrics.observe("model_cold_wait_ms",
                                     (self._clock() - t0) * 1000.0)
                return addr
            self._sleep(0.05)
        return None

    # -- the disaggregated prefill -> transfer -> decode path --------------

    def _route_disagg(self, msg: Dict[str, Any]) -> tuple:
        """Try the two-phase path; returns ``(reply, last_error)`` —
        ``reply`` is ``None`` when the caller should fall back to the
        unified tier (a tier is empty, or the bounded retries ran out;
        every such path counts ``disagg_fallback``).  Only an answer
        DETERMINISTIC for the REQUEST (a completion, or a prefill-phase
        bad_request — the request itself is invalid) short-circuits the
        fallback: transient failures (internal errors, timeouts, dead
        connections) retry onto a different replica and then fall back,
        and a decode-phase bad_request (the tiers disagree about the
        artifact, not the request) falls back too — a healthy unified
        tier must still get its chance."""
        prompt = msg.get("prompt")
        model = msg.get("_model")
        model = model if isinstance(model, str) and model else None
        if isinstance(msg.get("session"), str) and msg["session"] \
                and self._alive_by_role((UNIFIED,), model=model):
            # Sessions ride the unified tier: their parked KV lives in
            # a unified replica's tier, and the disaggregated handoff
            # has no park/resume surface — only a PURE disagg fleet
            # serves a session-labeled request through it (cold).
            return None, None
        if (prompt is None or not len(prompt)) \
                and self._alive_by_role((UNIFIED,), model=model):
            # An invalid prompt gets its bad_request from a unified
            # replica's own validation when one exists; in a PURE
            # disagg fleet the request stays on this path so the
            # prefill replica rejects it loudly — never an
            # "unavailable: no alive replicas" for a malformed request.
            return None, None
        # Both tiers must be alive BEFORE phase 1 runs: with no decode
        # replica the prefill compute (and its KV export) would be pure
        # waste on the way to the unified fallback.  An all-unified
        # fleet (neither tier exists) is not a "fallback" — it is the
        # normal path; a LONE tier is one, and counts.
        has_prefill = bool(self._alive_by_role((PREFILL,), model=model))
        has_decode = bool(self._alive_by_role((DECODE,), model=model))
        if not (has_prefill and has_decode):
            if has_prefill or has_decode:
                self.metrics.inc("disagg_fallback")
            return None, None
        last: Optional[BaseException] = None
        deadline = self._deadline_of(msg)
        ptried: set = set()
        t0 = self._clock()
        for attempt in range(self.max_retries + 1):
            if deadline is not None and self._clock() >= deadline:
                return self._expired_reply("before prefill could "
                                           "run"), None
            paddr = self.pick_prefill(exclude=ptried, prompt=prompt,
                                      model=model)
            if paddr is None:
                break               # prefill tier exhausted
            call = {"op": "prefill", "prompt": msg.get("prompt"),
                    "max_new_tokens": msg.get("max_new_tokens"),
                    "stop_token": msg.get("stop_token"),
                    "priority": msg.get("priority")}
            # Direct peer streaming (docs/SERVING.md "Cross-host KV
            # fabric"): broker the decode address UP FRONT so the
            # prefill replica can push its KV straight there — bytes
            # never transit the router.  A saturated/empty pick just
            # omits the broker fields and the reply relays as before.
            xfer = daddr0 = None
            daddr0 = self.pick_decode(model=model)
            if daddr0 is not None:
                xfer = _new_xfer_id()
                call["push_to"] = daddr0
                call["xfer"] = xfer
            pprobe = self._breaker_dispatch(paddr)
            patt0 = tracing.cur_elapsed()
            tp = self._clock()
            # The prefill phase spends at most a quarter of the
            # remaining budget: decode is the long phase, and a hung
            # prefill replica must leave it a real slice.
            timeout = self._call_timeout(
                deadline, attempt >= self.max_retries, share=0.25)
            try:
                praw = self._link(paddr).call(
                    self._wire_msg(call, deadline), timeout=timeout)
            except CallTimeout as e:
                last = e
                self._trace_attempt("prefill", patt0, paddr, "timeout",
                                    clipped=timeout < self.request_timeout)
                if not self._note_timeout(
                        paddr, ptried, attempt, "prefill",
                        clipped=timeout < self.request_timeout,
                        probe=pprobe):
                    break
                continue
            except wire.WireError as e:
                # The prefill call is the same small JSON dict the
                # unified path would send — if it cannot encode, no
                # tier can serve it.
                raise RoutingError(
                    f"request not encodable for {paddr}: {e}") from e
            except (ConnectionLost, OSError) as e:
                last = e
                self._trace_attempt("prefill", patt0, paddr,
                                    "link_failure")
                if not self._note_link_failure(e, paddr, ptried,
                                               attempt, "prefill",
                                               probe=pprobe):
                    break
                continue
            pushed = (isinstance(praw, dict)
                      and praw.get("op") == "prefilled"
                      and praw.get("pushed") and xfer is not None)
            if isinstance(praw, dict) and not pushed:
                self._trace_attempt("prefill", patt0, paddr,
                                    "error_reply", reply=praw,
                                    kind=str(praw.get("kind")))
                if praw.get("kind") in ("bad_request",
                                        "deadline_exceeded"):
                    # Deterministic rejection: retrying elsewhere (or
                    # on the unified tier) cannot change the answer.
                    return praw, None
                # Transient replica-side failure (internal error, pool
                # exhaustion): another prefill replica may serve it.
                err = RoutingError(
                    f"prefill failed on {paddr}: {praw.get('error')}")
                last = err
                if not self._note_replica_error(paddr, ptried, err,
                                                probe=pprobe):
                    break
                continue
            if not pushed and (not isinstance(praw, wire.RawFrame)
                               or not isinstance(praw.meta, dict)):
                last = RoutingError(
                    f"malformed prefill reply from {paddr}")
                ptried.add(paddr)
                continue
            self._trace_attempt("prefill", patt0, paddr, "ok",
                                reply=praw)
            self._breaker_ok(paddr, tp, pprobe)
            ttft_ms = (self._clock() - t0) * 1000.0
            self.metrics.inc("disagg_prefills")
            if pushed:
                out, derr = self._disagg_decode_pushed(msg, praw,
                                                       daddr0)
            else:
                out, derr = self._disagg_decode(msg, praw)
            if out is not None:
                if isinstance(out, dict) and out.get("op") == "completion":
                    # The first token exists the moment the prefill
                    # reply lands; the decode replica's own ttft is the
                    # import turnaround, not the user-visible one.
                    dec_total = out.get("total_ms")
                    dec_ttft = out.get("ttft_ms")
                    if isinstance(dec_total, (int, float)) and \
                            isinstance(dec_ttft, (int, float)):
                        out["decode_ms"] = round(dec_total - dec_ttft, 3)
                    out["ttft_ms"] = round(ttft_ms, 3)
                    out["total_ms"] = round(
                        (self._clock() - t0) * 1000.0, 3)
                    self.metrics.inc("disagg_requests")
                    self.budget.on_success()
                return out, None
            # The decode tier could not take this VALID artifact within
            # its retry budget: re-running the whole prefill elsewhere
            # cannot revive a decode replica — fall back without
            # wasting another prompt's worth of compute.
            last = derr or last
            break
        self.metrics.inc("disagg_fallback")
        tracing.cur_event("router", "disagg_fallback")
        return None, last

    def _disagg_decode(self, msg: Dict[str, Any],
                       praw: "wire.RawFrame",
                       art_wv: Optional[str] = None) -> tuple:
        """Phase 2: forward the KV artifact to a decode replica as one
        raw frame; bounded retry onto a different decode replica
        (transient failures — connection loss, timeout, internal
        errors — retry; a bad_request rejection is deterministic and
        returns).  Returns ``(reply, last_error)`` with ``reply`` None
        when the tier is exhausted.  ``art_wv`` pre-pins the artifact's
        weights_version (a suspended mid-stream export adopted by the
        pushed path arrives already pinned)."""
        meta = {k: v for k, v in praw.meta.items()
                if k not in ("op", "id", "prefill_ms", "trace", "gen",
                             "weights_version")}
        meta.update(op="generate", prompt=msg.get("prompt"),
                    max_new_tokens=msg.get("max_new_tokens"),
                    stop_token=msg.get("stop_token"),
                    priority=msg.get("priority"))
        if msg.get("stream"):
            meta["stream"] = True
        emit = msg.get("_emit")
        deadline = self._deadline_of(msg)
        model = msg.get("_model")
        model = model if isinstance(model, str) and model else None
        last: Optional[BaseException] = None
        dtried: set = set()
        # A mid-stream artifact adopted from a drained decode replica
        # pins its weights_version: pages decoded under one set of
        # weights must only continue under the same (fresh prefill
        # artifacts carry no pin — the tier shares the fleet version).
        for attempt in range(self.max_retries + 1):
            if deadline is not None and self._clock() >= deadline:
                return self._expired_reply("before decode could "
                                           "run"), None
            daddr = self.pick_decode(exclude=dtried,
                                     weights_version=art_wv,
                                     model=model)
            if daddr is None:
                return None, last
            dprobe = self._breaker_dispatch(daddr)
            datt0 = tracing.cur_elapsed()
            timeout = self._call_timeout(deadline,
                                         attempt >= self.max_retries)
            try:
                tm = t0 = self._clock()
                if emit is not None:
                    dlink = self._link(daddr)
                    reply = dlink.call_raw(
                        self._wire_msg(meta, deadline), praw.body,
                        timeout=timeout,
                        on_partial=self._cancel_on_disconnect(emit,
                                                              dlink))
                else:
                    reply = self._link(daddr).call_raw(
                        self._wire_msg(meta, deadline), praw.body,
                        timeout=timeout)
                self.metrics.observe(
                    "kv_decode_turnaround_ms",
                    (self._clock() - t0) * 1000.0)
                # Counted only on a delivered transfer: a retried or
                # failed send must not inflate the bench's KV-transfer
                # throughput.
                self.metrics.inc("kv_transfer_bytes", len(praw.body))
            except CallTimeout as e:
                last = e
                self._trace_attempt("decode", datt0, daddr, "timeout",
                                    clipped=timeout < self.request_timeout)
                if not self._note_timeout(
                        daddr, dtried, attempt, "disagg decode",
                        clipped=timeout < self.request_timeout,
                        probe=dprobe):
                    return None, last
                continue
            except wire.WireError as e:
                # Deterministic for this ARTIFACT (its meta — prompt +
                # manifest — or the frame overflows the raw bounds),
                # not for the request: every decode replica would
                # reject the identical bytes, but a unified replica
                # serves the plain generate without them.  Fall back
                # without touching the healthy link.
                return None, RoutingError(
                    f"KV transfer to {daddr} not encodable: {e}")
            except (ConnectionLost, OSError) as e:
                last = e
                self._trace_attempt("decode", datt0, daddr,
                                    "link_failure")
                if not self._note_link_failure(e, daddr, dtried,
                                               attempt, "disagg decode",
                                               probe=dprobe):
                    return None, last
                continue
            s = self._suspended_of(reply)
            if s is not None:
                # The decode replica is being drain-migrated: adopt its
                # fresher suspended artifact (it holds the tokens
                # decoded so far) and retry on another decode replica —
                # or, on a requeue/fenced export, retry the ORIGINAL
                # prefill artifact, which re-decodes deterministically.
                # Healthy outcome for the breaker (see route()).
                self._trace_attempt("decode", datt0, daddr, "suspended",
                                    reply=reply)
                self._breaker_ok(daddr, tm, dprobe)
                dtried.add(daddr)
                self.metrics.inc("migration_exports")
                meta2, body2 = s
                if body2 is not None \
                        and self.registry.gen_allowed(meta2.get("gen")):
                    meta = {k: v for k, v in meta2.items()
                            if k not in ("op", "id", "gen",
                                         "weights_version", "trace")}
                    meta.update(op="generate", prompt=msg.get("prompt"),
                                max_new_tokens=msg.get("max_new_tokens"),
                                stop_token=msg.get("stop_token"),
                                priority=msg.get("priority"))
                    praw = wire.RawFrame(meta2, body2)
                    wv2 = meta2.get("weights_version")
                    art_wv = wv2 if isinstance(wv2, str) and wv2 else None
                last = RoutingError(
                    f"decode replica {daddr} suspended the request")
                continue
            if isinstance(reply, dict) and reply.get("op") == "error":
                self._trace_attempt("decode", datt0, daddr,
                                    "error_reply", reply=reply,
                                    kind=str(reply.get("kind")))
                if reply.get("kind") == "deadline_exceeded":
                    # The decode replica's in-batcher cancel fired:
                    # final for the request — falling back to unified
                    # would only burn more time on expired work.
                    return reply, None
                if reply.get("kind") == "bad_request":
                    # Deterministic for THIS artifact (a config
                    # mismatch between the tiers), not for the
                    # request: a unified replica can still serve the
                    # plain generate, so fall back instead of failing
                    # the client outright.  No retry within the tier —
                    # a homogeneous decode tier rejects everywhere,
                    # and each retry re-ships a multi-MB body.
                    return None, RoutingError(
                        f"decode replica {daddr} rejected the KV "
                        f"artifact: {reply.get('error')}")
                # Transient decode-side failure: another decode replica
                # (or the unified fallback) may still serve it.
                err = RoutingError(
                    f"decode failed on {daddr}: {reply.get('error')}")
                last = err
                if not self._note_replica_error(daddr, dtried, err,
                                                probe=dprobe):
                    return None, last
                continue
            self._trace_attempt("decode", datt0, daddr, "ok",
                                reply=reply)
            self._breaker_ok(daddr, tm, dprobe)
            self.metrics.inc("disagg_decodes")
            return reply, None
        return None, last

    def _disagg_decode_pushed(self, msg: Dict[str, Any],
                              pref: Dict[str, Any],
                              daddr: str) -> tuple:
        """Phase 2 after a DIRECT peer push: the KV artifact already
        sits staged on ``daddr`` (the prefill replica streamed it there
        and acked ``pushed``), so the decode call is one small dict
        naming the ``kv_ref``.  Single bounded attempt — the stage
        lives on exactly one host; any failure returns ``(None, err)``
        and the caller falls back to the unified tier, the stage just
        expires."""
        xfer = pref.get("xfer")
        nbytes = pref.get("bytes")
        emit = msg.get("_emit")
        deadline = self._deadline_of(msg)
        if deadline is not None and self._clock() >= deadline:
            return self._expired_reply("before decode could run"), None
        call = {"op": "generate", "kv_ref": xfer,
                "prompt": msg.get("prompt"),
                "max_new_tokens": msg.get("max_new_tokens"),
                "stop_token": msg.get("stop_token"),
                "priority": msg.get("priority")}
        if msg.get("stream"):
            call["stream"] = True
        dprobe = self._breaker_dispatch(daddr)
        datt0 = tracing.cur_elapsed()
        timeout = self._call_timeout(deadline, True)
        try:
            tm = t0 = self._clock()
            if emit is not None:
                dlink = self._link(daddr)
                reply = dlink.call(
                    self._wire_msg(call, deadline), timeout=timeout,
                    on_partial=self._cancel_on_disconnect(emit, dlink))
            else:
                reply = self._link(daddr).call(
                    self._wire_msg(call, deadline), timeout=timeout)
            self.metrics.observe("kv_decode_turnaround_ms",
                                 (self._clock() - t0) * 1000.0)
            # The bytes moved peer-to-peer (the prefill replica's ack
            # counted them); recorded only once the referencing decode
            # call DELIVERED, mirroring the relay path's discipline.
            if isinstance(nbytes, int) and nbytes > 0:
                self.metrics.inc("kv_transfer_bytes", nbytes)
                self.metrics.inc("kv_direct_bytes", nbytes)
            self.metrics.inc("kv_direct_transfers")
        except CallTimeout as e:
            self._trace_attempt("decode", datt0, daddr, "timeout",
                                clipped=timeout < self.request_timeout)
            return None, e
        except wire.WireError as e:
            return None, RoutingError(
                f"pushed decode call to {daddr} not encodable: {e}")
        except (ConnectionLost, OSError) as e:
            self._trace_attempt("decode", datt0, daddr, "link_failure")
            self._drop_link(daddr)
            self.registry.mark_dead(daddr, why="pushed decode link "
                                               "failure")
            return None, e
        s = self._suspended_of(reply)
        if s is not None:
            # The decode replica is being drained: adopt its fresher
            # suspended artifact through the standard relay machinery
            # (it carries the tokens decoded so far).
            self._trace_attempt("decode", datt0, daddr, "suspended",
                                reply=reply)
            self._breaker_ok(daddr, tm, dprobe)
            self.metrics.inc("migration_exports")
            meta2, body2 = s
            if body2 is not None \
                    and self.registry.gen_allowed(meta2.get("gen")):
                wv2 = meta2.get("weights_version")
                wv2 = wv2 if isinstance(wv2, str) and wv2 else None
                return self._disagg_decode(
                    msg, wire.RawFrame(meta2, body2), art_wv=wv2)
            return None, RoutingError(
                f"decode replica {daddr} suspended the pushed request")
        if isinstance(reply, dict) and reply.get("op") == "error":
            self._trace_attempt("decode", datt0, daddr, "error_reply",
                                reply=reply,
                                kind=str(reply.get("kind")))
            if reply.get("kind") == "deadline_exceeded":
                return reply, None
            # unknown kv_ref (stage expired/evicted), artifact
            # mismatch, transient failure: the stage is single-homed,
            # so every path falls back to the unified tier.
            return None, RoutingError(
                f"pushed decode on {daddr} failed: "
                f"{reply.get('error')}")
        self._trace_attempt("decode", datt0, daddr, "ok", reply=reply)
        self._breaker_ok(daddr, tm, dprobe)
        self.metrics.inc("disagg_decodes")
        return reply, None

    def close(self) -> None:
        with self._lock:
            links = list(self._links.values())
            self._links.clear()
        for link in links:
            link.close()
