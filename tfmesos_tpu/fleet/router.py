"""Load-aware request routing across the replica fleet.

Replica choice is **least-outstanding-requests with power-of-two-choices
sampling**: with many alive replicas, sampling two uniformly and taking
the less-loaded one gets within a constant of full least-loaded routing
at O(1) cost and — crucially — without the herd behavior of everyone
chasing the single globally-least-loaded replica between load updates.
The load signal is the router's OWN outstanding count per replica link
(what we have in hand is exact and instantaneous; the registry's
self-reported count lags a heartbeat).

Failure handling is **bounded retry-with-backoff onto a DIFFERENT
replica**: a connection failure (dial refused, mid-request EOF, bad
frame) marks the replica dead in the registry, drops its link, and the
request is retried elsewhere — safe for generation because replica
outputs are deterministic functions of the request (greedy streams are
bit-identical across replicas; the dead replica never delivered a
completion, so nothing double-counts).  After ``max_retries`` failovers
the request fails with :class:`RoutingError` and the gateway reports it
to the client explicitly — never a silent hang.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, Iterable, Optional

from tfmesos_tpu import wire
from tfmesos_tpu.fleet.client import CallTimeout, ConnectionLost, MuxConnection
from tfmesos_tpu.fleet.metrics import FleetMetrics
from tfmesos_tpu.fleet.registry import ReplicaRegistry
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["RoutingError", "Router"]


class RoutingError(RuntimeError):
    """No replica could serve the request within the retry budget."""


class Router:
    """Routes one request dict to one replica and returns its reply."""

    def __init__(self, registry: ReplicaRegistry, metrics: FleetMetrics,
                 token: str = "", max_retries: int = 2,
                 backoff_s: float = 0.05, request_timeout: float = 120.0,
                 connect_timeout: float = 10.0,
                 rng: Optional[random.Random] = None):
        self.registry = registry
        self.metrics = metrics
        self.token = token
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.request_timeout = float(request_timeout)
        self.connect_timeout = float(connect_timeout)
        self.log = get_logger("tfmesos_tpu.fleet.router")
        self._rng = rng or random.Random()
        self._links: Dict[str, MuxConnection] = {}
        self._lock = threading.Lock()

    # -- load signal -------------------------------------------------------

    def outstanding(self, addr: str) -> int:
        with self._lock:
            link = self._links.get(addr)
        return link.outstanding if link is not None and not link.closed else 0

    # -- replica choice ----------------------------------------------------

    def pick(self, exclude: Iterable[str] = ()) -> Optional[str]:
        """Power-of-two-choices over alive replicas not in ``exclude``;
        ``None`` when no eligible replica exists."""
        exclude = set(exclude)
        cands = [r.addr for r in self.registry.alive()
                 if r.addr not in exclude]
        if not cands:
            return None
        if len(cands) <= 2:
            return min(cands, key=self.outstanding)
        a, b = self._rng.sample(cands, 2)
        return a if self.outstanding(a) <= self.outstanding(b) else b

    # -- link management ---------------------------------------------------

    def _link(self, addr: str) -> MuxConnection:
        with self._lock:
            link = self._links.get(addr)
            if link is not None and not link.closed:
                return link
        # Dial OUTSIDE the lock: a black-holed endpoint blocks the dial
        # for up to connect_timeout, and holding the router-wide lock
        # through that would stall every worker's pick()/route() on the
        # healthy replicas too.  A dial race just keeps the first link
        # registered and closes the loser.
        link = MuxConnection(addr, self.token,
                             connect_timeout=self.connect_timeout)
        with self._lock:
            existing = self._links.get(addr)
            if existing is not None and not existing.closed:
                pass    # lost the race
            else:
                self._links[addr] = link
                return link
        link.close()
        return existing

    def _drop_link(self, addr: str) -> None:
        with self._lock:
            link = self._links.pop(addr, None)
        if link is not None:
            link.close()

    # -- the routing loop --------------------------------------------------

    def route(self, msg: Dict[str, Any]) -> Any:
        """Send ``msg`` to a replica; on connection failure, retry on a
        different one (up to ``max_retries`` failovers, exponential
        backoff)."""
        tried = set()
        last: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            addr = self.pick(exclude=tried)
            if addr is None:
                break       # nothing (left) to try
            try:
                link = self._link(addr)
                return link.call(msg, timeout=self.request_timeout)
            except CallTimeout as e:
                # The CONNECTION is still up (per CallTimeout's
                # contract) — only this request is slow.  Retry it
                # elsewhere, but do NOT collapse the shared link
                # (that would abort every other in-flight request on
                # this replica) and do NOT mark the replica dead.
                # The eventual late reply finds its slot gone and is
                # dropped; deterministic generation makes the
                # duplicated work harmless.
                last = e
                tried.add(addr)
                self.metrics.inc("retries")
                self.log.warning("request timed out on %s after %.0fs; "
                                 "retrying on another replica "
                                 "(attempt %d/%d)", addr,
                                 self.request_timeout, attempt + 1,
                                 self.max_retries + 1)
            except (ConnectionLost, OSError, wire.WireError) as e:
                last = e
                tried.add(addr)
                self._drop_link(addr)
                self.registry.mark_dead(
                    addr, why=f"{type(e).__name__}: {e}")
                self.metrics.inc("retries")
                self.log.warning("replica %s failed (%s); retrying on "
                                 "another replica (attempt %d/%d)", addr, e,
                                 attempt + 1, self.max_retries + 1)
                time.sleep(self.backoff_s * (2 ** attempt))
        if last is not None:
            raise RoutingError(
                f"no replica could serve the request after trying "
                f"{sorted(tried)}: {last}") from last
        raise RoutingError("no alive replicas")

    def close(self) -> None:
        with self._lock:
            links = list(self._links.values())
            self._links.clear()
        for link in links:
            link.close()
