"""Gang replicas: N member tasks, ONE routable replica.

A gang replica is a model sharded across a pod slice — the source
paper's Mesos-scheduled multi-host gang, brought to the serving fleet.
``TPUMesosScheduler.add_gang`` places the N member tasks atomically
(all-or-nothing within an offer batch, one launch generation); this
module is the in-process half: the **leader** (rank 0) owns the fleet
identity — the serve socket, the registry heartbeat, the batcher — and
fans every dispatched request to its **members** (ranks 1..N-1) over
the existing raw-HMAC wire frames; members execute and answer token
DIGESTS the leader verifies, so the SPMD invariant ("every mesh
process derives the same tokens") is continuously checked in flight.

Rendezvous is registry-mediated so placement stays atomic (no
leader-must-start-first ordering): every member learns its gang
identity from the launch env (``TPUMESOS_GANG_ID/SIZE/RANK``, stamped
by ``add_gang``), the leader advertises its member-coordination
address in the ``gang`` field of its heartbeats, and members poll the
registry's ``gang_lookup`` op until it appears.  Joins are fenced by
the exact ``(gang_id, generation)`` pair: gang ids are fresh per
launch and the generation is PR 3's epoch, so a zombie member of a
torn-down gang can never join — and a member that discovers a
NEWER-generation leader under its gang id knows *it* is the zombie
and exits.

Failure semantics: a gang member's death is the gang's death.  The
leader sees the member connection EOF, flags the gang broken, and
exits; its registry entry dies with the heartbeat connection (the
earliest death signal) so routing fails over immediately, and the
scheduler's dynamic-death hook lets the fleet launcher tear down the
surviving siblings and re-form the whole gang under a bumped
generation.  The leader never serves while forming: it registers
``warming`` and only flips routable once all members have joined.

On a real pod slice the members hold mesh shards of the model and the
dispatch fan-out carries per-shard work; under CI (CPU, and a jax
without ``shard_map``) members MIRROR-execute the full request — the
wire contract, placement atomicity, fencing, and failure semantics
are exactly the pod-slice ones, and the digest check is exactly the
SPMD token-identity invariant.  Everything here is jax-free; the
``execute`` callable a member runs is injected (the replica process
wraps its batcher; tests wrap a stub).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from tfmesos_tpu import wire
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["GANG_ENV_ID", "GANG_ENV_SIZE", "GANG_ENV_RANK",
           "read_gang_env", "token_digest", "GangLeader", "GangMember",
           "leader_handler"]

#: Launch-env contract (stamped per member by ``add_gang`` through the
#: scheduler's per-task env merge; inherited across the Mode-B exec).
GANG_ENV_ID = "TPUMESOS_GANG_ID"
GANG_ENV_SIZE = "TPUMESOS_GANG_SIZE"
GANG_ENV_RANK = "TPUMESOS_GANG_RANK"

#: Leader-side bound on un-verified dispatch records: acks for mids
#: evicted past this are ignored (a suspended/migrated request's
#: mirror ack legitimately never matches a local digest).
MAX_PENDING_DIGESTS = 256


def read_gang_env(environ=None) -> Optional[Tuple[str, int, int]]:
    """The ``(gang_id, size, rank)`` this process was launched into, or
    None for the single-process replica of old.  Malformed values read
    as no gang — a broken env must degrade to the long-standing
    behavior, not crash the replica."""
    environ = os.environ if environ is None else environ
    gid = environ.get(GANG_ENV_ID, "")
    if not gid:
        return None
    try:
        size = int(environ.get(GANG_ENV_SIZE, "0"))
        rank = int(environ.get(GANG_ENV_RANK, "-1"))
    except ValueError:
        return None
    if size < 2 or not 0 <= rank < size:
        return None
    return gid, size, rank


def token_digest(tokens) -> str:
    """Canonical digest of one completion's token stream — what a
    member acks and the leader compares (the in-flight SPMD
    token-identity check)."""
    h = hashlib.sha256()
    for t in tokens or ():
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.hexdigest()[:16]


class GangLeader:
    """Rank 0's member-coordination server.

    Owns a :class:`~tfmesos_tpu.wire.WireServer` the members dial;
    accepts ``gang_join`` (fenced by exact ``(gang_id, generation)``),
    fans ``gang_dispatch`` frames to every joined member, and verifies
    ``gang_ack`` digests against the leader's own completions.  A
    member connection EOF marks the gang BROKEN and fires ``on_break``
    once — the leader process exits on it, which is what turns one
    member's death into the gang's death fleet-wide."""

    def __init__(self, gang_id: str, size: int, generation: int = 0,
                 token: str = "", host: str = "127.0.0.1",
                 on_break: Optional[Callable[[int], None]] = None):
        if size < 2:
            raise ValueError(f"a gang needs >= 2 members, got {size}")
        self.gang_id = gang_id
        self.size = int(size)
        self.generation = int(generation)
        self.token = token
        self.host = host
        self.on_break = on_break
        self.log = get_logger("tfmesos_tpu.fleet.gang")
        self.divergence = 0         # digest mismatches observed
        self.dispatches = 0
        self._server: Optional[wire.WireServer] = None
        self._members: Dict[int, wire.WireConn] = {}
        self._pending: "OrderedDict[Any, dict]" = OrderedDict()
        self._lock = threading.Lock()
        self._formed = threading.Event()
        self._broken = threading.Event()
        self._stopping = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "GangLeader":
        self._server = wire.WireServer(
            self._on_msg, token=self.token, host=self.host,
            allow_raw=True, name="gang-leader",
            on_close=self._on_close).start()
        self.log.info("gang %s leader coordinating on %s (size %d, "
                      "generation %d)", self.gang_id, self._server.addr,
                      self.size, self.generation)
        return self

    def stop(self) -> None:
        self._stopping = True
        if self._server is not None:
            self._server.stop()

    @property
    def coord_addr(self) -> str:
        return self._server.addr if self._server is not None else ""

    @property
    def live(self) -> int:
        """Joined member count + the leader itself — the gang's
        member-liveness number (rides heartbeats into the registry)."""
        with self._lock:
            return 1 + len(self._members)

    @property
    def formed(self) -> bool:
        return self._formed.is_set()

    @property
    def broken(self) -> bool:
        return self._broken.is_set()

    def wait_formed(self, timeout: Optional[float] = None) -> bool:
        """Block until every member has joined (the leader's routable
        gate: it advertises ``warming`` until this returns True)."""
        return self._formed.wait(timeout)

    def gang_info(self) -> Dict[str, Any]:
        """The ``gang`` heartbeat field: identity, size, member
        liveness, and the coordination address ``gang_lookup`` serves
        to booting members."""
        return {"id": self.gang_id, "size": self.size,
                "live": self.live, "coord": self.coord_addr}

    # -- member protocol ---------------------------------------------------

    def _on_msg(self, conn, msg) -> None:
        head = msg.meta if isinstance(msg, wire.RawFrame) else msg
        if not isinstance(head, dict):
            return
        op = head.get("op")
        if op == "gang_join":
            self._join(conn, head)
        elif op == "gang_ack":
            self._ack(head)
        elif op == "ping":
            conn.send({"op": "pong", "id": head.get("id")})

    def _join(self, conn, head) -> None:
        try:
            rank = int(head.get("rank"))
            gen = int(head.get("gen"))
        except (TypeError, ValueError):
            conn.send({"op": "gang_joined", "ok": False,
                       "error": "malformed join"})
            conn.close()
            return
        # The zombie fence: the exact (gang_id, generation) pair must
        # match.  Gang ids are fresh per launch and the generation is
        # the launch epoch, so a straggler of a torn-down gang — or a
        # dispatch meant for another gang on a reused port — can never
        # take a member slot.
        if head.get("gang_id") != self.gang_id or gen != self.generation:
            self.log.warning(
                "gang %s refusing join (gang_id=%r gen=%r, ours gen %d)"
                ": fenced", self.gang_id, head.get("gang_id"), gen,
                self.generation)
            conn.send({"op": "gang_joined", "ok": False,
                       "error": "fenced: wrong gang or generation"})
            conn.close()
            return
        with self._lock:
            if not 1 <= rank < self.size or rank in self._members:
                ok = False
            else:
                self._members[rank] = conn
                conn.gang_rank = rank
                ok = True
                formed = len(self._members) == self.size - 1
        if not ok:
            conn.send({"op": "gang_joined", "ok": False,
                       "error": f"rank {rank} invalid or taken"})
            conn.close()
            return
        conn.send({"op": "gang_joined", "ok": True,
                   "gen": self.generation})
        self.log.info("gang %s member rank %d joined (%d/%d)",
                      self.gang_id, rank, self.live, self.size)
        if formed:
            self._formed.set()

    def _ack(self, head) -> None:
        mid = head.get("id")
        digest = head.get("digest")
        with self._lock:
            rec = self._pending.get(mid)
            if rec is None:
                return
            rec["acks"][head.get("rank")] = digest
            local = rec["local"]
        if local is not None and digest != local:
            self._note_divergence(mid, head.get("rank"), digest, local)

    def _on_close(self, conn) -> None:
        rank = getattr(conn, "gang_rank", None)
        if rank is None:
            return
        with self._lock:
            if self._members.get(rank) is not conn:
                return
            del self._members[rank]
        if self._stopping:
            return
        # A member's death is the gang's death: flag it once and let
        # on_break turn it into a process exit (the registry sees the
        # heartbeat EOF, the scheduler sees the task death, and the
        # fleet re-forms the whole gang).
        first = not self._broken.is_set()
        self._broken.set()
        self.log.warning("gang %s member rank %d lost: gang broken",
                         self.gang_id, rank)
        if first and self.on_break is not None:
            try:
                self.on_break(rank)
            except Exception:
                self.log.exception("on_break callback failed")

    # -- dispatch fan-out --------------------------------------------------

    def dispatch(self, head: Dict[str, Any]) -> None:
        """Fan one plain ``generate`` head to every joined member (the
        raw-HMAC frames the replica links already speak).  Non-blocking:
        sends ride each connection's buffered writer, acks verify
        asynchronously against :meth:`observe_local`."""
        mid = head.get("id")
        with self._lock:
            conns = list(self._members.values())
            self._pending[mid] = {"local": None, "acks": {}}
            while len(self._pending) > MAX_PENDING_DIGESTS:
                self._pending.popitem(last=False)
        self.dispatches += 1
        out = dict(head)
        out["op"] = "gang_dispatch"
        for conn in conns:
            conn.send(out)

    def observe_local(self, mid, tokens) -> None:
        """Record the leader's own completion for ``mid`` and verify
        any member acks already in."""
        local = token_digest(tokens)
        stale = []
        with self._lock:
            rec = self._pending.get(mid)
            if rec is None:
                return
            rec["local"] = local
            stale = [(r, d) for r, d in rec["acks"].items()
                     if d != local]
        for rank, digest in stale:
            self._note_divergence(mid, rank, digest, local)

    def _note_divergence(self, mid, rank, digest, local) -> None:
        self.divergence += 1
        self.log.error(
            "gang %s TOKEN DIVERGENCE on request %r: member rank %s "
            "digest %s != leader %s (SPMD invariant violated)",
            self.gang_id, mid, rank, digest, local)


class GangMember:
    """Rank 1..N-1's whole life: find the leader through the registry,
    join (fenced), mirror-execute dispatches, ack digests, die with
    the leader.

    ``execute(head) -> tokens`` is injected: the replica process wraps
    its own batcher (mirror execution of the full request — the CPU
    stand-in for holding a mesh shard); tests wrap a stub."""

    def __init__(self, gang_id: str, size: int, rank: int,
                 generation: int, registry_addr: str, token: str = "",
                 execute: Optional[Callable[[Dict[str, Any]], Any]] = None,
                 poll_interval: float = 0.2,
                 lookup_timeout: float = 120.0):
        if not 1 <= rank < size:
            raise ValueError(f"member rank must be in [1, {size}), "
                             f"got {rank}")
        self.gang_id = gang_id
        self.size = int(size)
        self.rank = int(rank)
        self.generation = int(generation)
        self.registry_addr = registry_addr
        self.token = token
        self.execute = execute
        self.poll_interval = float(poll_interval)
        self.lookup_timeout = float(lookup_timeout)
        self.served = 0
        self.log = get_logger("tfmesos_tpu.fleet.gang")

    # -- rendezvous --------------------------------------------------------

    def _lookup_once(self) -> Optional[Dict[str, Any]]:
        sock = None
        try:
            sock = wire.connect(self.registry_addr, timeout=5.0)
            wire.send_msg(sock, {"op": "gang_lookup",
                                 "gang_id": self.gang_id}, self.token)
            reply = wire.recv_msg(sock, self.token)
            return reply if isinstance(reply, dict) else None
        except (OSError, wire.WireError):
            return None
        finally:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

    def find_leader(self, stop: Optional[threading.Event] = None
                    ) -> Optional[str]:
        """Poll ``gang_lookup`` until the leader's coord addr appears
        for OUR generation.  A leader advertising a newer generation
        means this process is the zombie of a torn-down gang — give up
        immediately (the fence's mirror image)."""
        deadline = time.monotonic() + self.lookup_timeout
        while time.monotonic() < deadline:
            if stop is not None and stop.is_set():
                return None
            info = self._lookup_once()
            if info and info.get("found"):
                try:
                    gen = int(info.get("gen"))
                except (TypeError, ValueError):
                    gen = None
                if gen is not None and gen > self.generation:
                    self.log.warning(
                        "gang %s leader runs generation %s, ours is %d:"
                        " we are the zombie; exiting", self.gang_id,
                        gen, self.generation)
                    return None
                if gen == self.generation:
                    coord = info.get("coord")
                    if isinstance(coord, str) and coord:
                        return coord
            if stop is not None:
                if stop.wait(self.poll_interval):
                    return None
            else:
                time.sleep(self.poll_interval)
        self.log.warning("gang %s rank %d: leader never appeared in "
                         "%.0fs", self.gang_id, self.rank,
                         self.lookup_timeout)
        return None

    # -- serve loop --------------------------------------------------------

    def run(self, stop: Optional[threading.Event] = None) -> str:
        """The member's whole life; returns why it ended — one of
        ``"no_leader"``, ``"refused"``, ``"leader_eof"``,
        ``"stopped"``."""
        coord = self.find_leader(stop)
        if coord is None:
            return "no_leader"
        sock = None
        try:
            sock = wire.connect(coord, timeout=10.0)
            sock.settimeout(None)
            wire.send_msg(sock, {"op": "gang_join",
                                 "gang_id": self.gang_id,
                                 "rank": self.rank,
                                 "gen": self.generation}, self.token)
            framer = wire.Framer(self.token, allow_raw=True)
            for msg in wire.iter_msgs(sock, framer):
                if stop is not None and stop.is_set():
                    return "stopped"
                head = (msg.meta if isinstance(msg, wire.RawFrame)
                        else msg)
                if not isinstance(head, dict):
                    continue
                op = head.get("op")
                if op == "gang_joined":
                    if not head.get("ok"):
                        self.log.warning(
                            "gang %s rank %d join refused: %s",
                            self.gang_id, self.rank,
                            head.get("error"))
                        return "refused"
                    self.log.info("gang %s rank %d joined leader %s",
                                  self.gang_id, self.rank, coord)
                elif op == "gang_dispatch":
                    self._serve_one(sock, head)
            return "stopped" if (stop is not None and stop.is_set()) \
                else "leader_eof"
        except (OSError, wire.WireError) as e:
            self.log.warning("gang %s rank %d link error: %s",
                             self.gang_id, self.rank, e)
            return "leader_eof"
        finally:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

    def _serve_one(self, sock, head) -> None:
        try:
            tokens = self.execute(head) if self.execute else []
            digest = token_digest(tokens)
        except Exception as e:
            self.log.exception("gang %s rank %d mirror execution "
                               "failed: %s", self.gang_id, self.rank, e)
            digest = f"error:{type(e).__name__}"
        self.served += 1
        wire.send_msg(sock, {"op": "gang_ack", "id": head.get("id"),
                             "rank": self.rank, "digest": digest},
                      self.token)


def leader_handler(inner: Callable, leader: GangLeader) -> Callable:
    """Wrap a replica handler with the gang fan-out: plain ``generate``
    dicts are dispatched to every member before the leader serves them
    locally, and the leader's own completion tokens feed the digest
    verification.  Raw frames (disaggregated KV imports) and control
    ops pass straight through — members mirror the decode stream, not
    the control plane."""

    def handler(msg, reply: Callable) -> None:
        if not isinstance(msg, dict) or msg.get("op") != "generate":
            inner(msg, reply)
            return
        mid = msg.get("id")
        leader.dispatch(msg)

        def wrapped(out) -> None:
            if isinstance(out, dict) and out.get("op") == "completion":
                leader.observe_local(mid, out.get("tokens") or [])
            reply(out)

        wrapped.partial = getattr(reply, "partial", None)
        inner(msg, wrapped)

    return handler
