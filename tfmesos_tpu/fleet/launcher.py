"""Fleet bring-up: registry + gateway + dynamically-launched replicas.

``FleetServer`` is the one-object front: it generates a cluster token,
starts the registry and gateway locally, then launches the replicas as
**Mode-B tasks through the backend abstraction** — ``LocalBackend``
(the default with no master) runs whole fleets as CPU subprocesses for
development and CI; a Mesos master runs them on TPU agents with
per-replica chip/mem reservations.  The scheduler, registry, and
gateway share ONE token, delivered to replicas over the scheduler's
existing transport (mode-0600 token file for co-located backends), so
every hop of the serving path is authenticated with the same secret.

Replica membership is a RUNTIME property, not a launch-time constant
(the TF-Replicator stance): the scheduler runs in dynamic mode with an
initially-empty task table, and each tier converges toward a target
count — ``launch_replica``/``kill_replica`` grow and shrink it one
Mode-B task at a time, ``--autoscale`` hands the targets to a
:class:`~tfmesos_tpu.fleet.autoscaler.FleetAutoscaler` feedback loop,
and :meth:`FleetServer.rollout` replaces a whole tier's weights
blue-green with zero downtime (launch new-version replicas, warm them,
shift the router's version preference, bake, drain, reap — with the
registry's generation fence keeping reaped-generation stragglers out of
the serving path forever).

Replica death is a SERVING event here, not a cluster event: the
scheduler's fail-fast policy is for training meshes (which cannot
hot-swap members); the fleet instead routes around dead replicas and —
with the autoscaler on — relaunches them from the convergence loop.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from tfmesos_tpu import wire
from tfmesos_tpu.fleet.admission import AdmissionController, PriorityClass
from tfmesos_tpu.fleet.autoscaler import AutoscalerConfig, FleetAutoscaler
from tfmesos_tpu.fleet.catalog import (POOL, POOL_KEY, ModelCatalog,
                                       ModelSpec, ModelTrader,
                                       TraderConfig, filter_members,
                                       model_key, pack_adapter,
                                       split_key)
from tfmesos_tpu.fleet.client import FleetClient
from tfmesos_tpu.fleet.gateway import Gateway
from tfmesos_tpu.fleet.metrics import FleetMetrics
from tfmesos_tpu.fleet.registry import (ALIVE, DEAD, DECODE, KV,
                                        PREFILL, UNIFIED, WARMING,
                                        ReplicaRegistry,
                                        validate_model_id)
from tfmesos_tpu.fleet.router import Router
from tfmesos_tpu.fleet.tracing import TraceBook
from tfmesos_tpu.scheduler import (MAX_FAILURE_COUNT, ClusterError,
                                   TPUMesosScheduler)
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["FleetServer", "RolloutError"]

#: tier role -> the scheduler job name its Mode-B tasks launch under.
TIER_JOBS = {UNIFIED: "replica", PREFILL: "prefill", DECODE: "decode",
             KV: "kv"}

#: weights_version labels join the replica COMMAND LINE, which Mode-B
#: agents execute with shell=True — the charset is a hard security
#: boundary, not cosmetics: a serve-token holder drives rollout through
#: the gateway op, and PR 4's hardening promise (a token cannot become
#: code execution) must hold for this surface too.
_VERSION_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9._-]{0,63}")

#: the KV-tier disk directory joins the same shell=True command line —
#: same boundary (conservative path charset, no whitespace, no shell
#: metacharacters, and no leading '-' that argparse would eat as a
#: flag).
_KV_DIR_RE = re.compile(r"[A-Za-z0-9/._~][A-Za-z0-9/._~+-]{0,255}")


def validate_kv_tier_dir(path: str) -> str:
    path = str(path)
    if not _KV_DIR_RE.fullmatch(path):
        raise ValueError(
            f"kv_tier_dir {path!r} is not a safe path: want 1-256 "
            f"chars of [A-Za-z0-9/._~+-] not starting with '-' or '+' "
            f"(it joins the replica command line, so the charset is a "
            f"security boundary)")
    return path


def validate_weights_version(version: str) -> str:
    version = str(version)
    # fullmatch, not match-with-$: '$' would accept a trailing newline,
    # which shell=True treats as a command terminator.
    if not _VERSION_RE.fullmatch(version):
        raise ValueError(
            f"weights_version {version!r} is not a valid label: want "
            f"1-64 chars of [A-Za-z0-9._-] starting alphanumeric (it "
            f"joins the replica command line, so the charset is a "
            f"security boundary)")
    return version


class RolloutError(RuntimeError):
    """A blue-green rollout aborted (the old version kept serving)."""


class FleetServer:
    """Bring up (and tear down) a whole serving fleet."""

    def __init__(self, replicas: int = 2, rows: int = 4,
                 tiny: bool = False, seed: int = 0,
                 max_len: Optional[int] = None,
                 page_size: Optional[int] = None,
                 prefill_bucket: Optional[int] = None,
                 multi_step: int = 1,
                 prefix_cache_pages: int = 0,
                 pipeline_depth: int = 0,
                 fused_prefill: bool = False,
                 tokens_per_tick: Optional[int] = None,
                 draft: bool = False,
                 n_draft: int = 4,
                 kv_tier_mb: float = 0.0,
                 kv_tier_dir: Optional[str] = None,
                 kv_replication: int = 1,
                 kv_placement: str = "rendezvous",
                 kv_replicas: int = 0,
                 warmup: bool = False,
                 prefill_replicas: int = 0,
                 decode_replicas: int = 0,
                 models: Optional[List[ModelSpec]] = None,
                 gang_size: int = 1,
                 warm_pool: int = 0,
                 model_budget: Optional[int] = None,
                 trader_config: Optional[TraderConfig] = None,
                 weights_version: str = "v0",
                 autoscale: bool = False,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 autoscale_config: Optional[AutoscalerConfig] = None,
                 backend=None, master: Optional[str] = None,
                 replica_cpus: float = 1.0, replica_mem: float = 1024.0,
                 replica_chips: int = 0,
                 gateway_host: str = "127.0.0.1", gateway_port: int = 0,
                 gateways: int = 1,
                 gateway_processes: int = 0,
                 http_port: Optional[int] = None,
                 workers: int = 8, max_queue: int = 64,
                 rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 priority_classes: Optional[List[PriorityClass]] = None,
                 batch_lane: bool = False,
                 migrate_on_drain: bool = True,
                 breakers: bool = True,
                 max_retries: int = 2, request_timeout: float = 120.0,
                 start_timeout: float = 300.0,
                 heartbeat_interval: float = 0.3,
                 report_interval: Optional[float] = None,
                 metrics_port: Optional[int] = None,
                 trace_sample: float = 0.05,
                 trace_slow_ms: float = 1000.0,
                 quiet: bool = True, token: Optional[str] = None):
        if min(replicas, prefill_replicas, decode_replicas) < 0:
            raise ValueError(
                f"replica counts must be >= 0, got replicas={replicas} "
                f"prefill_replicas={prefill_replicas} "
                f"decode_replicas={decode_replicas}")
        if (prefill_replicas > 0) != (decode_replicas > 0):
            raise ValueError(
                f"prefill_replicas and decode_replicas come together — "
                f"a lone tier cannot serve the disaggregated handoff "
                f"(got prefill_replicas={prefill_replicas}, "
                f"decode_replicas={decode_replicas})")
        # Gang replicas (docs/SERVING.md "Gang replicas"): each unified
        # "replica" is N member tasks forming one pod-slice mesh,
        # scheduled as an atomic gang and routed as ONE replica (the
        # leader).  gang_size=1 is the classic single-process fleet —
        # zero behavior change.  Role-split tiers stay single-process
        # (the disaggregated handoff is a per-request hop, not a mesh).
        self.gang_size = int(gang_size)
        if self.gang_size < 1:
            raise ValueError(
                f"gang_size must be >= 1, got {gang_size}")
        if self.gang_size > 1 and (prefill_replicas or decode_replicas):
            raise ValueError(
                "gang replicas serve the unified tier; drop "
                "prefill_replicas/decode_replicas or gang_size")
        # Model catalog (docs/SERVING.md "Model catalog"): with
        # ``models``, the catalog entries size the fleet (each entry's
        # own ``replicas``), a ``warm_pool`` of undedicated pre-warmed
        # replicas caps cold-start TTFT, and every replica count lives
        # under ONE fleet-wide ``model_budget`` the trader reallocates
        # within.  ``replicas`` (the single-model knob) is ignored,
        # and the disaggregated role split is per-model routing only —
        # launching per-model role tiers is a later PR.
        self.catalog: Optional[ModelCatalog] = None
        self.warm_pool = int(warm_pool)
        self.trader_config = trader_config
        self.trader: Optional[ModelTrader] = None
        self.replica_budget: Optional[int] = None
        if self.warm_pool < 0:
            raise ValueError(f"warm_pool must be >= 0, got {warm_pool}")
        if models:
            if prefill_replicas or decode_replicas:
                raise ValueError(
                    "a model catalog runs unified tiers; drop "
                    "prefill_replicas/decode_replicas")
            self.catalog = ModelCatalog(models)
            # Budget math is in SLOTS (member tasks): a gang replica
            # of size N occupies N of them.
            boot = sum(s.replicas * s.gang_size for s in self.catalog)
            if boot + self.warm_pool < 1:
                raise ValueError(
                    "the catalog fleet needs at least one replica: "
                    "every entry boots 0 and warm_pool is 0")
            self.replica_budget = int(model_budget) \
                if model_budget is not None else boot + self.warm_pool
            if self.replica_budget < max(1, boot + self.warm_pool):
                raise ValueError(
                    f"model_budget ({self.replica_budget}) is below "
                    f"the boot footprint ({boot} model replicas + "
                    f"{self.warm_pool} warm pool)")
            replicas = 0
        elif self.warm_pool or model_budget is not None:
            raise ValueError("warm_pool/model_budget need a model "
                             "catalog (models=[...])")
        if self.catalog is None \
                and replicas + prefill_replicas + decode_replicas < 1:
            raise ValueError(
                f"the fleet needs at least one replica, got "
                f"replicas={replicas} + prefill_replicas="
                f"{prefill_replicas} + decode_replicas={decode_replicas}")
        self.replicas = int(replicas)
        self.prefill_replicas = int(prefill_replicas)
        self.decode_replicas = int(decode_replicas)
        initial = {UNIFIED: self.replicas, PREFILL: self.prefill_replicas,
                   DECODE: self.decode_replicas}
        # Autoscale bounds are PER TIER: an explicit --max-replicas
        # applies to every tier, but the default ceiling is twice EACH
        # tier's own initial count (a decode tier booted at 1 must not
        # inherit a 4-replica prefill tier's headroom), and a
        # non-autoscaled fleet's ceiling is exactly what was asked for.
        self.min_replicas = 1 if min_replicas is None else int(min_replicas)
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1 (a routable tier can never "
                f"scale to zero), got {self.min_replicas}")
        self._tier_max: Dict[str, int] = {}
        for role, n in initial.items():
            if not n:
                continue
            if max_replicas is not None:
                self._tier_max[role] = int(max_replicas)
            else:
                self._tier_max[role] = max(2 * n, n + 1) if autoscale \
                    else n
        if self.catalog is not None:
            # Per-(model, tier) bounds are the trader's business: each
            # key may range [0, budget] — floors and scale-to-zero live
            # in the catalog entries, the ceiling is the shared budget.
            self.max_replicas = self.replica_budget
        else:
            self.max_replicas = max(self._tier_max.values())
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})")
        for role, n in initial.items():
            if n and not (self.min_replicas <= n
                          <= self._tier_max[role]):
                raise ValueError(
                    f"initial {role} tier count {n} lies outside the "
                    f"autoscale bounds [{self.min_replicas}, "
                    f"{self._tier_max[role]}]")
        self.weights_version = validate_weights_version(weights_version)
        self.autoscale = bool(autoscale)
        self.autoscale_config = autoscale_config
        self.rows = int(rows)
        self.tiny = bool(tiny)
        self.seed = int(seed)
        self.max_len = max_len
        self.page_size = page_size
        self.prefill_bucket = prefill_bucket
        self.multi_step = int(multi_step)
        self.prefix_cache_pages = int(prefix_cache_pages)
        self.pipeline_depth = int(pipeline_depth)
        #: stall-free fused scheduling per replica (docs/SERVING.md
        #: "Stall-free fused scheduling"): one dispatch per tick covers
        #: the decode block AND a budgeted batch of prefill chunk
        #: slots.  Default off; modes the fused program cannot cover
        #: bypass inside the batcher with a recorded reason.  Both
        #: values join the shell=True replica command line, so both are
        #: validated as ints/bools here (str(int) is charset-safe).
        self.fused_prefill = bool(fused_prefill)
        self.tokens_per_tick = (None if tokens_per_tick is None
                                else int(tokens_per_tick))
        if self.tokens_per_tick is not None and self.tokens_per_tick < 1:
            raise ValueError(f"tokens_per_tick must be >= 1, got "
                             f"{tokens_per_tick}")
        #: speculative decoding per replica (replicas serve with the
        #: preset draft companion model; the acceptance rate rides
        #: heartbeats into the gateway's ``spec`` gauge).  Composes
        #: with the prefix cache, the KV tier, migration, and the
        #: disagg role split — the bypass registry enforces what
        #: doesn't (docs/SERVING.md "Speculative decoding &
        #: composition").
        self.draft = bool(draft)
        self.n_draft = int(n_draft)
        if self.draft and self.n_draft < 1:
            raise ValueError(f"n_draft must be >= 1, got {n_draft}")
        #: tiered KV store per replica (docs/SERVING.md "KV tiering &
        #: sessions"): a >0 RAM budget turns it on; with no explicit
        #: disk dir the launcher mints ONE host-shared temp directory
        #: so every co-located replica can resume any sibling's parked
        #: sessions (removed on stop).  0/None = off: zero behavior
        #: change.
        if kv_tier_mb < 0:
            raise ValueError(f"kv_tier_mb must be >= 0, got {kv_tier_mb}")
        self.kv_tier_mb = float(kv_tier_mb)
        self.kv_tier_dir = (validate_kv_tier_dir(kv_tier_dir)
                            if kv_tier_dir is not None else None)
        self._kv_tier_tmp: Optional[str] = None
        #: cross-host KV fabric (docs/SERVING.md "Cross-host KV
        #: fabric"): replication is the K-way parking factor each
        #: replica's fabric wrapper enforces (1 = local-only, the
        #: pre-fabric behavior exactly); kv_replicas boots that many
        #: dedicated KV-role holders — storage-only peers that park
        #: sessions/prefixes but never serve tokens, so artifacts
        #: survive every serving replica scaling to zero.  Both join
        #: the shell=True replica command line, so both are validated
        #: as ints here (str(int) emits [0-9]+ only — charset-safe).
        self.kv_replication = int(kv_replication)
        if not 1 <= self.kv_replication <= 8:
            raise ValueError(
                f"kv_replication must be in [1, 8], got {kv_replication}")
        #: replica-copy placement policy for the KV fabric (PR 18's sim
        #: knob promoted to production): "rendezvous" = pure HRW hash;
        #: "loaded" = HRW within occupancy buckets, so loaded peers
        #: shed copy traffic (tuned via ``tfserve simulate sessions
        #: --sweep kv.placement=rendezvous,loaded``).  Validated against
        #: the closed set here because it joins the shell=True replica
        #: command line.
        if kv_placement not in ("rendezvous", "loaded"):
            raise ValueError(f"kv_placement must be 'rendezvous' or "
                             f"'loaded', got {kv_placement!r}")
        self.kv_placement = kv_placement
        self.kv_replicas = int(kv_replicas)
        if self.kv_replicas < 0:
            raise ValueError(
                f"kv_replicas must be >= 0, got {kv_replicas}")
        if self.kv_replicas and self.kv_tier_mb <= 0:
            raise ValueError(
                "dedicated KV-role replicas hold tier artifacts — they "
                "need kv_tier_mb > 0")
        if self.kv_replicas:
            # The kv tier is pinned at its boot size: the autoscaler's
            # signals (queue wait, utilization) never move for a
            # storage-only holder, so letting the loop retarget it
            # would only ever shrink it.
            self._tier_max[KV] = self.kv_replicas
        self.warmup = bool(warmup)
        self.backend = backend
        self.master = master
        self.replica_cpus = float(replica_cpus)
        self.replica_mem = float(replica_mem)
        self.replica_chips = int(replica_chips)
        self.gateway_host = gateway_host
        self.gateway_port = int(gateway_port)
        #: horizontal front-door scale (docs/SERVING.md "Front-door
        #: scaling"): N stateless gateways over ONE shared
        #: registry/router/admission view.  The first listens on
        #: ``gateway_port``, the rest on OS-assigned ports; all
        #: register for the ``gateways`` discovery op, and
        #: FleetClient fails over between them.
        self.n_gateways = int(gateways)
        if self.n_gateways < 1:
            raise ValueError(
                f"gateways must be >= 1, got {gateways}")
        #: multi-PROCESS front door (docs/SERVING.md "Multi-process
        #: gateways"): > 0 replaces the in-process gateway threads with
        #: N ``python -m tfmesos_tpu.fleet.gateway`` OS processes, each
        #: running its own WireServer/admission/router over a registry-
        #: client sidecar's mirrored view.  They share ONE public port
        #: via SO_REUSEPORT where the platform has it, else fall back
        #: to per-process ports behind the ``gateways`` discovery op.
        #: 0 = in-process mode, the pre-PR behavior exactly.
        self.gateway_processes = int(gateway_processes)
        if self.gateway_processes < 0:
            raise ValueError(
                f"gateway_processes must be >= 0, got {gateway_processes}")
        if self.gateway_processes and self.catalog is not None:
            # The trader answers cold-start demand through the SHARED
            # in-process router; a subprocess gateway's private router
            # has no trader to ask, so a catalog fleet would silently
            # lose scale-from-zero.  Refuse loudly instead.
            raise ValueError(
                "gateway_processes and a model catalog are mutually "
                "exclusive: catalog cold-start demand rides the "
                "in-process router")
        #: HTTP/1.1 + SSE ingress (docs/SERVING.md "HTTP/SSE edge"):
        #: None = off (the pre-PR wire-only surface).  In-process mode
        #: gives the port to the FIRST gateway; in subprocess mode the
        #: first gateway process carries it.
        self.http_port = None if http_port is None else int(http_port)
        self.workers = int(workers)
        self.max_queue = int(max_queue)
        self.rate = rate
        self.burst = burst
        #: admission priority classes (weighted-fair queues at the
        #: gateway + preemption ranks inside the replicas); None = one
        #: default class, the pre-priority behavior exactly.
        self.priority_classes = list(priority_classes) \
            if priority_classes else None
        #: the OFFLINE lane (docs/SERVING.md "Offline lane"): appends a
        #: deadline-less ``batch`` class that dispatches only when
        #: every interactive queue is empty (strict background at the
        #: gateway's WFQ) and ranks BELOW every other class, so its
        #: resident rows yield their decode slots to the first
        #: interactive arrival via the replicas' preemption machinery.
        self.batch_lane = bool(batch_lane)
        if self.batch_lane:
            specs = (list(self.priority_classes)
                     if self.priority_classes
                     else [PriorityClass("interactive", weight=1.0,
                                         rank=0)])
            if not any(c.name == "batch" for c in specs):
                floor = min(c.rank for c in specs)
                specs.append(PriorityClass("batch", weight=1.0,
                                           rank=floor - 1, batch=True))
            self.priority_classes = specs
        #: drain-migrate-kill: when a drain is pinned (autoscaler
        #: scale-down, rollout reap), ask the victim to SUSPEND its
        #: in-flight rows so the router re-places them on survivors —
        #: instead of waiting for them to finish (or worse, flushing
        #: them).  False restores plain drain-then-kill.
        self.migrate_on_drain = bool(migrate_on_drain)
        #: per-replica circuit breakers in the router (consecutive-
        #: failure + latency-outlier tripping); False is the bench's
        #: control arm and an operator escape hatch, never the default.
        self.breakers = bool(breakers)
        self.max_retries = int(max_retries)
        self.request_timeout = float(request_timeout)
        self.start_timeout = float(start_timeout)
        self.heartbeat_interval = float(heartbeat_interval)
        self.report_interval = report_interval
        #: optional stdlib-HTTP Prometheus exposition (GET /metrics on
        #: loopback); None = no endpoint, the pre-PR-10 behavior.
        self.metrics_port = metrics_port
        #: request-tracing knobs (docs/SERVING.md "Observability"):
        #: head-sample fraction retained with FULL span detail, and the
        #: slower-than-this tail threshold that retains detail
        #: regardless — failures/sheds/deadline-exceeded always retain.
        self.trace_sample = float(trace_sample)
        self.trace_slow_ms = float(trace_slow_ms)
        self.quiet = quiet
        self.log = get_logger("tfmesos_tpu.fleet", quiet=quiet)

        # An explicit token lets external clients authenticate (tfserve
        # resolves one from the standard TPUMESOS_TOKEN/_FILE contract);
        # by default each bring-up mints its own.
        self._token = token
        self.token: Optional[str] = None
        self.metrics: Optional[FleetMetrics] = None
        self.tracebook: Optional[TraceBook] = None
        self._metrics_http = None
        self.registry: Optional[ReplicaRegistry] = None
        self.router: Optional[Router] = None
        self.admission: Optional[AdmissionController] = None
        self.gateway: Optional[Gateway] = None
        #: every running front door (``gateway`` is ``gateways[0]``).
        self.gateways: List[Gateway] = []
        #: gateway OS processes (subprocess mode); empty in-process.
        self._gateway_procs: list = []
        #: the HTTP/SSE edge address once bound (either mode).
        self.http_addr: Optional[str] = None
        self.scheduler: Optional[TPUMesosScheduler] = None
        self.autoscaler: Optional[FleetAutoscaler] = None
        #: per-tier replica targets — what the control plane WANTS; the
        #: convergence loops (autoscaler, _wait_replicas) drive actuals
        #: toward these.
        self.targets: Dict[str, int] = {}
        #: serializes every scaling decision: autoscaler ticks and
        #: rollouts are mutually exclusive (a rollout must not race the
        #: loop retargeting the tier it is replacing).
        self.scale_lock = threading.RLock()
        #: node id -> target key ("role", or "model/role" / POOL_KEY in
        #: catalog mode): how per-(model, tier) actuals are counted
        #: when every model's tasks share one scheduler job.  Updated
        #: at launch and on warm-pool adoption.
        self._node_keys: Dict[str, str] = {}
        #: gang id -> {key, job, size, task_ids, leader_node,
        #: weights_version}.  The gang book: popped on the FIRST member
        #: death (or a deliberate kill) so sibling deaths and racing
        #: reforms dedup to exactly one action per gang.
        self._gangs: Dict[str, dict] = {}
        self._gang_lock = threading.Lock()
        self._started = False

    # -- bring-up ----------------------------------------------------------

    def _replica_cmd(self, role: str = UNIFIED,
                     weights_version: Optional[str] = None,
                     model: Optional[ModelSpec] = None,
                     pool: bool = False) -> str:
        version = self.weights_version if weights_version is None \
            else weights_version
        parts = [sys.executable, "-m", "tfmesos_tpu.fleet.replica",
                 "--registry", self.registry.addr,
                 "--rows", str(self.rows),
                 "--seed", str(self.seed),
                 "--heartbeat-interval", str(self.heartbeat_interval)]
        if model is not None:
            # model_id is validated at catalog construction — the same
            # shell=True boundary as weights_version.
            parts += ["--model-id", model.model_id,
                      "--model-seed", str(model.seed)]
        if pool:
            parts += ["--warm-pool"]
        if role != UNIFIED:
            parts += ["--role", role]
        if version:
            parts += ["--weights-version", version]
        if self.tiny:
            parts.append("--tiny")
        if self.max_len is not None:
            parts += ["--max-len", str(self.max_len)]
        if self.page_size is not None:
            parts += ["--page-size", str(self.page_size)]
        if self.prefill_bucket is not None:
            parts += ["--prefill-bucket", str(self.prefill_bucket)]
        if self.multi_step != 1:
            parts += ["--multi-step", str(self.multi_step)]
        if self.prefix_cache_pages:
            parts += ["--prefix-cache-pages", str(self.prefix_cache_pages)]
        if self.pipeline_depth:
            parts += ["--pipeline-depth", str(self.pipeline_depth)]
        if self.fused_prefill:
            parts.append("--fused-prefill")
        if self.tokens_per_tick is not None:
            parts += ["--tokens-per-tick", str(self.tokens_per_tick)]
        if self.draft:
            parts += ["--draft", "--n-draft", str(self.n_draft)]
        if self.kv_tier_mb > 0:
            parts += ["--kv-tier-mb", str(self.kv_tier_mb)]
            tier_dir = self.kv_tier_dir or self._kv_tier_tmp
            if tier_dir:
                parts += ["--kv-tier-dir", tier_dir]
        elif self.kv_tier_dir:
            parts += ["--kv-tier-dir", self.kv_tier_dir]
        if self.kv_replication > 1:
            parts += ["--kv-replication", str(self.kv_replication)]
        if self.kv_placement != "rendezvous":
            # Validated against the closed set at construction (the
            # same shell=True boundary as the ints above).
            parts += ["--kv-placement", self.kv_placement]
        if self.warmup:
            # Every launch of this cmd — boot, an autoscale-up, OR a
            # later elastic/Mode-B relaunch — registers warming,
            # compiles, then takes traffic: re-warming is a property of
            # the command line, not of the first bring-up.
            parts.append("--warmup")
        return " ".join(parts)

    def _gateway_cmd(self, port: int, reuseport: bool,
                     http_port: Optional[int]) -> List[str]:
        """One gateway process's argv (exec'd directly, never through a
        shell): the wire listener address plus the same admission/
        routing constants every in-process gateway gets.  The cluster
        token rides the environment (``TPUMESOS_TOKEN``), never the
        command line."""
        parts = [sys.executable, "-m", "tfmesos_tpu.fleet.gateway",
                 "--registry", self.registry.addr,
                 "--host", self.gateway_host,
                 "--port", str(int(port)),
                 "--workers", str(self.workers),
                 "--max-queue", str(self.max_queue),
                 "--max-retries", str(self.max_retries),
                 "--request-timeout", str(self.request_timeout)]
        if reuseport:
            parts.append("--reuseport")
        if self.rate is not None:
            parts += ["--rate", str(self.rate)]
        if self.burst is not None:
            parts += ["--burst", str(self.burst)]
        if http_port is not None:
            parts += ["--http-port", str(int(http_port)),
                      "--http-host", self.gateway_host]
        return parts

    def _start_gateway_procs(self) -> None:
        """Launch ``gateway_processes`` front-door OS processes.  They
        share ONE public port via SO_REUSEPORT where the platform has
        it (the kernel load-balances accepts); elsewhere each takes an
        OS-assigned port and clients discover the set through the
        ``gateways`` op.  Either way every process leases a discovery
        entry in the central registry, which is also how this method
        knows bring-up finished."""
        n = self.gateway_processes
        reuseport = wire.reuseport_available()
        shared_port = 0
        if reuseport:
            shared_port = self.gateway_port
            if not shared_port:
                # Pick the shared port up front: bind-with-REUSEPORT,
                # read, close.  The tiny close-to-spawn window is the
                # standard ephemeral-port race; a loser fails loudly
                # at bind and the bring-up wait reports it.
                probe = wire.bind_ephemeral(self.gateway_host, 0,
                                            reuseport=True)
                shared_port = probe.getsockname()[1]
                probe.close()
        env = dict(os.environ)
        env["TPUMESOS_TOKEN"] = self.token
        env.pop("TPUMESOS_TOKEN_FILE", None)
        sink = subprocess.DEVNULL if self.quiet else None
        for i in range(n):
            if reuseport:
                port = shared_port
            else:
                port = self.gateway_port if i == 0 else 0
            cmd = self._gateway_cmd(
                port, reuseport,
                self.http_port if i == 0 else None)
            self._gateway_procs.append(subprocess.Popen(
                cmd, env=env, stdout=sink, stderr=sink))
        # Every process holds its OWN lease (keyed by its private
        # scrape addr), so N leases = N processes up even when
        # SO_REUSEPORT collapses the public discovery set to one addr.
        deadline = time.monotonic() + min(self.start_timeout, 30.0)
        while time.monotonic() < deadline:
            dead = [p for p in self._gateway_procs
                    if p.poll() is not None]
            if dead:
                raise ClusterError(
                    f"{len(dead)} gateway process(es) died during "
                    f"bring-up (first exit code "
                    f"{dead[0].returncode})")
            if len(self.registry.gateway_leases()) >= n:
                break
            time.sleep(0.05)
        else:
            raise ClusterError(
                f"only {len(self.registry.gateway_leases())} of {n} "
                f"gateway lease(s) registered within the bring-up "
                f"window")
        if self.http_port:
            self.http_addr = f"{self.gateway_host}:{self.http_port}"
        # Fleet-level scrape: the launcher's own /metrics (and
        # fleet_snapshot()) fold every gateway process's raw state in
        # at scrape time.
        self.metrics.fanin = self._scrape_gateway_raws
        self.log.info(
            "%d gateway process(es) up%s", n,
            f" sharing :{shared_port} via SO_REUSEPORT" if reuseport
            else " on per-process ports (no SO_REUSEPORT; clients "
                 "discover via the gateways op)")

    def _wait_gateway_mirrors(self, timeout: float = 15.0) -> None:
        """Block until every gateway process's sidecar mirror can route
        to as many alive replicas as the central registry lists RIGHT
        NOW — without this, a client's first request races the mirror's
        poll cadence and sheds with "no alive replicas" on a fleet
        that is, in fact, up."""
        want = len(self.registry.alive())
        if not want:
            return
        pending = set(self.registry.gateway_leases())
        deadline = time.monotonic() + timeout
        while pending and time.monotonic() < deadline:
            for addr in sorted(pending):
                try:
                    sock = wire.connect(addr, timeout=2.0)
                    try:
                        sock.settimeout(2.0)
                        wire.send_msg(sock, {"op": "status"}, self.token)
                        reply = wire.recv_msg(sock, self.token)
                    finally:
                        sock.close()
                except (OSError, wire.WireError):
                    continue
                alive = reply.get("alive") if isinstance(reply, dict) \
                    else None
                if isinstance(alive, int) and alive >= want:
                    pending.discard(addr)
            if pending:
                time.sleep(0.05)
        if pending:
            raise ClusterError(
                f"{len(pending)} gateway process(es) never mirrored "
                f"the {want} alive replica(s) within {timeout:.0f}s")

    def _scrape_gateway_raws(self) -> List[dict]:
        """Every gateway process's mergeable metrics state (``metrics``
        op with ``raw: true`` against each process's PRIVATE scrape
        listener — the shared REUSEPORT public addr would land on a
        kernel-chosen process); an unreachable process costs its
        contribution, never the scrape."""
        raws: List[dict] = []
        registry = self.registry
        if registry is None:
            return raws
        for addr in registry.gateway_leases():
            try:
                sock = wire.connect(addr, timeout=2.0)
                try:
                    sock.settimeout(2.0)
                    wire.send_msg(sock, {"op": "metrics", "raw": True},
                                  self.token)
                    reply = wire.recv_msg(sock, self.token)
                finally:
                    sock.close()
            except (OSError, wire.WireError):
                continue
            raw = reply.get("raw") if isinstance(reply, dict) else None
            if isinstance(raw, dict):
                raws.append(raw)
        return raws

    def fleet_snapshot(self) -> dict:
        """The FLEET-level metrics snapshot: in subprocess-gateway mode
        this merges every gateway process's counters/histograms into
        the launcher's own registry at scrape time; otherwise it is
        :meth:`snapshot` exactly."""
        if self.metrics is None:
            return {}
        if self.metrics.fanin is None:
            return self.metrics.snapshot()
        return self.metrics.merged().snapshot()

    def start(self) -> "FleetServer":
        self.token = self._token or wire.new_token()
        self.metrics = FleetMetrics()
        if self.kv_tier_mb > 0 and self.kv_tier_dir is None \
                and self._kv_tier_tmp is None:
            import tempfile

            # One HOST-shared disk tier for every co-located replica:
            # parked sessions resume on any same-version sibling, the
            # cross-replica half of the session contract.  mkdtemp is
            # mode 0700 and the entries are HMAC-framed with the
            # cluster token, so a foreign write reads as corruption.
            self._kv_tier_tmp = tempfile.mkdtemp(prefix="tfserve-kvtier-")
        try:
            # Liveness thresholds scale with the heartbeat cadence: a
            # slower (perfectly legal) interval must not make healthy
            # replicas flap alive -> draining between beats.
            hb = self.heartbeat_interval
            self.registry = ReplicaRegistry(
                token=self.token, metrics=self.metrics,
                suspect_after=max(1.5, 5.0 * hb),
                dead_after=max(3.0, 10.0 * hb),
                evict_after=max(10.0, 20.0 * hb)).start()
            self.router = Router(self.registry, self.metrics,
                                 token=self.token,
                                 max_retries=self.max_retries,
                                 request_timeout=self.request_timeout,
                                 breakers=self.breakers)
            self.admission = AdmissionController(
                max_queue=self.max_queue, rate=self.rate,
                burst=self.burst, classes=self.priority_classes)
            self.tracebook = TraceBook(sample=self.trace_sample,
                                       slow_ms=self.trace_slow_ms)
            # N stateless gateways over the ONE registry/router/
            # admission/tracebook view: any gateway serves any client,
            # so the set is purely a connection-capacity and failure-
            # isolation multiplier.  The shared router's lifecycle is
            # the launcher's (close_router=False) — a stopping gateway
            # must not tear down its siblings' replica links.
            if self.gateway_processes:
                # Multi-PROCESS front door: N OS processes, each with
                # its own WireServer loop, admission WFQ, and router
                # over a registry-sidecar view — the in-process Gateway
                # objects (and their shared-object wiring: rollout_fn,
                # catalog, swap_adapter) do not exist in this mode.
                self._start_gateway_procs()
            else:
                self.gateways = []
                for i in range(self.n_gateways):
                    gw = Gateway(self.router, self.admission, self.metrics,
                                 token=self.token, host=self.gateway_host,
                                 port=self.gateway_port if i == 0 else 0,
                                 workers=self.workers,
                                 registry=self.registry,
                                 tracebook=self.tracebook,
                                 close_router=False,
                                 http_port=self.http_port
                                 if i == 0 else None).start()
                    self.gateways.append(gw)
                self.gateway = self.gateways[0]
                self.http_addr = self.gateway.http_addr
            if self.metrics_port is not None:
                self._metrics_http = self.metrics.start_http_server(
                    self.metrics_port)
                self.log.info(
                    "prometheus exposition on :%d/metrics",
                    self._metrics_http.server_address[1])
            # The scheduler starts EMPTY in dynamic mode: the task table
            # is a runtime property, and every replica — boot ones
            # included — goes through the same launch_replica path the
            # autoscaler and rollouts use.
            self.scheduler = TPUMesosScheduler(
                [], dynamic=True, backend=self.backend, master=self.master,
                quiet=self.quiet, start_timeout=self.start_timeout,
                token=self.token)
            # A gang member's death is the GANG's death: the scheduler
            # reports it (off its status thread) and the fleet tears
            # down the siblings and re-forms the gang whole.
            self.scheduler.on_dynamic_death = self._on_dynamic_death
            self.scheduler.start()
            if self.catalog is not None:
                # Per-(model, tier) targets + the warm pool, all under
                # one budget.  Entries booting 0 replicas start scaled
                # to zero and cold-start through the pool on demand.
                for spec in self.catalog:
                    key = model_key(spec.model_id)
                    self.set_target(key, spec.replicas)
                    for _ in range(spec.replicas):
                        self.launch_replica(key)
                if self.warm_pool:
                    self.set_target(POOL_KEY, self.warm_pool)
                    for _ in range(self.warm_pool):
                        self.launch_replica(POOL_KEY)
            else:
                for role, n in ((UNIFIED, self.replicas),
                                (PREFILL, self.prefill_replicas),
                                (DECODE, self.decode_replicas)):
                    if n:
                        self.set_target(role, n)
                        for _ in range(n):
                            self.launch_replica(role)
            if self.kv_replicas:
                # Dedicated KV holders ride the same launch/convergence
                # path as serving tiers (a crashed holder relaunches),
                # but capacity-0: the router never routes tokens at one.
                self.set_target(KV, self.kv_replicas)
                for _ in range(self.kv_replicas):
                    self.launch_replica(KV)
            self._wait_replicas()
            if self.gateway_processes:
                self._wait_gateway_mirrors()
            for gw in self.gateways:
                gw.rollout_fn = self.rollout
                gw.catalog = self.catalog
                if self.catalog is not None:
                    gw.swap_adapter_fn = self._swap_adapter_packed
            if self.catalog is not None:
                # The trader IS the catalog fleet's control loop: it
                # reallocates the budget between models, scales idle
                # ones to zero, and answers the router's cold-start
                # demands from the warm pool.
                self.trader = ModelTrader(
                    self, self.catalog, self.autoscale_config,
                    trader_config=self.trader_config).start()
                self.autoscaler = self.trader
                self.router.on_model_demand = self.trader.demand
            elif self.autoscale:
                self.autoscaler = FleetAutoscaler(
                    self, self.autoscale_config).start()
        except Exception:
            self.stop()
            raise
        self._started = True
        if self.report_interval:
            self.metrics.start_reporter(self.log, self.report_interval)
        self.log.info("fleet up: gateway%s %s, %d replica(s) "
                      "(%d unified / %d prefill / %d decode)%s",
                      "s" if self.n_gateways > 1 else "",
                      ", ".join(self.addrs),
                      self.total_replicas, self.replicas,
                      self.prefill_replicas, self.decode_replicas,
                      f", autoscaling within [{self.min_replicas}, "
                      f"{self.max_replicas}]" if self.autoscale else "")
        return self

    @property
    def total_replicas(self) -> int:
        return self.replicas + self.prefill_replicas + self.decode_replicas

    # -- dynamic tier management -------------------------------------------

    def set_target(self, role: str, n: int) -> None:
        """Record one tier's wanted replica count (mirrored into the
        registry so the ``roles`` gauge shows target vs actual)."""
        self.targets[role] = int(n)
        self.registry.set_target(role, int(n))

    def bounds(self, key: str) -> Tuple[int, int]:
        """The autoscale bounds this tier's target must stay within
        (the floor is fleet-wide, the ceiling per tier).  Composite
        per-(model, tier) keys range [0, budget] — their floors and
        scale-to-zero policy live in the catalog entries the trader
        enforces."""
        model, _ = split_key(key)
        if model is not None:
            return 0, self.replica_budget or self.max_replicas
        return self.min_replicas, self._tier_max.get(key,
                                                     self.max_replicas)

    def gang_size_for(self, key: str) -> int:
        """How many member tasks one replica of ``key`` launches as:
        the catalog entry's ``gang_size`` for model keys, the fleet's
        for the unified tier, and always 1 for role-split tiers and
        the warm pool (a pool replica has no model to shard yet)."""
        model, role = split_key(key)
        if model == POOL:
            return 1
        if model is not None:
            return int(getattr(self.catalog.get(model),
                               "gang_size", 1) or 1)
        return self.gang_size if role == UNIFIED else 1

    def launch_replica(self, key: str,
                       weights_version: Optional[str] = None) -> str:
        """Launch ONE new Mode-B replica for ``key`` — a plain
        role, a composite ``"<model>/<role>"``, or the warm pool's
        :data:`POOL_KEY` — and return its node id ("job:index"); with
        ``--warmup`` on the cmd line it registers ``warming`` and
        never takes traffic cold.  With a gang size > 1 the "replica"
        is a whole gang (N tasks, one routable leader) and the node id
        is the LEADER's."""
        size = self.gang_size_for(key)
        if size > 1:
            return self.launch_gang(key, weights_version, size)
        model, role = split_key(key)
        spec = None
        pool = model == POOL
        if model is not None and not pool:
            spec = self.catalog.get(model)
        job = TIER_JOBS[role]
        task = self.scheduler.add_task(
            job, cmd=self._replica_cmd(role, weights_version,
                                       model=spec, pool=pool),
            cpus=self.replica_cpus, mem=self.replica_mem,
            chips=self.replica_chips)
        node = f"{job}:{task.task_index}"
        self._node_keys[node] = key
        return node

    def launch_gang(self, key: str,
                    weights_version: Optional[str] = None,
                    size: Optional[int] = None) -> str:
        """Launch one GANG replica for ``key``: N identical member
        cmds enter the scheduler as an atomic all-or-nothing gang
        (the gang env contract — id/size/rank — is stamped by
        ``add_gang``), rank 0 leads and registers as the one routable
        node this method returns."""
        size = self.gang_size_for(key) if size is None else int(size)
        model, role = split_key(key)
        spec = None
        if model is not None and model != POOL:
            spec = self.catalog.get(model)
        job = TIER_JOBS[role]
        cmd = self._replica_cmd(role, weights_version, model=spec)
        members = self.scheduler.add_gang(
            job, [cmd] * size, cpus=self.replica_cpus,
            mem=self.replica_mem, chips=self.replica_chips)
        gang_id = members[0].gang
        node = f"{job}:{members[0].task_index}"
        with self._gang_lock:
            self._gangs[gang_id] = {
                "key": key, "job": job, "size": size,
                "task_ids": [t.id for t in members],
                "leader_node": node,
                "weights_version": weights_version}
        self._node_keys[node] = key
        return node

    def kill_replica(self, node: str) -> bool:
        """Kill one replica by its node id ("job:index").  A gang
        leader's node kills the WHOLE gang — members without a leader
        are not a smaller replica, they are debris."""
        # The node->key book entry dies with the task either way — a
        # churning trader (trade = kill + relaunch per cooldown) must
        # not grow the book, and tier_actual scans it per tick.
        self._node_keys.pop(node, None)
        with self._gang_lock:
            gang_id = next(
                (g for g, info in self._gangs.items()
                 if info["leader_node"] == node), None)
            info = self._gangs.pop(gang_id, None) if gang_id else None
        if info is not None:
            # remove_task pulls each member from the table BEFORE the
            # kill, so the sibling deaths report under unknown ids and
            # never re-enter the gang-death path.
            killed = False
            for tid in info["task_ids"]:
                killed = self.scheduler.remove_task(tid) or killed
            return killed
        job, _, idx = node.rpartition(":")
        try:
            task = self.scheduler.task_by_index(job, int(idx))
        except ValueError:
            return False
        if task is None:
            return False
        return self.scheduler.remove_task(task.id)

    def _on_dynamic_death(self, task) -> None:
        """Scheduler death hook (on its own thread, never the status
        thread): a gang member died, so tear the gang down whole and
        re-form it under a FRESH generation and a fresh gang id — the
        double fence that makes a zombie member of the dead gang
        unroutable forever (its gang_lookup never resolves, and the
        new leader rejects joins of any other (gang, generation))."""
        gang_id = getattr(task, "gang", None)
        if gang_id is None:
            return
        with self._gang_lock:
            info = self._gangs.pop(gang_id, None)
        if info is None:
            return      # sibling already took the gang down
        self._node_keys.pop(info["leader_node"], None)
        for tid in info["task_ids"]:
            if tid == task.id:
                continue
            try:
                self.scheduler.remove_task(tid)
            except Exception as e:
                self.log.warning("gang %s sibling %s teardown failed: "
                                 "%s", gang_id, tid, e)
        if not self._started or self.scheduler is None:
            return
        try:
            self.scheduler.bump_generation()
            node = self.launch_gang(info["key"],
                                    info.get("weights_version"),
                                    info["size"])
            if self.metrics is not None:
                self.metrics.inc("gang_reforms")
            self.log.warning(
                "gang %s lost a member; torn down and re-forming as "
                "%s (leader %s)", gang_id, info["key"], node)
        except Exception:
            self.log.exception("gang %s re-form failed; the "
                               "convergence loop will retry", gang_id)

    def tier_actual(self, key: str) -> int:
        """Live tasks launched for one tier (registered or not) — the
        convergence loops' notion of "actual".  A gang counts as ONE
        unit (its N member tasks are one replica).  Composite keys
        count through the node->key map intersected with the
        scheduler's live task table (all models share one job)."""
        model, role = split_key(key)
        job = TIER_JOBS[role]
        if model is None:
            loose, gangs = 0, set()
            for t in self.scheduler.tasks_of(job):
                gang_id = getattr(t, "gang", None)
                if gang_id is None:
                    loose += 1
                else:
                    gangs.add(gang_id)
            return loose + len(gangs)
        # Only gang LEADERS enter the node->key book, so the
        # intersection already counts a gang once.
        live = {f"{job}:{t.task_index}"
                for t in self.scheduler.tasks_of(job)}
        return sum(1 for node, k in self._node_keys.items()
                   if k == key and node in live)

    def tier_members(self, key: str):
        """Registry members of one target key (the trader's
        membership query): role-filtered by the registry, model/pool-
        filtered here."""
        model, role = split_key(key)
        return filter_members(self.registry.members(role), key)

    def adopt_replica(self, addr: str, model_id: str,
                      timeout: float = 60.0) -> bool:
        """Assign a warm-pool replica a catalog model via the
        ``adopt`` control op (a weight install on a pre-warmed
        process — the cold-start path that skips launch + compile).
        Updates the node->key book immediately so the trader's actuals
        follow without waiting a heartbeat."""
        spec = self.catalog.get(model_id)
        try:
            reply = self.router.control(
                addr, {"op": "adopt", "model_id": spec.model_id,
                       "seed": spec.seed}, timeout=timeout)
        except Exception as e:
            self.log.warning("adoption of %s for model %s failed: %s",
                             addr, model_id, e)
            return False
        if not isinstance(reply, dict) or reply.get("op") != "adopted":
            self.log.warning("adoption of %s for model %s rejected: %r",
                             addr, model_id, reply)
            return False
        node = next((r.node for r in self.registry.members()
                     if r.addr == addr and r.node), None)
        if node is not None:
            self._node_keys[node] = model_key(model_id)
        return True

    def swap_adapter(self, model_id: str, adapter_version: str,
                     delta=None, packed: Optional[Tuple[dict, bytes]]
                     = None, timeout: float = 120.0) -> dict:
        """Hot-swap a LoRA-style weight delta onto EVERY alive replica
        of one model: the delta ships as ONE raw HMAC frame per
        replica (``swap_adapter`` op), each batcher folds it behind
        its weight-update fence (in-flight requests finish on the old
        delta; zero downtime), and the call returns once every replica
        acked.  ``delta`` is a param-path -> array dict (packed here);
        ``packed`` supplies pre-encoded ``(meta, body)`` instead (the
        gateway op's path — no numpy on the gateway).  Raises on an
        unknown model, a replica rejection, or a partial failure —
        a fleet serving two delta versions of one model would break
        the token-identical-streams contract, so partial application
        is an ERROR, not a success."""
        if self.catalog is None:
            raise RuntimeError("swap_adapter needs a model catalog")
        spec = self.catalog.get(model_id)     # KeyError on unknown
        adapter_version = validate_model_id(adapter_version)
        if packed is None:
            if delta is None:
                raise ValueError("swap_adapter needs delta or packed")
            packed = pack_adapter(delta)
        meta, body = packed
        members = self.registry.members(model=spec.model_id)
        if any(r.state == WARMING for r in members):
            # A warming replica would turn ALIVE on BASE weights right
            # after the swap acked — one model serving two weight
            # states, the exact partial-application state documented
            # as an error.  Fail up front; the operator retries once
            # the tier settles.
            raise RuntimeError(
                f"model {model_id!r} has replica(s) still warming; "
                f"they would come up on the old weights — retry the "
                f"swap once the tier is fully routable")
        targets = [r for r in members if r.state == ALIVE]
        if not targets:
            raise RuntimeError(
                f"no alive replica serves model {model_id!r} (scaled "
                f"to zero? the swap applies at the next cold start "
                f"only if re-issued)")
        failures = []
        for r in targets:
            call = dict(meta)
            call.update(op="swap_adapter", model_id=spec.model_id,
                        adapter_version=adapter_version)
            try:
                reply = self.router.control_raw(r.addr, call, body,
                                                timeout=timeout)
            except Exception as e:
                failures.append(f"{r.addr}: {e}")
                continue
            if not isinstance(reply, dict) \
                    or reply.get("op") != "adapter_swapped":
                err = reply.get("error") if isinstance(reply, dict) \
                    else repr(reply)
                failures.append(f"{r.addr}: {err}")
        if failures:
            raise RuntimeError(
                f"adapter swap {adapter_version!r} on model "
                f"{model_id!r} failed on {len(failures)}/"
                f"{len(targets)} replica(s): {'; '.join(failures)}")
        self.metrics.inc("adapter_swaps")
        self.log.info("adapter %s swapped onto %d replica(s) of model "
                      "%s", adapter_version, len(targets), model_id)
        return {"model_id": spec.model_id,
                "adapter_version": adapter_version,
                "replicas": len(targets)}

    def _alive_of(self, key: str,
                  weights_version: Optional[str] = None) -> int:
        model, role = split_key(key)
        members = filter_members(self.registry.members(role), key)
        return sum(1 for r in members
                   if r.state == ALIVE
                   and (weights_version is None
                        or r.weights_version == weights_version))

    def _swap_adapter_packed(self, model_id: str, adapter_version: str,
                             meta: dict, body: bytes) -> dict:
        """The gateway op's entry point: the delta arrived base64 over
        the public port (which rejects raw frames pre-auth) and ships
        onward to the replicas as raw HMAC frames."""
        return self.swap_adapter(model_id, adapter_version,
                                 packed=(meta, body))

    def request_migration(self, addr: str) -> bool:
        """Ask one (already drained) replica to SUSPEND its in-flight
        rows — the victim answers each pending generate with a
        ``suspended`` export the router re-places on a survivor, so the
        drain flushes in one round-trip instead of a full generation's
        tail latency, and a kill-after-timeout can no longer lose work.
        Best-effort: any failure just leaves the plain drain-then-kill
        behavior (the victim keeps finishing its rows)."""
        if not self.migrate_on_drain or self.router is None:
            return False
        msg: dict = {"op": "migrate"}
        try:
            # Broker a direct-stream target up front: the victim pushes
            # each suspended artifact straight at the survivor (one
            # bounded attempt) and the router adopts by reference —
            # artifact bytes cross the wire once instead of twice.  No
            # eligible survivor (or an old victim binary) just leaves
            # the relay path: the suspended RawFrames flow through the
            # router exactly as before.
            target = self.router.migration_target(addr)
            if target:
                msg["push_to"] = target
        except Exception:
            pass
        try:
            self.router.control(addr, msg, timeout=30.0)
        except Exception as e:
            self.log.warning("migrate request to %s failed (%s); its "
                             "in-flight work drains normally", addr, e)
            return False
        self.metrics.inc("migrations_requested")
        return True

    def _drain_and_flush(self, reps, drain_timeout: float) -> None:
        """ONE copy of the reap discipline both rollout paths share:
        pinned drains on every given replica (healthy members keep
        heartbeating while their in-flight work finishes), ask each to
        migrate its in-flight rows away (drain-migrate-kill; see
        :meth:`request_migration`), then wait until BOTH flush signals
        read zero for all of them — the heartbeat-reported outstanding
        AND the router's own in-flight count (a request dispatched
        after the last beat is invisible to the first) — or the drain
        deadline passes."""
        addrs = [r.addr for r in reps]
        for r in reps:
            self.registry.begin_drain(r.addr, pinned=True)
        for r in reps:
            self.request_migration(r.addr)
        deadline = time.monotonic() + float(drain_timeout)
        while addrs and time.monotonic() < deadline:
            table = {m.addr: m for m in self.registry.members()}
            busy = any(
                (table.get(a) is not None and table[a].state != DEAD
                 and table[a].outstanding > 0)
                or self.router.outstanding(a) > 0
                for a in addrs)
            if not busy:
                return
            time.sleep(0.05)

    def _wait_replicas(self) -> None:
        """Target-based bring-up: every tier must reach its target alive
        count.  Boot crashes are relaunched (the convergence discipline)
        up to the scheduler's per-task failure budget scaled by the
        tier size — a crash-looping replica cmd still fails the
        bring-up loudly instead of idling to timeout."""
        deadline = time.monotonic() + self.start_timeout
        while time.monotonic() < deadline:
            # finished() raises ClusterError on backend-fatal errors —
            # surface those instead of idling to timeout.
            self.scheduler.finished()
            if all(self._alive_of(role) >= n
                   for role, n in self.targets.items()):
                return
            for key, n in self.targets.items():
                job = TIER_JOBS[split_key(key)[1]]
                fails = self.scheduler.dynamic_failures.get(job, 0)
                if fails >= MAX_FAILURE_COUNT * max(1, n):
                    raise ClusterError(
                        f"replica job {job!r} failed {fails} times "
                        f"during fleet bring-up")
                for _ in range(n - self.tier_actual(key)):
                    self.log.warning("bring-up relaunch of a crashed "
                                     "%s replica", key)
                    self.launch_replica(key)
            time.sleep(0.1)
        warming = len(self.registry.warming())
        counts = {role: self._alive_of(role) for role in self.targets}
        raise ClusterError(
            f"replicas routable after {self.start_timeout:.0f}s: "
            f"{counts} of targets {self.targets}"
            + (f" ({warming} still warming — raise start_timeout for "
               f"slow compiles)" if warming else ""))

    # -- blue-green rollout ------------------------------------------------

    def rollout(self, weights_version: str, bake_s: float = 1.0,
                warm_timeout: Optional[float] = None,
                drain_timeout: float = 120.0) -> dict:
        """Replace every tier's weights blue-green with zero downtime:

        1. bump the scheduler generation (PR 3's fencing epoch) and
           launch a full NEW-version replica set next to the old one —
           same per-tier targets, same cmd line (``--warmup`` included,
           so the new tier warms before it can be routed);
        2. wait until every tier's new-version alive count reaches its
           target — if that never happens the rollout ABORTS: the new
           tasks are reaped and the old version keeps serving;
        3. the SHIFT: one atomic router update prefers the new
           weights_version (the old tier stays registered as fallback
           through the bake window, so the shift itself cannot shed);
        4. after ``bake_s``, drain the old tier (pinned drains — the
           healthy old replicas keep heartbeating while their in-flight
           work flushes, and those beats must not revive them), wait
           for the flush, kill the old tasks, and raise the registry's
           generation fence so a stalled old-generation straggler can
           never re-register and serve stale weights.

        Returns a summary dict; raises :class:`RolloutError` on abort.
        """
        version = validate_weights_version(weights_version)
        if self.scheduler is None or self.registry is None:
            raise RuntimeError("fleet not started")
        with self.scale_lock:
            old_version = self.weights_version
            if version == old_version:
                raise ValueError(
                    f"fleet already serves weights_version {version!r}")
            gen = self.scheduler.bump_generation()
            warm_timeout = self.start_timeout if warm_timeout is None \
                else float(warm_timeout)
            new_nodes: List[Tuple[str, str]] = []
            for role, target in self.targets.items():
                for _ in range(target):
                    new_nodes.append(
                        (role, self.launch_replica(role, version)))
            self.log.info(
                "rollout %s -> %s: %d new-version replica(s) launched "
                "(generation %d); old tier keeps serving", old_version,
                version, len(new_nodes), gen)
            deadline = time.monotonic() + warm_timeout
            while time.monotonic() < deadline:
                self.scheduler.finished()
                if all(self._alive_of(role, version) >= target
                       for role, target in self.targets.items()):
                    break
                time.sleep(0.1)
            else:
                # Abort: the new tier never left warming (or its tasks
                # kept dying).  Reap it; the old version never stopped
                # serving, so this is a no-downtime failure.  Routing
                # is version-blind BEFORE the shift, so any new-version
                # replica that did reach ALIVE may already carry
                # traffic — drain those and wait for the flush before
                # the kill, exactly like the post-shift reap path.
                new_set = {node for _, node in new_nodes}
                self._drain_and_flush(
                    [r for r in self.registry.members()
                     if r.node in new_set and r.state == ALIVE],
                    drain_timeout)
                for _, node in new_nodes:
                    self.kill_replica(node)
                self.metrics.inc("rollouts_aborted")
                raise RolloutError(
                    f"rollout to {version!r} aborted: new tier not "
                    f"routable within {warm_timeout:.0f}s "
                    f"({len(self.registry.warming())} still warming, "
                    f"{len(new_set)} launched); {old_version!r} keeps "
                    f"serving")
            # The shift point: one atomic preference update.  From the
            # next pick on, the router selects old-version replicas only
            # if NO new-version replica is routable.
            self.router.set_preferred_version(version)
            self.weights_version = version
            self.metrics.inc("rollouts")
            self.log.info("rollout shift: router now prefers "
                          "weights_version %s (old %s is fallback for "
                          "%.1fs bake)", version, old_version, bake_s)
            if bake_s:
                time.sleep(bake_s)
            # Drain the old tier: pinned — these replicas are healthy
            # and keep heartbeating while their last requests flush.
            # The drain set is computed NOW, not at rollout start: a
            # replica that registered during the warm wait (an
            # autoscaler launch racing the scale lock) is old-version
            # fallback traffic too and must flush before the reap.
            managed_roles = {split_key(k)[1] for k in self.targets}
            old_members = [r for r in self.registry.members()
                           if (r.role or UNIFIED) in managed_roles
                           and r.weights_version != version
                           and r.state != DEAD]
            self._drain_and_flush(old_members, drain_timeout)
            # Reap every old-generation task of the managed tiers (the
            # registry's node field maps members back; the scheduler
            # table diff catches launched-but-never-registered ones).
            new_set = {node for _, node in new_nodes}
            # Gang-aware reap: a NEW gang's members carry node ids that
            # never entered new_nodes (only the leader did) — keep any
            # task whose gang's leader is new; reap old gangs whole and
            # drop their book entries so no death hook re-forms them.
            with self._gang_lock:
                keep_gangs = {g for g, info in self._gangs.items()
                              if info["leader_node"] in new_set}
                for g in [g for g, info in self._gangs.items()
                          if g not in keep_gangs
                          and info["job"] in {TIER_JOBS[r]
                                              for r in managed_roles}]:
                    del self._gangs[g]
            reaped = 0
            for job in {TIER_JOBS[r] for r in managed_roles}:
                for t in self.scheduler.tasks_of(job):
                    if getattr(t, "gang", None) in keep_gangs:
                        continue
                    node = f"{job}:{t.task_index}"
                    if node not in new_set:
                        self.scheduler.remove_task(t.id)
                        reaped += 1
            # The fence: beats of generations before this rollout are
            # dropped from here on — a SIGSTOP'd straggler that wakes up
            # tomorrow cannot re-register and serve stale weights.
            self.registry.fence_generation(gen)
            self.log.info(
                "rollout to %s complete: %d old replica(s) drained and "
                "reaped, registry fenced at generation %d", version,
                reaped, gen)
            return {"old_version": old_version, "new_version": version,
                    "replicas": len(new_nodes), "reaped": reaped,
                    "generation": gen}

    # -- surface -----------------------------------------------------------

    @property
    def addr(self) -> Optional[str]:
        if self.gateway is not None:
            return self.gateway.addr
        addrs = self.addrs
        return addrs[0] if addrs else None

    @property
    def addrs(self) -> List[str]:
        """Every front door's address (multi-gateway deployments).  In
        subprocess mode this is the central registry's leased discovery
        set — with SO_REUSEPORT all N processes share one address, so
        one entry stands for the whole set."""
        if self.gateways:
            return [gw.addr for gw in self.gateways if gw.addr]
        if self._gateway_procs and self.registry is not None:
            return sorted(self.registry.gateway_addrs())
        return []

    def client(self, timeout: float = 120.0) -> FleetClient:
        """A client over EVERY gateway: it spreads nothing (one
        connection at a time) but fails over to a surviving gateway —
        replaying idempotent in-flight generates — when its own dies."""
        return FleetClient(self.addrs or [self.addr], self.token,
                           timeout=timeout)

    def snapshot(self) -> dict:
        """The fleet metrics snapshot; the ``roles`` gauge carries each
        tier's target vs actual counts and weights_version distribution,
        and ``autoscaler`` (when scaling) the control loop's beliefs."""
        return self.metrics.snapshot() if self.metrics is not None else {}

    # -- teardown ----------------------------------------------------------

    def stop(self) -> None:
        self._started = False
        if self.autoscaler is not None:
            self.autoscaler.stop()
            self.autoscaler = None
        if self.metrics is not None:
            self.metrics.stop_reporter()
        if self._metrics_http is not None:
            self._metrics_http.shutdown()
            self._metrics_http.server_close()
            self._metrics_http = None
        for gw in self.gateways:
            if not gw.killed:
                gw.stop()
        self.gateways = []
        self.gateway = None
        self.http_addr = None
        if self.metrics is not None:
            self.metrics.fanin = None
        for proc in self._gateway_procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in self._gateway_procs:
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
        self._gateway_procs = []
        # The gateways share the router (close_router=False); its
        # links close exactly once, here.
        if self.router is not None:
            self.router.close()
            self.router = None
        if self.scheduler is not None:
            # Teardown kills are deliberate: no gang death hook may
            # re-form what stop() is reaping.
            self.scheduler.on_dynamic_death = None
            self.scheduler.stop()
            self.scheduler = None
        with self._gang_lock:
            self._gangs.clear()
        if self.registry is not None:
            self.registry.stop()
            self.registry = None
        if self._kv_tier_tmp is not None:
            import shutil

            shutil.rmtree(self._kv_tier_tmp, ignore_errors=True)
            self._kv_tier_tmp = None

    def __enter__(self) -> "FleetServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
