"""Fleet bring-up: registry + gateway + N scheduled batcher replicas.

``FleetServer`` is the one-object front: it generates a cluster token,
starts the registry and gateway locally, then launches the replicas as
**Mode-B tasks through the backend abstraction** — ``LocalBackend``
(the default with no master) runs whole fleets as CPU subprocesses for
development and CI; a Mesos master runs them on TPU agents with
per-replica chip/mem reservations.  The scheduler, registry, and
gateway share ONE token, delivered to replicas over the scheduler's
existing transport (mode-0600 token file for co-located backends), so
every hop of the serving path is authenticated with the same secret.

Replica death is a SERVING event here, not a cluster event: the
scheduler's fail-fast policy is for training meshes (which cannot
hot-swap members); the fleet instead routes around dead replicas and
keeps serving on the survivors.  Replica auto-restart rides the same
Job machinery a future PR can point at ``task_spec``.
"""

from __future__ import annotations

import sys
from typing import Optional

from tfmesos_tpu import wire
from tfmesos_tpu.fleet.admission import AdmissionController
from tfmesos_tpu.fleet.client import FleetClient
from tfmesos_tpu.fleet.gateway import Gateway
from tfmesos_tpu.fleet.metrics import FleetMetrics
from tfmesos_tpu.fleet.registry import ReplicaRegistry
from tfmesos_tpu.fleet.router import Router
from tfmesos_tpu.scheduler import ClusterError, TPUMesosScheduler
from tfmesos_tpu.spec import Job
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["FleetServer"]


class FleetServer:
    """Bring up (and tear down) a whole serving fleet."""

    def __init__(self, replicas: int = 2, rows: int = 4,
                 tiny: bool = False, seed: int = 0,
                 max_len: Optional[int] = None,
                 page_size: Optional[int] = None,
                 prefill_bucket: Optional[int] = None,
                 multi_step: int = 1,
                 prefix_cache_pages: int = 0,
                 pipeline_depth: int = 0,
                 warmup: bool = False,
                 prefill_replicas: int = 0,
                 decode_replicas: int = 0,
                 backend=None, master: Optional[str] = None,
                 replica_cpus: float = 1.0, replica_mem: float = 1024.0,
                 replica_chips: int = 0,
                 gateway_host: str = "127.0.0.1", gateway_port: int = 0,
                 workers: int = 8, max_queue: int = 64,
                 rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 max_retries: int = 2, request_timeout: float = 120.0,
                 start_timeout: float = 300.0,
                 heartbeat_interval: float = 0.3,
                 report_interval: Optional[float] = None,
                 quiet: bool = True, token: Optional[str] = None):
        if min(replicas, prefill_replicas, decode_replicas) < 0:
            raise ValueError("replica counts must be >= 0")
        if (prefill_replicas > 0) != (decode_replicas > 0):
            raise ValueError(
                "prefill_replicas and decode_replicas come together — "
                "a lone tier cannot serve the disaggregated handoff")
        if replicas + prefill_replicas + decode_replicas < 1:
            raise ValueError("the fleet needs at least one replica")
        self.replicas = int(replicas)
        self.prefill_replicas = int(prefill_replicas)
        self.decode_replicas = int(decode_replicas)
        self.rows = int(rows)
        self.tiny = bool(tiny)
        self.seed = int(seed)
        self.max_len = max_len
        self.page_size = page_size
        self.prefill_bucket = prefill_bucket
        self.multi_step = int(multi_step)
        self.prefix_cache_pages = int(prefix_cache_pages)
        self.pipeline_depth = int(pipeline_depth)
        self.warmup = bool(warmup)
        self.backend = backend
        self.master = master
        self.replica_cpus = float(replica_cpus)
        self.replica_mem = float(replica_mem)
        self.replica_chips = int(replica_chips)
        self.gateway_host = gateway_host
        self.gateway_port = int(gateway_port)
        self.workers = int(workers)
        self.max_queue = int(max_queue)
        self.rate = rate
        self.burst = burst
        self.max_retries = int(max_retries)
        self.request_timeout = float(request_timeout)
        self.start_timeout = float(start_timeout)
        self.heartbeat_interval = float(heartbeat_interval)
        self.report_interval = report_interval
        self.quiet = quiet
        self.log = get_logger("tfmesos_tpu.fleet", quiet=quiet)

        # An explicit token lets external clients authenticate (tfserve
        # resolves one from the standard TPUMESOS_TOKEN/_FILE contract);
        # by default each bring-up mints its own.
        self._token = token
        self.token: Optional[str] = None
        self.metrics: Optional[FleetMetrics] = None
        self.registry: Optional[ReplicaRegistry] = None
        self.router: Optional[Router] = None
        self.admission: Optional[AdmissionController] = None
        self.gateway: Optional[Gateway] = None
        self.scheduler: Optional[TPUMesosScheduler] = None
        self._started = False

    # -- bring-up ----------------------------------------------------------

    def _replica_cmd(self, role: str = "unified") -> str:
        parts = [sys.executable, "-m", "tfmesos_tpu.fleet.replica",
                 "--registry", self.registry.addr,
                 "--rows", str(self.rows),
                 "--seed", str(self.seed),
                 "--heartbeat-interval", str(self.heartbeat_interval)]
        if role != "unified":
            parts += ["--role", role]
        if self.tiny:
            parts.append("--tiny")
        if self.max_len is not None:
            parts += ["--max-len", str(self.max_len)]
        if self.page_size is not None:
            parts += ["--page-size", str(self.page_size)]
        if self.prefill_bucket is not None:
            parts += ["--prefill-bucket", str(self.prefill_bucket)]
        if self.multi_step != 1:
            parts += ["--multi-step", str(self.multi_step)]
        if self.prefix_cache_pages:
            parts += ["--prefix-cache-pages", str(self.prefix_cache_pages)]
        if self.pipeline_depth:
            parts += ["--pipeline-depth", str(self.pipeline_depth)]
        if self.warmup:
            # Every launch of this cmd — boot OR a later elastic/Mode-B
            # relaunch — registers warming, compiles, then takes
            # traffic: re-warming is a property of the command line,
            # not of the first bring-up.
            parts.append("--warmup")
        return " ".join(parts)

    def start(self) -> "FleetServer":
        self.token = self._token or wire.new_token()
        self.metrics = FleetMetrics()
        try:
            # Liveness thresholds scale with the heartbeat cadence: a
            # slower (perfectly legal) interval must not make healthy
            # replicas flap alive -> draining between beats.
            hb = self.heartbeat_interval
            self.registry = ReplicaRegistry(
                token=self.token, metrics=self.metrics,
                suspect_after=max(1.5, 5.0 * hb),
                dead_after=max(3.0, 10.0 * hb),
                evict_after=max(10.0, 20.0 * hb)).start()
            self.router = Router(self.registry, self.metrics,
                                 token=self.token,
                                 max_retries=self.max_retries,
                                 request_timeout=self.request_timeout)
            self.admission = AdmissionController(max_queue=self.max_queue,
                                                 rate=self.rate,
                                                 burst=self.burst)
            self.gateway = Gateway(self.router, self.admission,
                                   self.metrics, token=self.token,
                                   host=self.gateway_host,
                                   port=self.gateway_port,
                                   workers=self.workers).start()
            jobs = []
            if self.replicas:
                jobs.append(Job(name="replica", num=self.replicas,
                                cpus=self.replica_cpus,
                                mem=self.replica_mem,
                                chips=self.replica_chips,
                                cmd=self._replica_cmd()))
            if self.prefill_replicas:
                jobs.append(Job(name="prefill", num=self.prefill_replicas,
                                cpus=self.replica_cpus,
                                mem=self.replica_mem,
                                chips=self.replica_chips,
                                cmd=self._replica_cmd("prefill")))
            if self.decode_replicas:
                jobs.append(Job(name="decode", num=self.decode_replicas,
                                cpus=self.replica_cpus,
                                mem=self.replica_mem,
                                chips=self.replica_chips,
                                cmd=self._replica_cmd("decode")))
            self.scheduler = TPUMesosScheduler(
                jobs, backend=self.backend, master=self.master,
                quiet=self.quiet, start_timeout=self.start_timeout,
                token=self.token)
            self.scheduler.start()
            self._wait_replicas()
        except Exception:
            self.stop()
            raise
        self._started = True
        if self.report_interval:
            self.metrics.start_reporter(self.log, self.report_interval)
        self.log.info("fleet up: gateway %s, %d replica(s) "
                      "(%d unified / %d prefill / %d decode)", self.addr,
                      self.total_replicas, self.replicas,
                      self.prefill_replicas, self.decode_replicas)
        return self

    @property
    def total_replicas(self) -> int:
        return self.replicas + self.prefill_replicas + self.decode_replicas

    def _wait_replicas(self) -> None:
        import time

        want = self.total_replicas
        deadline = time.monotonic() + self.start_timeout
        while time.monotonic() < deadline:
            if len(self.registry.alive()) >= want:
                return
            # finished() raises ClusterError if a replica task already
            # died fatally — surface that instead of idling to timeout.
            self.scheduler.finished()
            time.sleep(0.1)
        warming = len(self.registry.warming())
        raise ClusterError(
            f"only {len(self.registry.alive())}/{want} replicas "
            f"routable after {self.start_timeout:.0f}s"
            + (f" ({warming} still warming — raise start_timeout for "
               f"slow compiles)" if warming else ""))

    # -- surface -----------------------------------------------------------

    @property
    def addr(self) -> Optional[str]:
        return self.gateway.addr if self.gateway is not None else None

    def client(self, timeout: float = 120.0) -> FleetClient:
        return FleetClient(self.addr, self.token, timeout=timeout)

    def snapshot(self) -> dict:
        return self.metrics.snapshot() if self.metrics is not None else {}

    # -- teardown ----------------------------------------------------------

    def stop(self) -> None:
        self._started = False
        if self.metrics is not None:
            self.metrics.stop_reporter()
        if self.gateway is not None:
            self.gateway.stop()
            self.gateway = None
        if self.scheduler is not None:
            self.scheduler.stop()
            self.scheduler = None
        if self.registry is not None:
            self.registry.stop()
            self.registry = None

    def __enter__(self) -> "FleetServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
